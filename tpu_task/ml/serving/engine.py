"""Continuous-batching serving engine: iteration-level scheduling over a
fixed slot array (Orca, Yu et al., OSDI 2022) + the paged KV pool.

The engine owns a fixed-width slot array and loops one scheduler iteration
at a time (:meth:`ServingEngine.step`): retire slots that finished last
step (their blocks return to the pool the same step), admit queued
requests into free slots (bucketed-length prefill — one compiled program
per bucket), then run ONE jitted decode step across all slots with
per-slot positions and per-slot sampling params. A short request admitted
behind a long one retires the moment ITS eos/length hits — no
head-of-line blocking on the longest generation, which is the whole
throughput argument (``bench.py serving`` measures it).

Admission takes a request when a slot is free and the pool holds its
prompt's blocks plus one spare; growth past that is lazy (a block at each
block boundary). If the pool is exhausted mid-decode the youngest running
request is preempted back to the queue head (recompute-style, vLLM's
fallback policy): its blocks free immediately and its token stream is
reproduced exactly on re-admission because sampling keys derive from the
request key alone (fold_in per token index), never from the schedule.

Host/device split: the scheduler (allocator, slot table, queues, timing)
is plain Python/numpy; the device sees only static-shape jitted programs
(prefill per bucket, one decode step, one sampler per logits shape) whose
inputs — tokens, positions, block tables, active mask, sampling params —
are tiny per-step arrays. ``TPU_TASK_CHECKIFY=1`` (debug mode) wraps every
program in ``jax.experimental.checkify`` and throws on the bounds guards
(`decoding.bounds_guard`) that are silent no-ops in production."""

from __future__ import annotations

import collections
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from tpu_task.ml.models import transformer
from tpu_task.ml.models.transformer import Params, TransformerConfig
from tpu_task.ml.parallel.sharding import (
    PartitionPlan,
    compile_step,
    device_put_tree,
)
from tpu_task.ml.serving.cache import (
    SCRATCH_BLOCK,
    BlockAllocator,
    ServingConfig,
    init_pools,
    kv_shard_bytes,
    paged_cache_bytes,
    pool_pspecs,
)
from tpu_task.ml.serving.model import (
    decode_and_sample,
    greedy_decode_step,
    paged_prefill,
    sample_tokens,
)

QUEUED, RUNNING, DONE = "queued", "running", "done"


@dataclass
class Request:
    """One generation request and its lifecycle record."""

    rid: int
    prompt: np.ndarray                   # (prompt_len,) int32
    max_new_tokens: int
    temperature: float = 0.0
    top_p: float = 1.0                   # 1.0 = nucleus filter off
    eos_token: Optional[int] = None
    key: Optional[jax.Array] = None      # per-request PRNG key
    status: str = QUEUED
    tokens: List[int] = field(default_factory=list)
    submit_t: float = 0.0
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    preemptions: int = 0

    @property
    def finished(self) -> bool:
        if len(self.tokens) >= self.max_new_tokens:
            return True
        return bool(self.tokens) and self.eos_token is not None \
            and self.tokens[-1] == self.eos_token


class ServingEngine:
    """Front end: :meth:`submit` → request id, :meth:`poll` → status/tokens,
    :meth:`step` → one scheduler iteration, :meth:`drain` → run to empty.

    ``mesh=`` turns on tensor-parallel serving: weights shard per the
    logical rules (heads/mlp/vocab over ``tp``), the paged KV pools shard
    their kv-head axis over ``tp`` (so per-device KV bytes divide by tp —
    a model whose KV pool exceeds one chip decodes across the mesh), and
    the scheduler is UNCHANGED: block tables, positions, and masks
    replicate, and paging stays along the token axis. Requires
    ``cfg.kv_heads % tp == 0``. Greedy token streams are schedule- and
    shard-identical to the single-chip engine on small configs (pinned in
    tier-1); logits agree to accumulation-order tolerance (docs/parity.md)."""

    def __init__(self, params: Params, cfg: TransformerConfig,
                 scfg: Optional[ServingConfig] = None,
                 rng: Optional[jax.Array] = None, mesh=None):
        self.cfg = cfg
        self.scfg = scfg = scfg or ServingConfig()
        self.mesh = mesh
        self.tp = 1
        pools = init_pools(cfg, scfg)
        if mesh is None:
            self.params = params
            self.pools = pools
        else:
            # Tensor-parallel serving: weights lay out per the SAME logical
            # rules training uses (param_pspecs), the paged pools shard
            # their kv-head axis over tp (pool_pspecs, regex registry), and
            # everything the host scheduler owns — tokens, positions, block
            # tables, active masks, sampling params — replicates. Paging is
            # along the token axis, so block accounting (allocator, tables,
            # scratch block) is IDENTICAL at every tp width.
            self.tp = int(dict(mesh.shape).get("tp", 1))
            if cfg.kv_heads % self.tp:
                raise ValueError(
                    f"kv_heads {cfg.kv_heads} not divisible by tp "
                    f"{self.tp} (mesh axes {tuple(mesh.axis_names)}): the "
                    "paged pools shard their kv-head axis over tp")
            self._param_specs = transformer.param_pspecs(cfg, mesh=mesh)
            self._pool_specs = pool_pspecs(pools, mesh)
            self.params = device_put_tree(params, self._param_specs, mesh)
            self.pools = device_put_tree(pools, self._pool_specs, mesh)
        self.allocator = BlockAllocator(scfg.n_blocks)
        self.debug = os.environ.get("TPU_TASK_CHECKIFY", "") == "1"

        n, m = scfg.slots, scfg.max_blocks_per_slot
        self._slots: List[Optional[Request]] = [None] * n
        self._admit_seq = [0] * n        # admission order, preemption victim pick
        self._admit_counter = 0
        self._tables = np.zeros((n, m), np.int32)
        self._positions = np.zeros((n,), np.int32)
        self._last_token = np.zeros((n,), np.int32)
        self._slot_keys = np.zeros((n, 2), np.uint32)
        self._queue: collections.deque = collections.deque()
        self._requests: Dict[int, Request] = {}
        self._next_rid = 0
        self._base_key = rng if rng is not None else jax.random.PRNGKey(0)
        self.steps = 0
        self.decode_steps = 0
        self.prefills = 0

        # Pools are DONATED: the engine owns them exclusively and replaces
        # its reference with the returned ones, so XLA updates the block
        # pool in place — without donation every step would copy the whole
        # pool, the one cost generate's in-scan cache carry never pays.
        # Every program compiles through the shared seam
        # (sharding.compile_step): single-device plans are plain jit, mesh
        # plans pin weight/pool shardings and keep the donation — the same
        # seam the train-step builders use.
        rep = PartitionSpec()

        def plan(arg_specs, donate):
            if mesh is None:
                return PartitionPlan(donate=donate)
            return PartitionPlan(
                mesh=mesh, in_specs=arg_specs,
                out_specs=(rep, self._pool_specs), donate=donate)

        p_specs = getattr(self, "_param_specs", None)
        k_specs = getattr(self, "_pool_specs", None)
        self._prefill_fn = self._wrap(compile_step(
            lambda params, tokens, length, table, pools: paged_prefill(
                params, cfg, tokens, length, table, pools),
            plan((p_specs, rep, rep, rep, k_specs), (4,))))
        # One fused program per decode iteration: forward + in-program key
        # fold + sampler — per-step dispatch overhead is the engine's whole
        # tax over generate's scan, so it is kept to a single call.
        self._decode_fn = self._wrap(compile_step(
            lambda params, tokens, positions, tables, active, temps, tops,
            keys, ngen, pools: decode_and_sample(
                params, cfg, tokens, positions, tables, active, temps,
                tops, keys, ngen, pools),
            plan((p_specs, rep, rep, rep, rep, rep, rep, rep, rep,
                  k_specs), (9,))))
        # Greedy fast path: when every active slot decodes at temperature 0
        # (the common serving default and the whole bench), the sampler
        # reduces to argmax — no sort/cumsum/categorical/key-fold in the
        # step program.
        self._decode_greedy_fn = self._wrap(compile_step(
            lambda params, tokens, positions, tables, active, pools:
            greedy_decode_step(params, cfg, tokens, positions, tables,
                               active, pools),
            plan((p_specs, rep, rep, rep, rep, k_specs), (5,))))
        self._prefill_sample_fn = self._wrap(jax.jit(
            lambda logits, temp, top, key, n: sample_tokens(
                logits, temp, top, jax.random.fold_in(key, n)[None])))

    def _wrap(self, fn):
        """Debug mode: functionalize the bounds guards and throw on them."""
        if not self.debug:
            return fn
        from jax.experimental import checkify

        checked = checkify.checkify(fn)

        def run(*args):
            err, out = checked(*args)
            err.throw()
            return out

        return run

    # -- front end -----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *, temperature: float = 0.0,
               top_p: Optional[float] = None,
               eos_token: Optional[int] = None) -> int:
        """Queue a generation request; returns its id. Same sampling
        contract as ``generate``: temperature 0 is greedy, ``top_p`` needs
        temperature > 0."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("prompt must hold at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if top_p is not None and not 0 < top_p <= 1:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_p is not None and temperature == 0:
            raise ValueError("top_p needs temperature > 0 (greedy ignores it)")
        self.scfg.bucket_for(len(prompt))  # must fit a prefill bucket
        total = len(prompt) + max_new_tokens
        if total > self.scfg.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds max_len {self.scfg.max_len}")
        if self.scfg.blocks_for(total) > self.scfg.n_blocks - 1:
            raise ValueError(
                f"request needs {self.scfg.blocks_for(total)} blocks but the "
                f"pool holds {self.scfg.n_blocks - 1}")
        rid = self._next_rid
        self._next_rid += 1
        req = Request(
            rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, top_p=1.0 if top_p is None else top_p,
            eos_token=eos_token, key=jax.random.fold_in(self._base_key, rid),
            submit_t=time.monotonic())
        self._requests[rid] = req
        self._queue.append(req)
        return rid

    def poll(self, rid: int) -> dict:
        req = self._requests[rid]
        return {"status": req.status, "tokens": list(req.tokens)}

    def request(self, rid: int) -> Request:
        """The full lifecycle record (timestamps, preemptions) — the bench
        computes TTFT/latency percentiles from these."""
        return self._requests[rid]

    def result(self, rid: int) -> List[int]:
        req = self._requests[rid]
        if req.status != DONE:
            raise RuntimeError(f"request {rid} is {req.status}, not done")
        return list(req.tokens)

    @property
    def n_active(self) -> int:
        return sum(r is not None for r in self._slots)

    @property
    def has_work(self) -> bool:
        return bool(self._queue) or self.n_active > 0

    def step(self) -> dict:
        """One scheduler iteration: admit → decode → retire. Returns what
        happened (request ids admitted/finished, active count)."""
        self.steps += 1
        admitted, finished = [], []
        self._admit(admitted, finished)
        if self.n_active:
            self._decode(finished)
        return {"admitted": admitted, "finished": finished,
                "active": self.n_active, "queued": len(self._queue)}

    def drain(self, max_steps: int = 100_000) -> Dict[int, List[int]]:
        """Step until queue and slots are empty; returns {rid: tokens} for
        every request ever submitted."""
        steps = 0
        while self.has_work:
            if steps >= max_steps:
                raise RuntimeError(f"drain exceeded {max_steps} steps")
            self.step()
            steps += 1
        return {rid: list(r.tokens) for rid, r in self._requests.items()}

    # -- scheduler internals -------------------------------------------------

    def _sample_one(self, req: Request, logits) -> int:
        tok = self._prefill_sample_fn(
            logits, jnp.asarray([req.temperature], jnp.float32),
            jnp.asarray([req.top_p], jnp.float32), req.key,
            jnp.int32(len(req.tokens)))
        return int(tok[0])

    def _admit(self, admitted: list, finished: list) -> None:
        while self._queue:
            slot = next(
                (i for i, r in enumerate(self._slots) if r is None), None)
            if slot is None:
                return
            req = self._queue[0]
            need = self.scfg.blocks_for(len(req.prompt))
            # Keep one spare so the running set can cross its next block
            # boundary without an instant preemption; an idle engine admits
            # with no spare (a solo request can always grow into the pool
            # its own submit-time validation reserved).
            if self.allocator.available < need + (1 if self.n_active else 0):
                return
            self._queue.popleft()
            blocks = self.allocator.alloc(need)
            bucket = self.scfg.bucket_for(len(req.prompt))
            table = np.zeros((self.scfg.max_blocks_per_slot,), np.int32)
            table[:need] = blocks
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :len(req.prompt)] = req.prompt
            logits, self.pools = self._prefill_fn(
                self.params, jnp.asarray(padded),
                jnp.int32(len(req.prompt)), jnp.asarray(table), self.pools)
            self.prefills += 1
            first = self._sample_one(req, logits)
            now = time.monotonic()
            req.status = RUNNING
            req.tokens.append(first)
            if req.first_token_t is None:
                req.first_token_t = now
            self._slots[slot] = req
            self._admit_counter += 1
            self._admit_seq[slot] = self._admit_counter
            self._slot_keys[slot] = np.asarray(req.key, np.uint32)
            self._tables[slot] = table
            self._positions[slot] = len(req.prompt)
            self._last_token[slot] = first
            admitted.append(req.rid)
            if req.finished:
                self._retire(slot)
                finished.append(req.rid)

    def _ensure_blocks(self) -> None:
        """Every active slot whose next write crosses into an unallocated
        block gets one — preempting the youngest running request (requeued
        at the head, restart-from-scratch recompute) when the pool is dry."""
        for slot in sorted(range(self.scfg.slots),
                           key=lambda i: self._admit_seq[i]):
            req = self._slots[slot]
            if req is None:
                continue
            block_i = int(self._positions[slot]) // self.scfg.block_size
            while self._tables[slot, block_i] == SCRATCH_BLOCK:
                got = self.allocator.alloc(1)
                if got is not None:
                    self._tables[slot, block_i] = got[0]
                    break
                victim = max(
                    (i for i, r in enumerate(self._slots) if r is not None),
                    key=lambda i: self._admit_seq[i])
                self._preempt(victim)
                if victim == slot:
                    break  # this slot itself was youngest — it is requeued
                if self.n_active <= 1 and self.allocator.available == 0:
                    raise RuntimeError(
                        "KV pool too small for a single request — raise "
                        "n_blocks")

    def _preempt(self, slot: int) -> None:
        req = self._slots[slot]
        req.preemptions += 1
        req.status = QUEUED
        req.tokens.clear()   # recompute policy: the keyed sampling stream
        req.first_token_t = None  # reproduces the same tokens on
        self._release(slot)       # re-admission; TTFT restarts honestly
        self._queue.appendleft(req)

    def _decode(self, finished: list) -> None:
        self._ensure_blocks()
        active = np.array([r is not None for r in self._slots])
        if not active.any():
            return
        if all(r is None or r.temperature == 0 for r in self._slots):
            toks, self.pools = self._decode_greedy_fn(
                self.params, jnp.asarray(self._last_token),
                jnp.asarray(np.where(active, self._positions, 0)),
                jnp.asarray(self._tables), jnp.asarray(active), self.pools)
        else:
            temps = np.array(
                [r.temperature if r else 0.0 for r in self._slots],
                np.float32)
            tops = np.array([r.top_p if r else 1.0 for r in self._slots],
                            np.float32)
            ngen = np.array([len(r.tokens) if r else 0 for r in self._slots],
                            np.int32)
            toks, self.pools = self._decode_fn(
                self.params, jnp.asarray(self._last_token),
                jnp.asarray(np.where(active, self._positions, 0)),
                jnp.asarray(self._tables), jnp.asarray(active),
                jnp.asarray(temps), jnp.asarray(tops),
                jnp.asarray(self._slot_keys), jnp.asarray(ngen), self.pools)
        self.decode_steps += 1
        toks = np.asarray(toks)
        now = time.monotonic()
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            tok = int(toks[slot])
            req.tokens.append(tok)
            if req.first_token_t is None:
                req.first_token_t = now
            self._positions[slot] += 1
            self._last_token[slot] = tok
            if req.finished:
                self._retire(slot)
                finished.append(req.rid)

    def _release(self, slot: int) -> None:
        """Free the slot's blocks and clear its row — same step it ends."""
        live = self._tables[slot][self._tables[slot] != SCRATCH_BLOCK]
        self.allocator.free(live.tolist())
        self._tables[slot] = 0
        self._positions[slot] = 0
        self._last_token[slot] = 0
        self._slots[slot] = None

    def _retire(self, slot: int) -> None:
        req = self._slots[slot]
        req.status = DONE
        req.finish_t = time.monotonic()
        self._release(slot)

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        """Scheduler counters + the KV cost model (docs/parity.md)."""
        from tpu_task.ml.serving.cache import dense_cache_bytes

        return {
            "steps": self.steps,
            "decode_steps": self.decode_steps,
            "prefills": self.prefills,
            "tp": self.tp,
            "kv_blocks_high_water": self.allocator.high_water,
            "kv_high_water_bytes": paged_cache_bytes(
                self.cfg, self.scfg, self.allocator.high_water),
            "kv_pool_bytes": paged_cache_bytes(
                self.cfg, self.scfg, self.scfg.n_blocks),
            "kv_pool_bytes_per_shard": kv_shard_bytes(
                self.cfg, self.scfg, self.scfg.n_blocks, self.tp),
            "kv_dense_worst_case_bytes": dense_cache_bytes(
                self.cfg, self.scfg.slots, self.scfg.max_len),
        }
