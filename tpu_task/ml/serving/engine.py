"""Continuous-batching serving engine: iteration-level scheduling over a
fixed slot array (Orca, Yu et al., OSDI 2022) + the paged KV pool, grown
for production-shaped traffic: shared-prefix KV caching, chunked prefill,
and speculative decoding (ROADMAP item 2).

The engine owns a fixed-width slot array and loops one scheduler iteration
at a time (:meth:`ServingEngine.step`): retire slots that finished last
step (their blocks return to the pool the same step), admit queued
requests into free slots, then run ONE jitted step across all slots.
A short request admitted behind a long one retires the moment ITS
eos/length hits — no head-of-line blocking on the longest generation.

Three production pieces ride the paged substrate (all host-side scheduling
over the same static-shape programs):

- **Prefix cache** (``ServingConfig.prefix_cache``): full KV blocks are
  content-hashed (chained over the token ids they cover) and registered
  when a slot releases them; a new admission maps its longest cached
  prefix to the existing physical blocks (refcounted) and prefills only
  the O(new tokens) tail. A slot that must write into a shared block gets
  a private copy first (copy-on-write); refcount-0 cached blocks are
  evicted LRU only when the free list runs dry, so the cache never causes
  a recompute preemption an uncached engine would not have had.
- **Chunked prefill** (``ServingConfig.prefill="chunked"``, the default):
  prompt ingestion folds into the fused step — each iteration ingests at
  most ``chunk_tokens`` prompt positions of ONE admitting slot while every
  running slot still decodes its token (Sarathi-style), so a long
  admission bounds other slots' inter-token stall by one chunk, not one
  prompt. ``"bucketed"`` keeps the legacy PR 5 whole-prompt-per-program
  path as the comparison baseline; both produce bit-identical greedy
  streams (docs/parity.md).
- **Speculative decoding** (``ServingConfig.spec_k`` + a draft model):
  a small draft proposes up to ``spec_k`` tokens per slot (greedy, its own
  cache in a statically-tabled paged pool), ONE fused target step scores
  all ``spec_k + 1`` positions (the chunked multi-token step reused), and
  acceptance commits in place — greedy output is bit-identical to
  non-speculative decoding (longest agreeing prefix + bonus token);
  sampled requests go through rejection sampling against the SAME
  temper-then-top_p-filtered target distribution (distribution-exact).

Admission takes a request when a slot is free and the pool holds its
(uncached) prompt blocks plus one spare; growth past that is lazy. If the
pool is exhausted mid-decode the engine first evicts refcount-0 cached
blocks, then preempts the youngest running request back to the queue head
(recompute-style): its blocks free immediately and — at model-dtype pools
— its token stream is reproduced exactly on re-admission because sampling
keys derive from the request key alone (fold_in per token index), never
from the schedule. int8 pools demote replay to the same tolerance class
as everything else quantized: the recompute requantizes whole blocks in
one pass where the original stream appended incrementally, so the
rebuilt codes (and a near-tie argmax) can differ (docs/parity.md).

Raw decode speed (ROADMAP items 3 + 4) rides three static knobs resolved
at construction: ``ServingConfig.decode_impl`` selects the paged
attention inside every fused step — the XLA gather+dense reference, the
Pallas block-table-walking kernel (``ml.ops.paged_attention``) that
streams KV straight from the physical pools, or its DMA-pipelined
variant that double-buffers the block copies; ``kv_dtype`` stores the
pools as int8 or fp8-e4m3 codes + per-(block, kv-head) scales (~2× the
blocks in the same HBM), with writes requantizing the touched blocks per
step (host-computed ``_quant_layout``) and attention dequantizing on
read; and ``micro_k`` fuses K sequential decode iterations into ONE
jitted program (in-program eos/length retirement), so steady-state
decode is one dispatch per K tokens — streams bit-identical (greedy) /
key-identical (sampled) to K=1 (docs/parity.md "Dispatch
amortization"). ``stats()["decode_impl"]`` records which path actually
compiled; ``stats()["micro_k"]`` the configured amortization.

Host/device split: the scheduler (allocator, prefix cache, slot table,
queues, timing) is plain Python/numpy; the device sees only static-shape
jitted programs whose inputs are tiny per-step arrays.
``TPU_TASK_CHECKIFY=1`` (debug mode) wraps every program in
``jax.experimental.checkify`` and throws on the bounds guards."""

from __future__ import annotations

import collections
import os
import time
import warnings
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from tpu_task.ml.models import transformer
from tpu_task.ml.models.transformer import Params, TransformerConfig
from tpu_task.ml.ops import paged_attention as pa
from tpu_task.obs import Obs
from tpu_task.obs.goodput import GoodputMeter
from tpu_task.obs.sla import DEFAULT_CLASS, class_rank
from tpu_task.obs.trace import Span, TraceContext
from tpu_task.ml.parallel.sharding import (
    PartitionPlan,
    compile_step,
    device_put_tree,
    mesh_axis_size,
)
from tpu_task.ml.serving.cache import (
    QUANT_DTYPES,
    SCRATCH_BLOCK,
    BlockAllocator,
    PrefixCache,
    ServingConfig,
    chain_block_hashes,
    copy_block,
    fp8_supported,
    init_pools,
    kv_shard_bytes,
    kv_token_bytes,
    paged_cache_bytes,
    pool_pspecs,
    split_block_bytes,
    stage_block_arrays,
    staged_block_to_bytes,
    write_blocks,
)
from tpu_task.ml.serving.lora import (
    adapter_fingerprint,
    adapter_payload,
    init_adapter_pool,
    pack_adapter,
    split_adapter_payload,
)
from tpu_task.ml.serving.offload import HostKvTier
from tpu_task.ml.serving.model import (
    chunk_carry_greedy,
    chunk_carry_sample,
    chunked_step_greedy,
    decode_and_sample,
    greedy_decode_step,
    micro_carry_greedy,
    micro_carry_sample,
    micro_decode_greedy,
    micro_decode_sample,
    paged_prefill,
    sample_tokens,
    serving_moe_fn,
    spec_score_greedy,
    spec_score_probs,
)

QUEUED, RUNNING, DONE = "queued", "running", "done"


def _kv_itemsize(scfg: ServingConfig, cfg) -> int:
    """Bytes per KV POOL element — what sets the kernel's sublane tile.
    Every quantized dtype (int8, fp8 e4m3, packed int4) is a 1-byte pool
    element; int4's 2× density comes from the HALVED trailing dim."""
    return (1 if scfg.kv_dtype in QUANT_DTYPES
            else jnp.dtype(cfg.dtype).itemsize)


def resolve_decode_impl(scfg: ServingConfig, cfg, tp: int = 1) -> str:
    """Pick the paged-attention implementation the fused steps compile
    with (ROADMAP item 3). ``"xla"``/``"interpret"`` pass through;
    ``"pallas"`` validates the backend and the pool geometry against the
    kernel's tile constraints AND scalar-prefetch SMEM budget, raising an
    ACTIONABLE error (never a Pallas trace failure mid-decode);
    ``"auto"`` selects the compiled kernel on a TPU backend when the
    geometry satisfies the constraints, falling back to the XLA gather
    path with a one-time warning when it does not, and picks XLA
    everywhere else. ``tp``: kv-head shard width — per-shard SMEM holds
    only the local heads' scale sidecars."""
    want = scfg.decode_impl
    if want in ("xla", "interpret", "interpret_pipelined"):
        return want
    viol = pa.kernel_constraint_violation(
        scfg.block_size, cfg.d_head, _kv_itemsize(scfg, cfg),
        n_blocks=scfg.n_blocks, kv_heads=cfg.kv_heads // max(1, tp),
        slots=scfg.slots + (scfg.chunk_tokens
                            if scfg.prefill == "chunked" else 0),
        max_blocks=scfg.max_blocks_per_slot,
        q_width=scfg.spec_k + 1,
        quantized=scfg.kv_dtype in QUANT_DTYPES,
        packed=scfg.kv_dtype == "int4")
    if want in ("pallas", "pipelined"):
        if not pa.use_pallas_paged():
            raise ValueError(
                f"decode_impl={want!r} needs a TPU backend for the "
                "compiled kernel; use decode_impl='interpret' (or "
                "'interpret_pipelined') to emulate it elsewhere, or "
                "'xla'")
        if viol:
            raise ValueError(
                f"decode_impl={want!r} rejected: {viol} — adjust the "
                "ServingConfig/model geometry or use decode_impl='xla'")
        return want
    if pa.use_pallas_paged():
        if viol:
            warnings.warn(
                f"paged-decode kernel unavailable for this pool geometry "
                f"({viol}); serving falls back to the XLA gather path — "
                "stats()['decode_impl'] records which path ran",
                RuntimeWarning)
            return "xla"
        return "pallas"
    return "xla"

#: Salt folded into a request's key before deriving per-position uniforms
#: for speculative rejection sampling — keeps the spec stream disjoint from
#: the ``fold_in(key, token_index)`` stream the plain sampler consumes.
_SPEC_SALT = 0x5BEC


def _check_key(key) -> jax.Array:
    """Normalize a caller-supplied per-request PRNG key to the raw
    two-word uint32 form the slot table stores — raising HERE (the
    validated submission boundary), not later inside a fused step when
    the malformed key hits the slot array."""
    try:
        raw = np.asarray(key, np.uint32).reshape(-1)
    except (TypeError, ValueError) as error:
        raise ValueError(f"request key is not uint32 words: {error}")
    if raw.shape != (2,):
        raise ValueError(
            f"request key must be 2 uint32 words, got shape {raw.shape}")
    return jnp.asarray(raw)


class DrainTimeout(RuntimeError):
    """:meth:`ServingEngine.drain` ran out of steps with work in flight.
    Carries the ids of every request not yet done so callers can requeue
    or report them instead of silently losing partial results."""

    def __init__(self, max_steps: int, unfinished: List[int]):
        self.max_steps = max_steps
        self.unfinished = sorted(unfinished)
        super().__init__(
            f"drain exceeded {max_steps} steps with {len(self.unfinished)} "
            f"unfinished request(s): {self.unfinished}")


@dataclass
class Request:
    """One generation request and its lifecycle record."""

    rid: int
    prompt: np.ndarray                   # (prompt_len,) int32
    max_new_tokens: int
    temperature: float = 0.0
    top_p: float = 1.0                   # 1.0 = nucleus filter off
    eos_token: Optional[int] = None
    key: Optional[jax.Array] = None      # per-request PRNG key
    status: str = QUEUED
    tokens: List[int] = field(default_factory=list)
    submit_t: float = 0.0
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    preemptions: int = 0
    #: tokens that existed when this request entered THIS engine — nonzero
    #: only for :meth:`ServingEngine.resume_inflight` imports, where the
    #: resumed prefix is context to re-ingest, never to regenerate. A
    #: recompute preemption rolls ``tokens`` back to this floor, not to 0.
    resume_from: int = 0
    #: incoming trace context (the router's dispatch span, off the HTTP
    #: header) — the parent every engine-side span of this request links
    #: to. None when tracing is off or the caller sent no context.
    trace: Optional[TraceContext] = None
    #: SLA metadata (the router's SLA header, landed): protection class
    #: and absolute deadline on THIS engine's monotonic clock (converted
    #: from remaining-ms at submit; None = no deadline). Consumed by
    #: slack-ordered admission and victim selection — NEVER by sampling:
    #: tokens are keyed by (key, index), so SLA-driven reordering cannot
    #: change a stream's values, only when/whether it runs.
    slo_class: str = "standard"
    deadline: Optional[float] = None
    #: LoRA adapter this stream decodes under (None = the base model —
    #: its slot rides the all-zero scratch block, an exact no-op). Set
    #: at submit/resume, validated against the registry, round-tripped
    #: through export/resume records.
    adapter_id: Optional[str] = None
    #: Param generation this stream is PINNED to: assigned at submit
    #: time, never changed — a weight roll (adopt_params) moves new
    #: admissions to the new generation while this stream keeps
    #: decoding under the one it started on (docs/parity.md
    #: "Multi-model tenancy").
    generation: int = 0

    @property
    def finished(self) -> bool:
        if len(self.tokens) >= self.max_new_tokens:
            return True
        return bool(self.tokens) and self.eos_token is not None \
            and self.tokens[-1] == self.eos_token


class ServingEngine:
    """Front end: :meth:`submit` → request id, :meth:`poll` → status/tokens,
    :meth:`step` → one scheduler iteration, :meth:`drain` → run to empty.

    ``mesh=`` turns on multi-chip serving: a tp axis shards weights and
    the paged pools' kv-head axis exactly as in PR 6; an ``ep`` axis
    places MoE expert weights one group per shard (the SAME logical
    rules training uses) and routes tokens through the
    ``moe.apply_sharded`` all_to_all dispatch inside every fused step —
    the scheduler is unchanged at any tp×ep width. ``draft_params``/
    ``draft_cfg`` + ``scfg.spec_k > 0`` turn on speculative decoding;
    the draft pool shards with the same rules as the target's."""

    def __init__(self, params: Params, cfg: TransformerConfig,
                 scfg: Optional[ServingConfig] = None,
                 rng: Optional[jax.Array] = None, mesh=None,
                 draft_params: Optional[Params] = None,
                 draft_cfg: Optional[TransformerConfig] = None,
                 obs: Optional[Obs] = None, kv_fleet=None,
                 param_loader=None):
        self.cfg = cfg
        self.scfg = scfg = scfg or ServingConfig()
        self.mesh = mesh
        self.tp = 1
        self.ep = 1
        pools = init_pools(cfg, scfg)
        if mesh is None:
            self._gen_params: Dict[int, Params] = {0: params}
            self.pools = pools
        else:
            # Multi-chip serving: weights lay out per the SAME logical
            # rules training uses (param_pspecs — MoE expert weights shard
            # one group per ep shard, their hidden dim over tp), the paged
            # pools shard their kv-head axis over tp (pool_pspecs, regex
            # registry), and everything the host scheduler owns — tokens,
            # positions, block tables, active masks, sampling params —
            # replicates. Paging is along the token axis, so block
            # accounting (allocator, tables, scratch block, prefix cache)
            # is IDENTICAL at every tp×ep width.
            self.tp = mesh_axis_size(mesh, "tp")
            self.ep = mesh_axis_size(mesh, "ep")
            if cfg.kv_heads % self.tp:
                raise ValueError(
                    f"kv_heads {cfg.kv_heads} not divisible by tp "
                    f"{self.tp} (mesh axes {tuple(mesh.axis_names)}): the "
                    "paged pools shard their kv-head axis over tp")
            if self.ep > 1 and cfg.moe_every <= 0:
                raise ValueError(
                    f"mesh carries ep={self.ep} but the model has no MoE "
                    "layers (moe_every=0): drop the ep axis or serve an "
                    "MoE config")
            # Resolve the ep dispatch BEFORE any placement: an
            # indivisible expert count must fail with ITS error, not a
            # device_put sharding failure.
            serving_moe_fn(cfg, mesh)
            self._param_specs = transformer.param_pspecs(cfg, mesh=mesh)
            self._pool_specs = pool_pspecs(pools, mesh)
            self._gen_params = {
                0: device_put_tree(params, self._param_specs, mesh)}
            self.pools = device_put_tree(pools, self._pool_specs, mesh)
        #: The expert-parallel MoE dispatch threading through every fused
        #: step (None = the dense-dispatch reference — single chip, or a
        #: mesh without an ep axis). Resolved ONCE here; validates
        #: n_experts % ep at construction, never mid-decode.
        self._moe_fn = serving_moe_fn(cfg, mesh)
        self.allocator = BlockAllocator(scfg.n_blocks)
        self._pcache = (PrefixCache(self.allocator, scfg.block_size)
                        if scfg.prefix_cache else None)
        self.debug = os.environ.get("TPU_TASK_CHECKIFY", "") == "1"
        #: Which paged attention the fused steps actually compiled with —
        #: resolved ONCE here (auto-fallback warns), recorded in stats()
        #: so a silent fallback to the gather path is visible in benches
        #: and soaks.
        self.decode_impl = resolve_decode_impl(scfg, cfg, tp=self.tp)
        #: The DRAFT programs' impl (None without speculative decoding) —
        #: may differ from decode_impl when the draft's geometry forces
        #: the XLA fallback; recorded in stats() like the target's.
        self.draft_decode_impl: Optional[str] = None
        self._quantized = scfg.kv_dtype in QUANT_DTYPES
        if scfg.kv_dtype == "fp8" and not fp8_supported():
            raise ValueError(
                "kv_dtype='fp8' needs float8_e4m3fn support in this jax "
                "build/backend (cache.fp8_supported() is False) — use "
                "kv_dtype='int8' for the same byte density or None for "
                "model-dtype pools")

        # Fleet KV plane (ROADMAP item 2): an attached client (duck-typed
        # — serve/kvfleet.py defines the real one; ml.serving never
        # imports it) lets admission import content-hash-matched blocks
        # other replicas published instead of prefilling them, and lets
        # this engine publish its own hot cached blocks
        # (export_cached_blocks). Single-chip only: an imported payload
        # is one unsharded block's bytes.
        self._fleet = kv_fleet
        if kv_fleet is not None:
            if mesh is not None:
                raise ValueError(
                    "kv_fleet is single-chip for now: block payloads are "
                    "unsharded (attach it to a mesh=None engine)")
            if not scfg.prefix_cache:
                raise ValueError(
                    "kv_fleet needs prefix_cache=True — imported blocks "
                    "are adopted INTO the local prefix cache")
            kv_fleet.bind(cfg, scfg)
        self.fleet_hit_blocks = 0
        self.fleet_miss_blocks = 0
        self.fleet_import_requests = 0
        self.fleet_prefetch_blocks = 0
        self._h_kv_import = None

        # Host-RAM offload tier (ROADMAP item 3): the middle rung of the
        # HBM → host RAM → fleet bucket hierarchy. Cold retained ref-0
        # cached blocks (the prefix cache's LRU tail — including every
        # idle session's blocks, which _release parked there) demote
        # into it on the overlap seam: staged non-blocking while a
        # program is in flight (_demote_pass), forced to bytes at the
        # consume edge where the host is blocked anyway
        # (_finalize_demotions). Admission imports and prefetch hints
        # consult it BEFORE the fleet bucket; entries past the budget
        # spill to the bucket through the attached fleet client.
        self._host_tier: Optional[HostKvTier] = None
        if scfg.host_offload_blocks > 0:
            if mesh is not None:
                raise ValueError(
                    "host_offload_blocks is single-chip for now: tier "
                    "payloads are unsharded block bytes (attach the "
                    "host tier to a mesh=None engine)")
            spill = (kv_fleet.ship_bytes
                     if kv_fleet is not None
                     and hasattr(kv_fleet, "ship_bytes") else None)
            self._host_tier = HostKvTier(
                scfg.host_offload_blocks, spill=spill)
        self.demoted_blocks = 0
        self.promoted_blocks = 0
        #: Demotions staged against an in-flight program, as (hash,
        #: block, staged device slices): the bytes force one consume
        #: edge later, after the program the reads enqueued behind has
        #: completed — never on the dispatch path.
        self._pending_demotions: List[Tuple[bytes, int, List]] = []

        # Paged LoRA adapters (ISSUE 19): multi-tenant fine-tunes page
        # through a second BlockAllocator over a device pool of
        # (2, rank, d_model) per-layer blocks — registered content-
        # addressed, gathered per slot inside every fused step, and
        # LRU-evicted/reloaded through the kvfleet plane like demoted
        # KV. Single-chip for now (like overlap/kv_fleet): the pool is
        # unsharded and the per-slot gather replicates.
        self._lora_on = scfg.lora_rank > 0
        if self._lora_on and mesh is not None:
            raise ValueError(
                "lora_rank > 0 is single-chip for now: the adapter pool "
                "is unsharded (attach adapters to a mesh=None engine)")
        #: adapter_id -> {hash, scale, payload (host np copy or None),
        #: blocks (resident pool blocks or None), last_use, refs
        #: (slotted requests decoding under it — the eviction pin)}.
        self._adapters: Dict[str, dict] = {}
        self._lora_alloc: Optional[BlockAllocator] = None
        self._lora_pool = None
        if self._lora_on:
            self._lora_alloc = BlockAllocator(scfg.n_adapter_blocks)
            self._lora_pool = init_adapter_pool(
                scfg.n_adapter_blocks, scfg.lora_rank, cfg.d_model,
                cfg.dtype)
        #: Per-slot gather tables the fused programs consume: row i of
        #: _slot_lora_blocks is slot i's per-layer adapter block (0 =
        #: scratch = exact no-op), _slot_lora_scale its LoRA scale.
        self._slot_lora_blocks = np.zeros(
            (scfg.slots, cfg.n_layers), np.int32)
        self._slot_lora_scale = np.zeros((scfg.slots,), np.float32)
        self.adapters_registered = 0
        self.adapter_loads = 0
        self.adapter_evictions = 0

        # Live weight hot-swap (ISSUE 19): params are double-buffered
        # by GENERATION — adopt_params installs a new pytree under the
        # next generation, new admissions bind to it, every in-flight
        # stream keeps decoding under the generation it started on
        # (step() partitions dispatches by generation while slots span
        # a roll), and an old buffer frees when its last stream
        # retires. param_loader(generation) -> params (set by the
        # replica) restores an already-freed generation so a resumed
        # stream can pin it instead of silently decoding under new
        # weights.
        self.generation = 0
        self._gen_streams: Dict[int, int] = {}
        self._gen_filter: Optional[int] = None
        self.param_swaps = 0
        self.param_loader = param_loader

        # Asynchronous engine loop (ROADMAP item 4, the overlap PR): the
        # host sweep of micro-step N runs while the device executes
        # micro-step N+1 — see _step_overlapped for the loop contract.
        # Single-chip only for now: carry programs pack chunk rows with
        # static slices, and the jax 0.4.x CPU SPMD concatenate gotcha
        # (docs/parity.md) is moot when no shard_map is in the path.
        self._overlap = scfg.overlap
        if self._overlap and mesh is not None:
            raise ValueError(
                "overlap=True is single-chip for now: run the overlapped "
                "loop on a mesh=None engine (the sharded gangs keep the "
                "synchronous loop)")
        #: The dispatched-but-unswept program's record (None between
        #: drains): device token futures + the host-side plan the sweep
        #: replays against. Exactly ONE program is ever in flight.
        self._inflight: Optional[dict] = None
        #: Device-resident (tok, pos, alive, emitted) threaded from
        #: program to program — None means "rebuild from the host
        #: mirrors at the next dispatch" (engine start, or after flush).
        self._carry = None
        #: Worst-case per-slot device position/emitted count after every
        #: dispatched program completes — what block reservation and
        #: planning read while the mirrors lag one program behind.
        self._planned_pos = np.zeros((scfg.slots,), np.int32)
        self._planned_emitted = np.zeros((scfg.slots,), np.int32)
        #: Retirements swept outside step() (a flush) — reported in the
        #: NEXT step's ``finished`` list rather than dropped.
        self._pending_finished: List[int] = []
        self.overlap_flushes = 0

        # Speculative decoding: validate the draft triple together. The
        # draft rides the SAME partition rules as the target (PR 8's
        # "spec decode is single-chip" note closes here): draft weights
        # through param_pspecs, the draft pool's kv-head axis over tp.
        self._spec_on = scfg.spec_k > 0
        #: Brownout knob (the degrade ladder's no-spec rung): False caps
        #: the draft width at zero INSIDE the spec step — every admitted
        #: row still scores through the spec program's position-keyed
        #: streams (width-1 valid), so toggling it mid-stream cannot
        #: change token values, only skip the draft forward passes.
        self.spec_enabled = True
        if self._spec_on and (draft_params is None or draft_cfg is None):
            raise ValueError(
                "spec_k > 0 needs draft_params and draft_cfg")
        if self._spec_on and mesh is not None \
                and draft_cfg.kv_heads % self.tp:
            raise ValueError(
                f"draft kv_heads {draft_cfg.kv_heads} not divisible by tp "
                f"{self.tp}: the draft pool shards its kv-head axis with "
                "the same rules as the target's")
        if draft_cfg is not None and draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft_cfg.vocab_size} != target vocab "
                f"{cfg.vocab_size}")
        self.draft_cfg = draft_cfg
        self.draft_params = draft_params
        if self._spec_on and mesh is not None:
            self._draft_param_specs = transformer.param_pspecs(
                draft_cfg, mesh=mesh)
            self.draft_params = device_put_tree(
                draft_params, self._draft_param_specs, mesh)

        n, m = scfg.slots, scfg.max_blocks_per_slot
        self._slots: List[Optional[Request]] = [None] * n
        self._admit_seq = [0] * n        # admission order, preemption victim pick
        self._admit_counter = 0
        self._tables = np.zeros((n, m), np.int32)
        self._positions = np.zeros((n,), np.int32)
        # Prefill target per slot: the CONTEXT length (prompt + any resumed
        # tokens) captured at admission — a slot is prefilling while its
        # position sits below it. Static per admission on purpose: the
        # context keeps growing after prefill, the target must not.
        self._prefill_target = np.zeros((n,), np.int32)
        self._last_token = np.zeros((n,), np.int32)
        self._slot_keys = np.zeros((n, 2), np.uint32)
        self._queue: collections.deque = collections.deque()
        self._requests: Dict[int, Request] = {}
        self._next_rid = 0
        self._base_key = rng if rng is not None else jax.random.PRNGKey(0)
        self.steps = 0
        self.decode_steps = 0
        self.micro_steps = 0             # K-wide fused micro dispatches
        self.prefills = 0
        self.prefill_chunks = 0
        self.chunk_steps = 0
        self.preemption_count = 0
        self.cow_copies = 0
        self.prefix_hit_blocks = 0
        self.prefix_miss_blocks = 0
        self.prefix_hit_requests = 0
        self.prefix_tokens_saved = 0
        self.spec_rounds = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.quantized_block_writes = 0
        self.max_quant_error = 0.0       # debug mode only (readback cost)

        # Observability (the PR 11 plane). obs=None is the ZERO-OVERHEAD
        # path: every recording site below guards on `self._obs is not
        # None` and nothing else runs — no timestamps, no spans, no
        # histogram bumps. With obs on, everything recorded is HOST-side
        # at dispatch boundaries (never inside a traced program): one
        # perf_counter pair per step, one span per request phase
        # (queue → prefill → decode), and the latency histograms the SLA
        # plane needs (step wall, TTFT, inter-token).
        self._obs = obs
        self._phase_spans: Dict[int, Span] = {}
        #: Goodput/MFU/dispatch accounting (PR 12) — exists only when obs
        #: does (the obs=None zero-overhead contract is one guard for
        #: both): splits step wall into in-program vs host-gap time,
        #: discounts wasted token-work into a goodput ratio, and runs the
        #: static FLOP cost model into an MFU gauge, all on the registry.
        self._goodput: Optional[GoodputMeter] = None
        if obs is not None:
            metrics = obs.metrics
            self._goodput = GoodputMeter(cfg, metrics)
            self._h_step = metrics.histogram("engine.step_s")
            self._h_ttft = metrics.histogram("engine.ttft_s")
            self._h_intertok = metrics.histogram("engine.intertoken_s")
            self._h_e2e = metrics.histogram("engine.e2e_s")
            # Existing plain counters join the one export path lazily —
            # mutation sites (and bench's resets) unchanged. Monotonic
            # totals register as counters (they SUM in the fleet merge);
            # instantaneous values as gauges (last-write-wins).
            for stat in ("steps", "decode_steps", "micro_steps",
                         "chunk_steps", "prefills",
                         "prefill_chunks", "preemption_count", "cow_copies",
                         "prefix_hit_requests", "prefix_tokens_saved",
                         "spec_rounds", "spec_accepted"):
                metrics.counter_fn(f"engine.{stat}",
                                   lambda self=self, stat=stat:
                                   float(getattr(self, stat)))
            for stat in ("n_active", "queue_depth"):
                metrics.gauge_fn(f"engine.{stat}",
                                 lambda self=self, stat=stat:
                                 float(getattr(self, stat)))
            # The configured amortization factor next to the measured
            # goodput.dispatches_per_token — the pair `obs watch` and the
            # replica /stats surface (configured K vs what actually ran).
            metrics.gauge_fn("engine.micro_k",
                             lambda scfg=scfg: float(scfg.micro_k))
            # Multi-tenant serving (ISSUE 19): the param-generation roll
            # and adapter residency, on the one registry so replica
            # /stats, /metrics, and `obs watch` all see a mid-roll
            # replica and its tenant density.
            metrics.gauge_fn("engine.param_generation",
                             lambda self=self: float(self.generation))
            metrics.counter_fn("engine.param_swaps",
                               lambda self=self: float(self.param_swaps))
            metrics.gauge_fn("engine.stale_generation_streams",
                             lambda self=self:
                             float(self.stale_generation_streams))
            if self._lora_on:
                for stat, name in (("adapters_registered", "registered"),
                                   ("adapter_loads", "loads"),
                                   ("adapter_evictions", "evictions")):
                    metrics.counter_fn(f"adapters.{name}",
                                       lambda self=self, stat=stat:
                                       float(getattr(self, stat)))
                metrics.gauge_fn("adapters.resident",
                                 lambda self=self: float(sum(
                                     1 for a in self._adapters.values()
                                     if a["blocks"] is not None)))
                metrics.gauge_fn("adapters.pool_high_water",
                                 lambda self=self:
                                 float(self._lora_alloc.high_water))
            if kv_fleet is not None:
                # The fleet-KV counters the obs satellite names: block
                # hit/miss at admission, bytes shipped out by the
                # publisher, bytes pulled in by the importer, and the
                # per-admission import latency histogram. All flow to
                # replica /stats, /metrics Prometheus text, and the
                # `obs watch` KV line through the one registry.
                self._h_kv_import = metrics.histogram("kvfleet.import_s")
                for stat in ("fleet_hit_blocks", "fleet_miss_blocks",
                             "fleet_import_requests",
                             "fleet_prefetch_blocks"):
                    name = stat.replace("fleet_", "")
                    metrics.counter_fn(f"kvfleet.{name}",
                                       lambda self=self, stat=stat:
                                       float(getattr(self, stat)))
                for stat in ("bytes_shipped", "bytes_fetched",
                             "published_blocks"):
                    metrics.counter_fn(f"kvfleet.{stat}",
                                       lambda kv_fleet=kv_fleet, stat=stat:
                                       float(getattr(kv_fleet, stat, 0)))
            if self._host_tier is not None:
                # The tiered-KV counters (ROADMAP item 3): HBM↔host
                # migration traffic plus the host tier's own hit/spill
                # tail — beside kvfleet.* on the one registry, so
                # replica /stats, /metrics, and `obs watch` see the
                # whole hierarchy through one export path.
                tier = self._host_tier
                for stat in ("demoted_blocks", "promoted_blocks"):
                    metrics.counter_fn(f"tier.{stat}",
                                       lambda self=self, stat=stat:
                                       float(getattr(self, stat)))
                for stat in ("hits", "misses", "spilled_blocks",
                             "dropped_blocks"):
                    metrics.counter_fn(f"tier.host_{stat}",
                                       lambda tier=tier, stat=stat:
                                       float(getattr(tier, stat)))
                metrics.gauge_fn("tier.host_resident_blocks",
                                 lambda tier=tier: float(len(tier)))

        # Draft-model state: its "dense" cache is a paged pool with a
        # STATIC identity block layout — slot s owns blocks
        # [1 + s·m, 1 + (s+1)·m), never allocated or freed — so every
        # draft pass reuses the battle-tested paged programs unchanged.
        if self._spec_on:
            d_shape = (n * m + 1, scfg.block_size, draft_cfg.kv_heads,
                       draft_cfg.d_head)
            self._draft_pools = [
                {"k": jnp.zeros(d_shape, draft_cfg.dtype),
                 "v": jnp.zeros(d_shape, draft_cfg.dtype)}
                for _ in range(draft_cfg.n_layers)]
            if mesh is not None:
                # The draft pool shards exactly like the target's: the
                # kv-head axis over tp (the one SERVING_POOL_RULES
                # registry), tables/positions replicated.
                self._draft_pool_specs = pool_pspecs(
                    self._draft_pools, mesh)
                self._draft_pools = device_put_tree(
                    self._draft_pools, self._draft_pool_specs, mesh)
            self._draft_tables = jnp.asarray(
                1 + np.arange(n * m, dtype=np.int32).reshape(n, m))
        self._draft_pos = np.zeros((n,), np.int32)

        # Pools are DONATED: the engine owns them exclusively and replaces
        # its reference with the returned ones, so XLA updates the block
        # pool in place — without donation every step would copy the whole
        # pool, the one cost generate's in-scan cache carry never pays.
        # Every program compiles through the shared seam
        # (sharding.compile_step): single-device plans are plain jit, mesh
        # plans pin weight/pool shardings and keep the donation — the same
        # seam the train-step builders use.
        rep = PartitionSpec()
        impl = self.decode_impl
        quant = self._quantized
        mfn = self._moe_fn   # static per engine: the ep MoE dispatch
        dbg = self.debug        # static: only debug engines pay for the
                                # in-program quant-error measurement

        def plan(arg_specs, donate, out=None):
            if mesh is None:
                return PartitionPlan(donate=donate)
            if out is None:
                out = (rep, self._pool_specs)
                if quant:
                    out = out + (rep,)       # the max-quant-error scalar
            return PartitionPlan(
                mesh=mesh, in_specs=arg_specs, out_specs=out,
                donate=donate)

        p_specs = getattr(self, "_param_specs", None)
        k_specs = getattr(self, "_pool_specs", None)
        self._prefill_fn = self._wrap(compile_step(
            lambda params, tokens, length, table, pools: paged_prefill(
                params, cfg, tokens, length, table, pools,
                measure_qerr=dbg, moe_fn=mfn),
            plan((p_specs, rep, rep, rep, k_specs), (4,))))
        # One fused program per decode iteration: forward + in-program key
        # fold + sampler — per-step dispatch overhead is the engine's whole
        # tax over generate's scan, so it is kept to a single call. The
        # paged-attention impl and (for int8 pools) the quantized-append
        # `qa` write layout thread through statically/as one extra arg;
        # the fp32+xla signatures stay EXACTLY the pre-kernel ones, which
        # is what keeps the bit-exact greedy-stream pins checkable.
        if quant:
            self._decode_fn = self._wrap(compile_step(
                lambda params, tokens, positions, tables, active, temps,
                tops, keys, ngen, qa, pools: decode_and_sample(
                    params, cfg, tokens, positions, tables, active, temps,
                    tops, keys, ngen, pools, qa, attn_impl=impl, mesh=mesh,
                    measure_qerr=dbg, moe_fn=mfn),
                plan((p_specs, rep, rep, rep, rep, rep, rep, rep, rep,
                      rep, k_specs), (10,))))
            self._decode_greedy_fn = self._wrap(compile_step(
                lambda params, tokens, positions, tables, active, qa,
                pools: greedy_decode_step(
                    params, cfg, tokens, positions, tables, active, pools,
                    qa, attn_impl=impl, mesh=mesh, measure_qerr=dbg,
                    moe_fn=mfn),
                plan((p_specs, rep, rep, rep, rep, rep, k_specs), (6,))))
        else:
            self._decode_fn = self._wrap(compile_step(
                lambda params, tokens, positions, tables, active, temps,
                tops, keys, ngen, pools: decode_and_sample(
                    params, cfg, tokens, positions, tables, active, temps,
                    tops, keys, ngen, pools, attn_impl=impl, mesh=mesh,
                    moe_fn=mfn),
                plan((p_specs, rep, rep, rep, rep, rep, rep, rep, rep,
                      k_specs), (9,))))
            # Greedy fast path: when every active slot decodes at
            # temperature 0 (the common serving default and the whole
            # bench), the sampler reduces to argmax — no sort/cumsum/
            # categorical/key-fold in the step program.
            self._decode_greedy_fn = self._wrap(compile_step(
                lambda params, tokens, positions, tables, active, pools:
                greedy_decode_step(params, cfg, tokens, positions, tables,
                                   active, pools, attn_impl=impl,
                                   mesh=mesh, moe_fn=mfn),
                plan((p_specs, rep, rep, rep, rep, k_specs), (5,))))
        # K-token micro-steps (ROADMAP item 4): ONE program runs micro_k
        # sequential decode iterations with in-program eos/length
        # retirement, so steady-state decode is one dispatch per K tokens
        # instead of one per token. Compiled only at micro_k > 1 — K=1
        # keeps the byte-identical per-token programs above (and their
        # bit-exact pins) untouched.
        mk = scfg.micro_k
        if mk > 1:
            if quant:
                self._micro_greedy_fn = self._wrap(compile_step(
                    lambda params, tokens, positions, tables, active,
                    limits, eos, qa, pools: micro_decode_greedy(
                        params, cfg, tokens, positions, tables, active,
                        limits, eos, pools, qa, micro_k=mk,
                        attn_impl=impl, mesh=mesh, measure_qerr=dbg,
                        moe_fn=mfn),
                    plan((p_specs, rep, rep, rep, rep, rep, rep, rep,
                          k_specs), (8,))))
                self._micro_sample_fn = self._wrap(compile_step(
                    lambda params, tokens, positions, tables, active,
                    limits, eos, temps, tops, keys, ngen, qa, pools:
                    micro_decode_sample(
                        params, cfg, tokens, positions, tables, active,
                        limits, eos, temps, tops, keys, ngen, pools, qa,
                        micro_k=mk, attn_impl=impl, mesh=mesh,
                        measure_qerr=dbg, moe_fn=mfn),
                    plan((p_specs, rep, rep, rep, rep, rep, rep, rep,
                          rep, rep, rep, rep, k_specs), (12,))))
            else:
                self._micro_greedy_fn = self._wrap(compile_step(
                    lambda params, tokens, positions, tables, active,
                    limits, eos, pools: micro_decode_greedy(
                        params, cfg, tokens, positions, tables, active,
                        limits, eos, pools, micro_k=mk, attn_impl=impl,
                        mesh=mesh, moe_fn=mfn),
                    plan((p_specs, rep, rep, rep, rep, rep, rep,
                          k_specs), (7,))))
                self._micro_sample_fn = self._wrap(compile_step(
                    lambda params, tokens, positions, tables, active,
                    limits, eos, temps, tops, keys, ngen, pools:
                    micro_decode_sample(
                        params, cfg, tokens, positions, tables, active,
                        limits, eos, temps, tops, keys, ngen, pools,
                        micro_k=mk, attn_impl=impl, mesh=mesh,
                        moe_fn=mfn),
                    plan((p_specs, rep, rep, rep, rep, rep, rep, rep,
                          rep, rep, rep, k_specs), (11,))))
        # Carry-threaded programs for the overlapped loop: the loop state
        # (tok, pos, alive, emitted) stays ON DEVICE between dispatches,
        # so the host never restages it and the only blocking edge is the
        # swept token readback. Compiled at ANY micro_k (a K=1 scan —
        # bit-identical to the plain step, the PR 13 pin) because even
        # K=1 overlap needs the carry threading; mesh is None here
        # (validated above), so the plans are plain donate-the-pools.
        if self._overlap:
            if quant:
                self._micro_carry_greedy_fn = self._wrap(compile_step(
                    lambda params, tok, pos, alive, emitted, tables,
                    limits, eos, qa, pools: micro_carry_greedy(
                        params, cfg, tok, pos, alive, emitted, tables,
                        limits, eos, pools, qa, micro_k=mk,
                        attn_impl=impl, mesh=None, measure_qerr=dbg,
                        moe_fn=mfn),
                    PartitionPlan(donate=(9,))))
                self._micro_carry_sample_fn = self._wrap(compile_step(
                    lambda params, tok, pos, alive, emitted, tables,
                    limits, eos, temps, tops, keys, qa, pools:
                    micro_carry_sample(
                        params, cfg, tok, pos, alive, emitted, tables,
                        limits, eos, temps, tops, keys, pools, qa,
                        micro_k=mk, attn_impl=impl, mesh=None,
                        measure_qerr=dbg, moe_fn=mfn),
                    PartitionPlan(donate=(12,))))
                self._chunk_carry_greedy_fn = self._wrap(compile_step(
                    lambda params, tok, pos, alive, emitted, ctoks, cpos,
                    cvalid, tables, limits, eos, prow, ppos, pngen, qa,
                    pools: chunk_carry_greedy(
                        params, cfg, tok, pos, alive, emitted, ctoks,
                        cpos, cvalid, tables, limits, eos, prow, ppos,
                        pngen, pools, qa, attn_impl=impl, mesh=None,
                        measure_qerr=dbg, moe_fn=mfn),
                    PartitionPlan(donate=(15,))))
                self._chunk_carry_sample_fn = self._wrap(compile_step(
                    lambda params, tok, pos, alive, emitted, ctoks, cpos,
                    cvalid, tables, limits, eos, prow, ppos, pngen,
                    temps, tops, rkeys, cngen, qa, pools:
                    chunk_carry_sample(
                        params, cfg, tok, pos, alive, emitted, ctoks,
                        cpos, cvalid, tables, limits, eos, prow, ppos,
                        pngen, temps, tops, rkeys, cngen, pools, qa,
                        attn_impl=impl, mesh=None, measure_qerr=dbg,
                        moe_fn=mfn),
                    PartitionPlan(donate=(19,))))
            else:
                self._micro_carry_greedy_fn = self._wrap(compile_step(
                    lambda params, tok, pos, alive, emitted, tables,
                    limits, eos, pools: micro_carry_greedy(
                        params, cfg, tok, pos, alive, emitted, tables,
                        limits, eos, pools, micro_k=mk, attn_impl=impl,
                        mesh=None, moe_fn=mfn),
                    PartitionPlan(donate=(8,))))
                self._micro_carry_sample_fn = self._wrap(compile_step(
                    lambda params, tok, pos, alive, emitted, tables,
                    limits, eos, temps, tops, keys, pools:
                    micro_carry_sample(
                        params, cfg, tok, pos, alive, emitted, tables,
                        limits, eos, temps, tops, keys, pools,
                        micro_k=mk, attn_impl=impl, mesh=None,
                        moe_fn=mfn),
                    PartitionPlan(donate=(11,))))
                self._chunk_carry_greedy_fn = self._wrap(compile_step(
                    lambda params, tok, pos, alive, emitted, ctoks, cpos,
                    cvalid, tables, limits, eos, prow, ppos, pngen,
                    pools: chunk_carry_greedy(
                        params, cfg, tok, pos, alive, emitted, ctoks,
                        cpos, cvalid, tables, limits, eos, prow, ppos,
                        pngen, pools, attn_impl=impl, mesh=None,
                        moe_fn=mfn),
                    PartitionPlan(donate=(14,))))
                self._chunk_carry_sample_fn = self._wrap(compile_step(
                    lambda params, tok, pos, alive, emitted, ctoks, cpos,
                    cvalid, tables, limits, eos, prow, ppos, pngen,
                    temps, tops, rkeys, cngen, pools: chunk_carry_sample(
                        params, cfg, tok, pos, alive, emitted, ctoks,
                        cpos, cvalid, tables, limits, eos, prow, ppos,
                        pngen, temps, tops, rkeys, cngen, pools,
                        attn_impl=impl, mesh=None, moe_fn=mfn),
                    PartitionPlan(donate=(18,))))
        self._prefill_sample_fn = self._wrap(jax.jit(
            lambda logits, temp, top, key, n: sample_tokens(
                logits, temp, top, jax.random.fold_in(key, n)[None])))
        # Chunked prefill needs no program of its own: the fused chunk
        # step is the decode program above, specialized at the packed
        # batch slots + chunk_tokens (see _chunk_step).
        # Copy-on-write: one compiled program copies a physical block in
        # every layer (traced src/dst — a single compile covers all COWs;
        # for int8 pools the scale sidecars ride the same generic copy).
        self._copy_block_fn = self._wrap(compile_step(
            lambda pools, src, dst: copy_block(pools, src, dst),
            plan((k_specs, rep, rep), (0,),
                 out=k_specs if mesh is not None else None)))
        # Fleet block import: write a whole shipped prefix chain into
        # local physical blocks in ONE dispatch (the import sits on the
        # admission path with a running batch behind it — per-block
        # dispatches would stall every decode slot for the chain's
        # length). Chains are padded to power-of-two widths so the jit
        # cache holds O(log max_blocks) programs, not one per length;
        # kv_fleet is gated to mesh=None above, so a plain
        # donate-the-pools plan suffices. The host tier rides the SAME
        # program: a host→HBM promotion is a fleet import whose payload
        # came from RAM instead of the bucket.
        if kv_fleet is not None or self._host_tier is not None:
            self._import_blocks_fn = self._wrap(compile_step(
                lambda pools, dsts, values: write_blocks(
                    pools, dsts, values),
                PartitionPlan(donate=(0,))))
        if self._spec_on:
            # Target scoring: the chunked multi-token step at width k+1
            # — under a mesh it rides the SAME plan family as the
            # decode programs (weights/pools pinned, host arrays
            # replicated), closing PR 8's single-chip note.
            if quant:
                self._spec_greedy_fn = self._wrap(compile_step(
                    lambda params, tokens, positions, valid, tables, qa,
                    pools: spec_score_greedy(
                        params, cfg, tokens, positions, valid, tables,
                        pools, qa, attn_impl=impl, mesh=mesh,
                        measure_qerr=dbg, moe_fn=mfn),
                    plan((p_specs, rep, rep, rep, rep, rep, k_specs),
                         (6,))))
                self._spec_probs_fn = self._wrap(compile_step(
                    lambda params, tokens, positions, valid, tables,
                    temps, tops, qa, pools: spec_score_probs(
                        params, cfg, tokens, positions, valid, tables,
                        temps, tops, pools, qa, attn_impl=impl,
                        mesh=mesh, measure_qerr=dbg, moe_fn=mfn),
                    plan((p_specs, rep, rep, rep, rep, rep, rep, rep,
                          k_specs), (8,))))
            else:
                self._spec_greedy_fn = self._wrap(compile_step(
                    lambda params, tokens, positions, valid, tables,
                    pools: spec_score_greedy(
                        params, cfg, tokens, positions, valid, tables,
                        pools, attn_impl=impl, mesh=mesh, moe_fn=mfn),
                    plan((p_specs, rep, rep, rep, rep, k_specs),
                         (5,))))
                self._spec_probs_fn = self._wrap(compile_step(
                    lambda params, tokens, positions, valid, tables,
                    temps, tops, pools: spec_score_probs(
                        params, cfg, tokens, positions, valid, tables,
                        temps, tops, pools, attn_impl=impl, mesh=mesh,
                        moe_fn=mfn),
                    plan((p_specs, rep, rep, rep, rep, rep, rep,
                          k_specs), (7,))))
            # Draft programs: plain decode step (proposals) + multi-token
            # chunk (prompt ingestion / catch-up), compiled on draft_cfg.
            # The draft pool stays in the model dtype (it is small — the
            # density win is the target pool's) and rides the same
            # attention impl — UNLESS the draft's own geometry violates
            # the compiled kernel's constraints (resolve_decode_impl only
            # vets the TARGET's d_head): a typical half-width draft then
            # takes the XLA gather path rather than hitting the Mosaic
            # trace failure mid-round the resolver exists to prevent.
            draft_impl = impl
            # Draft pools always store the draft model's dtype.
            draft_viol = pa.kernel_constraint_violation(
                scfg.block_size, draft_cfg.d_head,
                jnp.dtype(draft_cfg.dtype).itemsize)
            if impl in ("pallas", "pipelined") and draft_viol:
                warnings.warn(
                    f"paged-decode kernel unavailable for the DRAFT model "
                    f"({draft_viol}); draft programs fall back to the XLA "
                    "gather path (target programs keep the kernel)",
                    RuntimeWarning)
                draft_impl = "xla"
            self.draft_decode_impl = draft_impl
            dmfn = serving_moe_fn(draft_cfg, mesh)
            d_specs = getattr(self, '_draft_pool_specs', None)
            dp_specs = getattr(self, '_draft_param_specs', None)

            def draft_plan(arg_specs, donate):
                if mesh is None:
                    return PartitionPlan(donate=donate)
                return PartitionPlan(mesh=mesh, in_specs=arg_specs,
                                     out_specs=(rep, d_specs),
                                     donate=donate)

            self._draft_decode_fn = self._wrap(compile_step(
                lambda params, tokens, positions, tables, active, pools:
                greedy_decode_step(params, draft_cfg, tokens, positions,
                                   tables, active, pools,
                                   attn_impl=draft_impl, mesh=mesh,
                                   moe_fn=dmfn),
                draft_plan((dp_specs, rep, rep, rep, rep, d_specs),
                           (5,))))
            self._draft_chunk_fn = self._wrap(compile_step(
                lambda params, tokens, positions, valid, last_idx, tables,
                pools: chunked_step_greedy(
                    params, draft_cfg, tokens, positions, valid, last_idx,
                    tables, pools, attn_impl=draft_impl, mesh=mesh,
                    moe_fn=dmfn),
                draft_plan((dp_specs, rep, rep, rep, rep, rep, d_specs),
                           (6,))))
            # Rejection-sampling uniforms for a WHOLE round in one call:
            # (slots, k+1, 2) — two uniforms per (request, absolute
            # position), derived exactly as the per-position contract
            # documents (fold_in(key, SALT) then fold_in(position)), so
            # one dispatch replaces up to slots × (k+1) host round-trips.
            self._spec_uniform_fn = jax.jit(
                lambda keys, positions: jax.vmap(
                    lambda k_, p_: jax.random.uniform(
                        jax.random.fold_in(
                            jax.random.fold_in(k_, _SPEC_SALT), p_), (2,))
                )(jnp.repeat(keys, positions.shape[1], axis=0),
                  positions.reshape(-1)).reshape(*positions.shape, 2))

    def _gp_timed(self, fn, *args):
        """Dispatch one device program with its wall charged to the
        goodput meter's in-program bucket (no meter: a plain call). Used
        by the call sites that bypass :meth:`_run_program` — COW copies,
        draft programs, the prefill/spec samplers."""
        if self._goodput is None:
            return fn(*args)
        t0 = time.perf_counter()
        out = fn(*args)
        self._goodput.program(time.perf_counter() - t0)
        return out

    def _wrap(self, fn):
        """Debug mode: functionalize the bounds guards and throw on them."""
        if not self.debug:
            return fn
        from jax.experimental import checkify

        checked = checkify.checkify(fn)

        def run(*args):
            err, out = checked(*args)
            err.throw()
            return out

        return run

    # -- observability hooks (every site guards on obs=None) -----------------

    def _obs_queue(self, req: Request, requeued: bool = False) -> None:
        """Open the queue-phase span (fresh submit, resume import, or a
        recompute preemption sending the request back to the head)."""
        if self._obs is None:
            return
        if req.trace is None:
            # No upstream context (engine driven directly): mint ONE
            # trace here so queue/prefill/decode share it — three
            # parentless starts would fragment the request across three
            # unrelated traces.
            req.trace = TraceContext.mint()
        self._phase_spans[req.rid] = self._obs.tracer.start(
            "engine.queue", parent=req.trace, rid=req.rid,
            requeued=requeued)

    def _obs_admit(self, req: Request, cached_tokens: int = 0) -> None:
        if self._obs is None:
            return
        span = self._phase_spans.pop(req.rid, None)
        if span is not None:
            self._obs.tracer.end(span)
        prefill = self._obs.tracer.start(
            "engine.prefill", parent=req.trace, rid=req.rid,
            prompt_tokens=len(req.prompt) + len(req.tokens),
            cached_tokens=cached_tokens)
        # Engine-lifetime counter snapshot: the span's `chunks` attr must
        # be THIS request's chunk count (the delta), not the total.
        prefill._chunk_base = self.prefill_chunks
        self._phase_spans[req.rid] = prefill

    def _obs_first_token(self, req: Request) -> None:
        """Called exactly when ``first_token_t`` is stamped: close the
        prefill span (its duration IS the engine-side TTFT) and open the
        decode span, which records which token indices THIS engine's
        life emitted (``token_start``; resumed imports start past their
        re-ingested prefix — that is what makes cross-replica coverage
        checkable from spans alone)."""
        if self._obs is None:
            return
        self._h_ttft.observe(req.first_token_t - req.submit_t)
        span = self._phase_spans.pop(req.rid, None)
        if span is not None:
            self._obs.tracer.end(
                span, chunks=self.prefill_chunks
                - getattr(span, "_chunk_base", self.prefill_chunks))
        self._phase_spans[req.rid] = self._obs.tracer.start(
            "engine.decode", parent=req.trace, rid=req.rid,
            token_start=len(req.tokens) - 1)

    def _obs_interrupt(self, req: Request, status: str) -> None:
        """A request leaving its slot without finishing (recompute
        preemption, drain export): close the open phase span with the
        interruption recorded and the token range it actually covered."""
        if self._obs is None:
            return
        span = self._phase_spans.pop(req.rid, None)
        if span is not None:
            self._obs.tracer.end(span, status=status,
                                 token_end=len(req.tokens))

    def _obs_retire(self, req: Request) -> None:
        if self._obs is None:
            return
        span = self._phase_spans.pop(req.rid, None)
        if span is not None:
            self._obs.tracer.end(span, token_end=len(req.tokens))
        self._h_e2e.observe(req.finish_t - req.submit_t)
        emitted = len(req.tokens) - req.resume_from
        if emitted > 1 and req.first_token_t is not None:
            self._h_intertok.observe(
                (req.finish_t - req.first_token_t) / (emitted - 1))

    # -- front end -----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *, temperature: float = 0.0,
               top_p: Optional[float] = None,
               eos_token: Optional[int] = None,
               key: Optional[jax.Array] = None,
               trace: Optional[TraceContext] = None,
               slo_class: str = "standard",
               deadline_s: Optional[float] = None,
               adapter_id: Optional[str] = None) -> int:
        """Queue a generation request; returns its id. Same sampling
        contract as ``generate``: temperature 0 is greedy, ``top_p`` needs
        temperature > 0. ``key`` overrides the engine-derived per-request
        PRNG key (``fold_in(base, rid)``) — a fleet router passes one so
        the SAME request dispatched to any replica (or re-dispatched after
        a preemption) draws the identical sampled stream regardless of the
        replica-local request id."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("prompt must hold at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if top_p is not None and not 0 < top_p <= 1:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_p is not None and temperature == 0:
            raise ValueError("top_p needs temperature > 0 (greedy ignores it)")
        if adapter_id is not None:
            if not self._lora_on:
                raise ValueError(
                    "adapter_id needs lora_rank > 0 in the ServingConfig")
            if adapter_id not in self._adapters:
                raise ValueError(
                    f"unknown adapter {adapter_id!r} — register_adapter "
                    "first")
        if self.scfg.prefill == "bucketed":
            self.scfg.bucket_for(len(prompt))  # must fit a prefill bucket
        total = len(prompt) + max_new_tokens
        if total > self.scfg.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds max_len {self.scfg.max_len}")
        if self.scfg.blocks_for(total) > self.scfg.n_blocks - 1:
            raise ValueError(
                f"request needs {self.scfg.blocks_for(total)} blocks but the "
                f"pool holds {self.scfg.n_blocks - 1}")
        rid = self._next_rid
        self._next_rid += 1
        if key is None:
            key = jax.random.fold_in(self._base_key, rid)
        else:
            key = _check_key(key)
        now = time.monotonic()
        req = Request(
            rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, top_p=1.0 if top_p is None else top_p,
            eos_token=eos_token, key=key,
            submit_t=now, trace=trace, slo_class=str(slo_class),
            deadline=None if deadline_s is None
            else now + float(deadline_s),
            adapter_id=adapter_id, generation=self.generation)
        self._requests[rid] = req
        self._gen_streams[req.generation] = \
            self._gen_streams.get(req.generation, 0) + 1
        self._queue.append(req)
        self._obs_queue(req)
        return rid

    def export_inflight(self) -> List[dict]:
        """Every not-yet-done request as a JSON-serializable record:
        original prompt, tokens emitted so far, the per-request sampling
        key (raw uint32 words), and the sampling params — everything a
        sibling engine needs to continue the stream token-identically via
        :meth:`resume_inflight`. The graceful-drain half of the serve
        subsystem's preemption contract (docs/parity.md "Serve as a
        task"); the engine itself is left untouched. In overlap mode the
        pipeline is flushed first — tokens still riding the in-flight
        program belong in the exported records, not on the floor."""
        if self._overlap:
            self.flush()
        records = []
        for req in self._requests.values():
            if req.status == DONE:
                continue
            record = {
                "rid": req.rid,
                "prompt": [int(t) for t in np.asarray(req.prompt)],
                "tokens": [int(t) for t in req.tokens],
                "key": np.asarray(req.key, np.uint32).reshape(-1).tolist(),
                "max_new_tokens": req.max_new_tokens,
                "temperature": req.temperature,
                "top_p": req.top_p,
                "eos_token": req.eos_token,
                "slo_class": req.slo_class,
                # The weights the stream decodes under — the importer
                # pins this generation or restores it before decoding.
                "generation": req.generation,
            }
            if req.adapter_id is not None:
                record["adapter_id"] = req.adapter_id
            if req.deadline is not None:
                # Deadlines cross processes as REMAINING seconds (no
                # shared monotonic clock), clamped at 0 — an expired
                # deadline stays expired on the importer.
                record["deadline_s"] = max(
                    0.0, req.deadline - time.monotonic())
            records.append(record)
            # Close the open phase span as "exported" — the drain/export
            # leg is part of the request's waterfall. Generation state is
            # untouched; only the observability record is finalized.
            self._obs_interrupt(req, "exported")
        return records

    def resume_inflight(self, records: List[dict],
                        trace: Optional[TraceContext] = None) -> Dict[int, int]:
        """Import :meth:`export_inflight` records (possibly from another
        process); returns {exported rid: local rid}. A resumed request
        re-ingests prompt + emitted tokens as context (prefilled, never
        regenerated) and continues generating at token index
        ``len(tokens)`` — with the exported key, the continued stream is
        token-identical to the uninterrupted one (greedy trivially so;
        sampled because every draw is keyed by ``fold_in(key, index)`` or
        absolute position, never by schedule). A record that already
        satisfied its stopping condition imports as done."""
        mapping: Dict[int, int] = {}
        for record in records:
            prompt = np.asarray(record["prompt"], np.int32).reshape(-1)
            tokens = [int(t) for t in record.get("tokens", ())]
            max_new = int(record["max_new_tokens"])
            eos = record.get("eos_token")
            if len(prompt) < 1:
                raise ValueError("prompt must hold at least one token")
            if max_new < 1:
                raise ValueError(
                    f"max_new_tokens must be >= 1, got {max_new}")
            if len(tokens) > max_new:
                raise ValueError(
                    f"resume record carries {len(tokens)} tokens but "
                    f"max_new_tokens is {max_new}")
            total = len(prompt) + max_new
            if total > self.scfg.max_len:
                raise ValueError(
                    f"resumed context {len(prompt)} + max_new_tokens "
                    f"{max_new} exceeds max_len {self.scfg.max_len}")
            if self.scfg.blocks_for(total) > self.scfg.n_blocks - 1:
                raise ValueError(
                    f"resumed request needs {self.scfg.blocks_for(total)} "
                    f"blocks but the pool holds {self.scfg.n_blocks - 1}")
            if self.scfg.prefill == "bucketed" and tokens:
                # Bucketed prefill must ingest prompt + resumed prefix in
                # ONE padded program, so the context needs a bucket even
                # though only the prompt did at original submit time. When
                # it has outgrown every bucket, fall back to recomputing
                # from the prompt alone: the keyed samplers (and greedy's
                # context purity) regenerate the identical prefix, so the
                # stream — and any offset-based reader — is unaffected; a
                # valid in-flight request must never become unresumable.
                try:
                    self.scfg.bucket_for(len(prompt) + len(tokens))
                except ValueError:
                    tokens = []
            aid = record.get("adapter_id")
            if aid is not None:
                if not self._lora_on:
                    raise ValueError(
                        f"resume record pins adapter {aid!r} but this "
                        "engine has lora_rank 0")
                if aid not in self._adapters:
                    raise ValueError(
                        f"resume record pins adapter {aid!r} — "
                        "register_adapter on the importer first")
            gen = int(record.get("generation", self.generation))
            rid = self._next_rid
            self._next_rid += 1
            key = _check_key(record["key"])
            now = time.monotonic()
            deadline_s = record.get("deadline_s")
            req = Request(
                rid=rid, prompt=prompt, max_new_tokens=max_new,
                temperature=float(record.get("temperature", 0.0)),
                top_p=float(record.get("top_p", 1.0)),
                eos_token=None if eos is None else int(eos), key=key,
                submit_t=now, tokens=tokens,
                resume_from=len(tokens), trace=trace,
                slo_class=str(record.get("slo_class", "standard")),
                deadline=None if deadline_s is None
                else now + float(deadline_s),
                adapter_id=aid, generation=gen)
            self._requests[rid] = req
            if req.finished:
                req.status = DONE
                req.finish_t = time.monotonic()
            else:
                if gen not in self._gen_params:
                    # The record pins a generation this engine does not
                    # hold. Restore it through the param loader rather
                    # than silently continuing the stream under
                    # different weights.
                    if self.param_loader is None:
                        raise ValueError(
                            f"resume record pins param generation {gen}, "
                            "which is not resident and no param_loader "
                            "could restore it — refusing to decode the "
                            "stream under different weights")
                    restored = self.param_loader(gen)
                    if restored is None:
                        raise ValueError(
                            f"resume record pins param generation {gen} "
                            "and the param_loader returned nothing — "
                            "refusing to decode the stream under "
                            "different weights")
                    self._gen_params[gen] = restored
                self._gen_streams[gen] = \
                    self._gen_streams.get(gen, 0) + 1
                if self._goodput is not None and tokens:
                    # The imported prefix is re-ingested context another
                    # engine already produced — work the goodput ratio
                    # discounts as re-dispatch waste.
                    self._goodput.wasted_reingest(len(tokens))
                self._queue.append(req)
                self._obs_queue(req)
            mapping[int(record.get("rid", rid))] = rid
        return mapping

    def poll(self, rid: int) -> dict:
        req = self._requests[rid]
        return {"status": req.status, "tokens": list(req.tokens)}

    def request(self, rid: int) -> Request:
        """The full lifecycle record (timestamps, preemptions) — the bench
        computes TTFT/latency percentiles from these."""
        return self._requests[rid]

    def result(self, rid: int) -> List[int]:
        req = self._requests[rid]
        if req.status != DONE:
            raise RuntimeError(f"request {rid} is {req.status}, not done")
        return list(req.tokens)

    @property
    def params(self) -> Params:
        """The ACTIVE generation's weights — what new admissions bind
        to. Older generations stay resident in ``_gen_params`` while
        any of their streams is in flight (:meth:`adopt_params`)."""
        return self._gen_params[self.generation]

    @property
    def stale_generation_streams(self) -> int:
        """In-flight streams still pinned to a non-active generation —
        the mid-roll gauge; 0 means the roll is complete and exactly
        one params buffer is resident."""
        return sum(c for g, c in self._gen_streams.items()
                   if g != self.generation)

    def adopt_params(self, params: Params,
                     generation: Optional[int] = None) -> int:
        """Install a new weight generation WITHOUT dropping a stream —
        the drain-free half of the hot swap: new admissions bind to the
        new params immediately, every in-flight stream keeps decoding
        under the generation it started on (step() partitions
        dispatches by generation until the old streams retire), and the
        old buffer frees when its last stream leaves. ``generation``
        defaults to the next integer; the replica passes the published
        checkpoint step so /healthz reports WHICH weights are live.
        Returns the installed generation."""
        if self.mesh is not None:
            raise ValueError(
                "adopt_params is single-chip for now: sharded gangs "
                "re-shard new params by building a fresh engine")
        gen = self.generation + 1 if generation is None else int(generation)
        if gen <= self.generation:
            raise ValueError(
                f"param generation must grow monotonically: got {gen}, "
                f"active is {self.generation}")
        if self._overlap:
            # The in-flight program was dispatched under the old
            # generation's params — sweep it before the active pointer
            # moves, so no future sweep replays a stale dispatch.
            self.flush()
        self._gen_params[gen] = params
        self.generation = gen
        self.param_swaps += 1
        # Free every non-active generation with no streams left — the
        # common roll (idle or all-current slots) frees the old buffer
        # here rather than waiting for a retirement edge.
        for g in [g for g in self._gen_params
                  if g != gen and not self._gen_streams.get(g, 0)]:
            del self._gen_params[g]
        return gen

    def _gen_release(self, req: Request) -> None:
        """One stream retired: drop its generation's stream refcount
        and free any non-active generation whose last stream just left
        — the double-buffer release edge of the hot swap."""
        g = req.generation
        left = self._gen_streams.get(g, 0) - 1
        if left > 0:
            self._gen_streams[g] = left
            return
        self._gen_streams.pop(g, None)
        if g != self.generation:
            self._gen_params.pop(g, None)

    # -- paged LoRA adapters -------------------------------------------------

    def register_adapter(self, adapter_id: str, layers,
                         scale: float = 1.0, *,
                         host_copy: bool = True) -> str:
        """Register a tenant's LoRA adapter under ``adapter_id``:
        ``layers`` is one (A (d, r), B (r, d)) pair per model layer
        (``{"a": ..., "b": ...}`` dicts or tuples; any r <= lora_rank,
        zero-padded — see :func:`lora.pack_adapter`). The packed
        payload is content-hashed (same weights + scale → same hash on
        every replica) and shipped to the fleet bucket when a kv_fleet
        client is attached, so reloads — and other replicas'
        registrations — move no duplicate bytes. Residency is lazy:
        pool blocks are claimed at first use, and cold refcount-0
        adapters LRU-evict under pool pressure, reloading from the
        host copy (``host_copy=True``) or the bucket. Returns the
        content hash."""
        if not self._lora_on:
            raise ValueError(
                "register_adapter needs lora_rank > 0 (and "
                "n_adapter_blocks) in the ServingConfig")
        payload = pack_adapter(layers, self.scfg.lora_rank,
                               self.cfg.d_model)
        if payload.shape[0] != self.cfg.n_layers:
            raise ValueError(
                f"adapter carries {payload.shape[0]} layers, the model "
                f"has {self.cfg.n_layers}")
        if self.cfg.n_layers > self.scfg.n_adapter_blocks - 1:
            raise ValueError(
                f"one adapter needs {self.cfg.n_layers} blocks but the "
                f"pool holds {self.scfg.n_adapter_blocks - 1} — raise "
                "n_adapter_blocks")
        h = adapter_fingerprint(payload, float(scale))
        existing = self._adapters.get(adapter_id)
        if existing is not None:
            if existing["hash"] == h:
                return h              # same content: keep residency
            if existing["refs"]:
                raise ValueError(
                    f"adapter {adapter_id!r} re-registered with "
                    "different weights while streams decode under it — "
                    "retire them first (or register a new id)")
            if existing["blocks"] is not None:
                self._evict_adapter(adapter_id)
        can_ship = self._fleet is not None \
            and hasattr(self._fleet, "ship_adapter")
        if not host_copy and not can_ship:
            raise ValueError(
                "host_copy=False needs an attached kv_fleet client "
                "with ship_adapter: an evicted adapter must have "
                "somewhere to reload from")
        if can_ship:
            self._fleet.ship_adapter(
                h, adapter_payload(payload, float(scale)))
        self._adapters[adapter_id] = {
            "hash": h, "scale": float(scale),
            "payload": payload if host_copy else None,
            "blocks": None, "last_use": 0.0, "refs": 0,
        }
        self.adapters_registered += 1
        return h

    def _evict_adapter(self, adapter_id: str) -> None:
        """Return a cold adapter's blocks to the pool (its bytes need
        no scrubbing — no slot table points at freed blocks, and the
        next load overwrites them)."""
        entry = self._adapters[adapter_id]
        for b in entry["blocks"]:
            self._lora_alloc.decref(int(b))
        entry["blocks"] = None
        self.adapter_evictions += 1

    def _ensure_adapter_resident(self, adapter_id: str) -> dict:
        """The adapter's registry entry with its pool blocks resident,
        loading (and LRU-evicting cold refcount-0 adapters) on a miss —
        the KV pool's evict-then-reload discipline applied to adapter
        bytes. A reload with no host copy fetches from the fleet bucket
        by content hash; any failure raises rather than decode under
        wrong weights."""
        entry = self._adapters[adapter_id]
        entry["last_use"] = time.monotonic()
        if entry["blocks"] is not None:
            return entry
        n_layers = self.cfg.n_layers
        while self._lora_alloc.available < n_layers:
            cold = [(aid, e) for aid, e in self._adapters.items()
                    if e["blocks"] is not None and not e["refs"]]
            if not cold:
                raise RuntimeError(
                    "adapter pool exhausted with every resident adapter "
                    "in use — raise n_adapter_blocks")
            self._evict_adapter(
                min(cold, key=lambda kv: kv[1]["last_use"])[0])
        blocks = self._lora_alloc.alloc(n_layers)
        payload = entry["payload"]
        if payload is None:
            data = (self._fleet.fetch_adapter(entry["hash"])
                    if self._fleet is not None
                    and hasattr(self._fleet, "fetch_adapter") else None)
            if data is None:
                for b in blocks:
                    self._lora_alloc.decref(b)
                raise RuntimeError(
                    f"adapter {adapter_id!r} evicted and its payload "
                    f"({entry['hash']}) unavailable in the fleet bucket "
                    "— refusing to decode under missing weights")
            payload, _scale = split_adapter_payload(data)
            if payload.shape != (n_layers, 2, self.scfg.lora_rank,
                                 self.cfg.d_model):
                for b in blocks:
                    self._lora_alloc.decref(b)
                raise RuntimeError(
                    f"adapter {adapter_id!r} payload has foreign "
                    f"geometry {payload.shape}")
        self._lora_pool = self._lora_pool.at[jnp.asarray(blocks)].set(
            jnp.asarray(payload, self._lora_pool.dtype))
        entry["blocks"] = [int(b) for b in blocks]
        self.adapter_loads += 1
        return entry

    def _bind_adapter(self, slot: int, req: Request) -> None:
        """Point the slot's per-layer gather rows at its adapter's pool
        blocks (scratch rows + scale 0 for adapter-less requests — the
        exact-no-op path) and pin the adapter against eviction for the
        slot's lifetime."""
        if not self._lora_on or req.adapter_id is None:
            self._slot_lora_blocks[slot] = 0
            self._slot_lora_scale[slot] = 0.0
            return
        entry = self._ensure_adapter_resident(req.adapter_id)
        entry["refs"] += 1
        self._slot_lora_blocks[slot] = np.asarray(
            entry["blocks"], np.int32)
        self._slot_lora_scale[slot] = entry["scale"]

    @property
    def n_active(self) -> int:
        return sum(r is not None for r in self._slots)

    @property
    def queue_depth(self) -> int:
        """Requests admitted to the engine but not yet holding a slot —
        the router's autoscale signal."""
        return len(self._queue)

    @property
    def has_work(self) -> bool:
        return bool(self._queue) or self.n_active > 0 \
            or self._inflight is not None

    def step(self) -> dict:
        """One scheduler iteration: admit → (chunk|spec|decode) → retire.
        Returns what happened (request ids admitted/finished, active).
        With ``ServingConfig.overlap`` on, the iteration instead runs the
        asynchronous loop (:meth:`_step_overlapped`): dispatch the NEXT
        program, then sweep the PREVIOUS one — results lag one step.

        Mid-roll (streams pinned to more than one param generation in
        flight after :meth:`adopt_params`) the step partitions its
        dispatches BY generation: each partition runs the normal fused
        programs under its own weights with the other partitions' slots
        masked out exactly like empty slots. Keyed sampling makes every
        stream schedule-independent, so the partitioned schedule emits
        the same tokens each stream would see in a dedicated engine —
        old streams finish under old weights, new ones run the new, and
        nobody drops. The overlap loop requires a single generation;
        the sync partitioned path carries the roll window."""
        if self._overlap:
            if all(r.generation == self.generation
                   for r in list(self._slots) + list(self._queue)
                   if r is not None):
                return self._step_overlapped()
            # Mid-roll: sweep the in-flight program and fall through to
            # the synchronous partitioned body until the old streams
            # retire.
            self.flush()
        t0 = time.perf_counter() if self._obs is not None else 0.0
        if self._goodput is not None:
            self._goodput.begin_step()
        self.steps += 1
        admitted = []
        finished = list(self._pending_finished)   # swept by a flush
        self._pending_finished = []
        self._admit(admitted, finished)
        gens = sorted({r.generation for r in self._slots if r is not None})
        for g in (gens if len(gens) > 1 else [None]):
            self._gen_filter = g
            try:
                if not any(self._gen_ok(r) for r in self._slots):
                    continue
                prefilling = self.scfg.prefill == "chunked" and any(
                    self._gen_ok(self._slots[i]) and self._prefilling(i)
                    for i in range(self.scfg.slots))
                if prefilling:
                    # With spec on, the chunk program advances ONLY the
                    # ingesting slot and the spec round below advances the
                    # decoders: a request's post-first tokens then ALWAYS
                    # come from the position-keyed spec streams, so its
                    # sampled stream is identical under any co-scheduling
                    # (the same schedule-independence the plain sampler's
                    # fold_in keys give the non-speculative engine).
                    self._chunk_step(finished)
                if self._spec_on:
                    self._spec_step(finished)
                elif not prefilling:
                    # One path per slot per scheduler step: a step with an
                    # admitting slot runs the packed chunk program above
                    # (the chunk IS that step's multi-token budget);
                    # pure-decode steady state runs the K-wide micro-step
                    # when configured (spec rounds, when on, are already
                    # the multi-token path). K=1 keeps the byte-identical
                    # per-token program.
                    if self.scfg.micro_k > 1:
                        self._micro_decode(finished)
                    else:
                        self._decode(finished)
            finally:
                self._gen_filter = None
        # Synchronous-mode demotion: stage and force back-to-back — the
        # device is idle after the step's readback, so the blocking
        # force costs what it costs (the overlap loop is the path that
        # hides it; sync mode keeps the same hierarchy semantics).
        self._demote_pass()
        self._finalize_demotions()
        if self._obs is not None:
            wall = time.perf_counter() - t0
            self._h_step.observe(wall)
            if self._goodput is not None:
                # Whatever the step's wall spent outside its program
                # dispatches is host gap — the ROADMAP-4 overhead gauge.
                self._goodput.end_step(wall)
        return {"admitted": admitted, "finished": finished,
                "active": self.n_active, "queued": len(self._queue)}

    def drain(self, max_steps: int = 100_000) -> Dict[int, List[int]]:
        """Step until queue and slots are empty; returns {rid: tokens} for
        every request ever submitted. Raises :class:`DrainTimeout` (with
        the unfinished request ids) if ``max_steps`` is exhausted first —
        partial results are never returned silently."""
        steps = 0
        while self.has_work:
            if steps >= max_steps:
                raise DrainTimeout(
                    max_steps,
                    [rid for rid, r in self._requests.items()
                     if r.status != DONE])
            self.step()
            steps += 1
        return {rid: list(r.tokens) for rid, r in self._requests.items()}

    # -- the asynchronous loop (ServingConfig.overlap) -----------------------
    #
    # One iteration of the overlapped engine (ROADMAP item 4's last rung):
    #
    #   admit        — into slots free as of the LAST sweep; the admitted
    #                  request rides the NEXT program's chunk rows, so the
    #                  in-flight program is never recompiled or restarted
    #   dispatch N+1 — planned from the worst-case device positions
    #                  (exact for live slots); loop state comes from
    #                  program N's device carry, never from the host
    #   consume N    — the ONE blocking edge: read program N's tokens
    #                  back and replay the sweep from its dispatch record
    #
    # so the host sweep of program N (retire, admit bookkeeping, publish
    # staging, obs) runs while the device executes program N+1.
    # Correctness leans on two facts. (a) Donated pools serialize device
    # execution in dispatch order: blocks freed by sweep N and handed to
    # a new admission are only written by programs enqueued after N+1,
    # and a ref-0 cached block is never in a dispatched table, so
    # eviction under an in-flight program races nothing. (b) Greedy and
    # keyed sampled streams are schedule-independent (the repo-wide pin),
    # so the async loop's streams are bit-identical to the synchronous
    # loop's even where admission lands one sweep later. Pool pressure
    # the planner cannot cover flushes to the synchronous edge first —
    # preemption happens exactly where (and only where) the sync loop
    # would preempt. docs/parity.md "Async overlap" carries the full
    # contract.

    def _step_overlapped(self) -> dict:
        t0 = time.perf_counter() if self._obs is not None else 0.0
        if self._goodput is not None:
            self._goodput.begin_step()
        self.steps += 1
        admitted: List[int] = []
        finished: List[int] = self._pending_finished
        self._pending_finished = []
        self._admit(admitted, finished)
        rec = self._dispatch_next(finished)   # pool pressure may flush
        # Covered = a program spanned this step's host work: either the
        # previous one was still unconsumed or a new one just enqueued.
        covered = rec is not None or self._inflight is not None
        self._consume_one(self._inflight, finished)
        # Tier migration rides the covered window: last step's staged
        # demotions force HERE (their reads enqueued behind the program
        # the consume edge just waited out), and the next batch stages
        # behind the program dispatched above — demote traffic is
        # overlapped host work, never a step-loop stall.
        self._finalize_demotions()
        self._demote_pass()
        self._inflight = rec
        if self._obs is not None:
            wall = time.perf_counter() - t0
            self._h_step.observe(wall)
            if self._goodput is not None:
                self._goodput.end_step_overlapped(wall, covered)
        return {"admitted": admitted, "finished": finished,
                "active": self.n_active, "queued": len(self._queue)}

    def flush(self) -> None:
        """Drain the overlap pipeline to the synchronous edge: consume
        and sweep the in-flight program, then drop the device carry (the
        next dispatch rebuilds it from the host mirrors — legal because
        the carry convention is absolute, so after a full sweep the
        mirrors ARE the device state). Every synchronous code path that
        needs exact state (preemption, export_inflight, direct reads)
        runs behind a flush. Retirements swept here surface in the next
        step's ``finished`` list. No-op in sync mode or when idle."""
        self._consume_one(self._inflight, self._pending_finished)
        self._inflight = None
        self._carry = None

    def _rebuild_carry(self) -> None:
        """Host mirrors → device carry (engine start, or after a flush).
        Prefilling and empty slots enter dead: the chunk program's
        in-program promotion is the only writer that turns a carry row
        live, so a dead row's tok/pos staleness is unreadable."""
        alive = np.array(
            [req is not None and req.status == RUNNING
             and not self._prefilling(i)
             for i, req in enumerate(self._slots)])
        emitted = np.array(
            [len(req.tokens) if req is not None else 0
             for req in self._slots], np.int32)
        self._carry = (
            jnp.asarray(self._last_token),
            jnp.asarray(np.where(alive, self._positions, 0)),
            jnp.asarray(alive),
            jnp.asarray(emitted))
        self._planned_pos = np.asarray(self._positions, np.int32).copy()
        self._planned_emitted = emitted.copy()

    # lint: begin-overlap-dispatch — nothing between these markers may
    # block on the device (block_until_ready / device_get / np.asarray
    # of a device value): this code runs while the PREVIOUS program is
    # still executing, and a blocking read here re-serializes the loop
    # the overlap exists to kill. `make lint` (tier-1) enforces it.

    def _plan_step(self):
        """What the next program should run, read off the worst-case
        device state (``_planned_pos``/``_planned_emitted`` — exact for
        live slots; an over-estimate only for slots that eos-retired
        inside a still-unswept program, whose rows the device masks
        anyway). Prefill rows split the ONE shared ``chunk_tokens``
        budget oldest-admission-first — several admitting slots pack
        into one program instead of serializing one slot per step.
        Returns (prefill rows, decode candidate slots, per-slot
        reservation widths), or None when nothing is worth running."""
        n, K, W = self.scfg.slots, self.scfg.micro_k, self.scfg.chunk_tokens
        prefill = []                  # (slot, chunk, planned pos, completing)
        budget = W
        for i in sorted(range(n), key=lambda j: self._admit_seq[j]):
            req = self._slots[i]
            if req is None or req.status != RUNNING or not budget:
                continue
            pos = int(self._planned_pos[i])
            target = int(self._prefill_target[i])
            if pos < target:
                c = min(budget, target - pos)
                budget -= c
                prefill.append((i, c, pos, pos + c >= target))
        decode = [
            i for i, req in enumerate(self._slots)
            if req is not None and req.status == RUNNING
            and int(self._planned_pos[i]) >= int(self._prefill_target[i])
            and int(self._planned_emitted[i]) < req.max_new_tokens]
        if not prefill and not decode:
            return None
        widths = np.zeros((n,), np.int32)
        for i, c, _, _ in prefill:
            widths[i] = c
        for i in decode:
            widths[i] = 1 if prefill else min(
                K, self._slots[i].max_new_tokens
                - int(self._planned_emitted[i]))
        return prefill, decode, widths

    def _reserve_planned(self, widths: np.ndarray) -> bool:
        """The async half of :meth:`_ensure_blocks`: cover every slot's
        next ``widths[i]`` writes FROM ITS PLANNED POSITION, evicting
        ref-0 cached blocks but never preempting — the in-flight program
        pins every running slot (a preemption would roll back state the
        device is still advancing). False = the pool can't cover it;
        allocations made so far are kept (the slots own them) and the
        caller flushes so the synchronous reservation path — the only
        place overlap mode preempts — can run on exact state."""
        bs = self.scfg.block_size
        for slot in sorted(range(self.scfg.slots),
                           key=lambda i: self._admit_seq[i]):
            w = int(widths[slot])
            if not w:
                continue
            pos = int(self._planned_pos[slot])
            for block_i in range(pos // bs, (pos + w - 1) // bs + 1):
                if self._tables[slot, block_i] != SCRATCH_BLOCK:
                    continue
                got = self._reserve(1, 0)
                if got is None:
                    return False
                self._tables[slot, block_i] = got[0]
        return True

    def _dispatch_next(self, finished: list) -> Optional[dict]:
        """Plan, reserve, and enqueue the next program; returns its sweep
        record (the caller installs it as in-flight AFTER consuming the
        previous program). Returns None when there is nothing to run —
        the consume-only drain tail."""
        if self._carry is None:
            self._rebuild_carry()
        plan = self._plan_step()
        if plan is None:
            return None
        prefill, decode, widths = plan
        if not self._reserve_planned(widths):
            # Pool pressure beyond eviction: fall back to the sync edge.
            # After the flush the mirrors are exact, so _ensure_blocks
            # preempts exactly where the synchronous loop would have.
            self.overlap_flushes += 1
            self.flush()
            finished.extend(self._pending_finished)
            self._pending_finished = []
            self._rebuild_carry()
            plan = self._plan_step()
            if plan is None:
                return None
            prefill, decode, widths = plan
            before = self.preemption_count
            self._ensure_blocks(widths)
            if self.preemption_count != before:
                self._rebuild_carry()     # preempted slots left the carry
                plan = self._plan_step()
                if plan is None:
                    return None
                prefill, decode, widths = plan
        if prefill:
            return self._dispatch_chunk(prefill, decode)
        return self._dispatch_micro(decode, widths)

    def _req_limits_eos(self):
        limits = np.array(
            [r.max_new_tokens if r is not None else 0
             for r in self._slots], np.int32)
        eos = np.array(
            [r.eos_token if r is not None and r.eos_token is not None
             else -1 for r in self._slots], np.int32)
        return limits, eos

    def _launch(self, fn, *args, qa=None):
        """Enqueue one carry program against the donated pools WITHOUT
        reading anything back: only the dispatch call's wall is charged
        to the program bucket here (execution overlaps the sweep; the
        consume edge charges the blocked wait). Installs the returned
        device carry/pools; returns the (ys, qerr) futures."""
        t0 = time.perf_counter() if self._goodput is not None else 0.0
        if self._quantized:
            ys, self._carry, self.pools, qerr = fn(*args, qa, self.pools)
        else:
            ys, self._carry, self.pools = fn(*args, self.pools)
            qerr = None
        if self._goodput is not None:
            self._goodput.program(time.perf_counter() - t0)
        return ys, qerr

    def _dispatch_micro(self, decode: List[int], widths: np.ndarray) -> dict:
        """Pure-decode program: the K-token carry micro-step (K=1 is a
        length-1 scan of the same body — bit-identical to the plain
        step, the PR 13 pin)."""
        n = self.scfg.slots
        tok, pos, alive, emitted = self._carry
        limits, eos = self._req_limits_eos()
        cand = np.zeros((n,), bool)
        cand[decode] = True
        qa = None
        if self._quantized:
            qa = self._micro_quant_layout(
                np.where(cand, self._planned_pos, 0).astype(np.int32),
                widths)
        rec_pos = self._planned_pos.copy()
        if self._all_greedy():
            ys, qerr = self._launch(
                self._micro_carry_greedy_fn, self._model_params(), tok, pos,
                alive,
                emitted, jnp.asarray(self._tables), jnp.asarray(limits),
                jnp.asarray(eos), qa=qa)
        else:
            temps, tops = self._temps_tops()
            ys, qerr = self._launch(
                self._micro_carry_sample_fn, self._model_params(), tok, pos,
                alive,
                emitted, jnp.asarray(self._tables), jnp.asarray(limits),
                jnp.asarray(eos), jnp.asarray(temps), jnp.asarray(tops),
                jnp.asarray(self._slot_keys), qa=qa)
        self.decode_steps += 1
        if self.scfg.micro_k > 1:
            self.micro_steps += 1
        for i in decode:
            w = int(widths[i])
            self._planned_pos[i] += w
            self._planned_emitted[i] += w
        return {"kind": "micro", "ys": ys, "qerr": qerr,
                "reqs": list(self._slots), "cand": cand, "pos0": rec_pos}

    def _dispatch_chunk(self, prefill, decode: List[int]) -> dict:
        """Mixed program: every admitting slot's chunk rows packed beside
        the decode carry rows — the multi-slot generalization of
        :meth:`_chunk_step`, with completing prefills PROMOTED in-program
        into the carry (their first token samples on device; the host
        only reads it back at the sweep)."""
        n, W = self.scfg.slots, self.scfg.chunk_tokens
        m = self.scfg.max_blocks_per_slot
        tok, pos_c, alive_c, emitted_c = self._carry
        limits, eos = self._req_limits_eos()
        ctoks = np.zeros((W,), np.int32)
        cpos = np.zeros((W,), np.int32)
        cvalid = np.zeros((W,), bool)
        tables = np.zeros((n + W, m), np.int32)
        tables[:n] = self._tables
        prow = np.full((n,), -1, np.int32)
        ppos = np.zeros((n,), np.int32)
        pngen = np.zeros((n,), np.int32)
        temps = np.zeros((n + W,), np.float32)
        tops = np.ones((n + W,), np.float32)
        rkeys = np.zeros((n + W, 2), np.uint32)
        cngen = np.zeros((W,), np.int32)
        temps[:n], tops[:n] = self._temps_tops()
        rkeys[:n] = self._slot_keys
        rows = []                     # (slot, row offset, c, pos, completing)
        off = 0
        for i, c, pos, completing in prefill:
            req = self._slots[i]
            ctx = self._context_ids(req)
            ctoks[off:off + c] = ctx[pos:pos + c]
            cpos[off:off + c] = np.arange(pos, pos + c)
            cvalid[off:off + c] = True
            tables[n + off:n + off + c] = self._tables[i]
            temps[n + off:n + off + c] = req.temperature
            tops[n + off:n + off + c] = req.top_p
            rkeys[n + off:n + off + c] = self._slot_keys[i]
            cngen[off:off + c] = len(req.tokens)
            if completing:
                prow[i] = off + c - 1
                ppos[i] = int(self._prefill_target[i])
                pngen[i] = len(req.tokens)
            rows.append((i, off, c, pos, completing))
            off += c
        qa = None
        if self._quantized:
            rpos = np.zeros((n + W,), np.int32)
            rvalid = np.zeros((n + W,), bool)
            for i in decode:
                rpos[i] = self._planned_pos[i]
                rvalid[i] = True
            rpos[n:], rvalid[n:] = cpos, cvalid
            qa = self._quant_layout(tables, rpos[:, None], rvalid[:, None])
        rec_pos = self._planned_pos.copy()
        work = (len(decode) + int(cvalid.sum()),
                float(sum(int(rec_pos[i]) for i in decode))
                + float(cpos[cvalid].sum()))
        lblocks = lscales = None
        if self._lora_on:
            # Chunk rows inherit the owning slot's adapter rows (same
            # expansion as the sync chunk path).
            lblocks = np.zeros((n + W, self.cfg.n_layers), np.int32)
            lscales = np.zeros((n + W,), np.float32)
            lblocks[:n] = self._slot_lora_blocks
            lscales[:n] = self._slot_lora_scale
            for i, roff, c, _pos, _completing in rows:
                lblocks[n + roff:n + roff + c] = self._slot_lora_blocks[i]
                lscales[n + roff:n + roff + c] = self._slot_lora_scale[i]
        base = (self._model_params(lblocks, lscales), tok, pos_c, alive_c,
                emitted_c,
                jnp.asarray(ctoks), jnp.asarray(cpos),
                jnp.asarray(cvalid), jnp.asarray(tables),
                jnp.asarray(limits), jnp.asarray(eos), jnp.asarray(prow),
                jnp.asarray(ppos), jnp.asarray(pngen))
        if self._all_greedy():
            ys, qerr = self._launch(
                self._chunk_carry_greedy_fn, *base, qa=qa)
        else:
            ys, qerr = self._launch(
                self._chunk_carry_sample_fn, *base, jnp.asarray(temps),
                jnp.asarray(tops), jnp.asarray(rkeys),
                jnp.asarray(cngen), qa=qa)
        self.chunk_steps += 1
        for i, c, pos, completing in prefill:
            if completing:
                self._planned_pos[i] = int(self._prefill_target[i])
                self._planned_emitted[i] += 1
            else:
                self._planned_pos[i] = pos + c
        for i in decode:
            self._planned_pos[i] += 1
            self._planned_emitted[i] += 1
        return {"kind": "chunk", "ys": ys, "qerr": qerr,
                "reqs": list(self._slots), "decode": list(decode),
                "rows": rows, "pos0": rec_pos, "work": work}

    # lint: end-overlap-dispatch

    def _consume_one(self, rec: Optional[dict], finished: list) -> None:
        """The pipeline's ONE blocking edge: force the recorded program's
        tokens and replay the sweep strictly from the DISPATCH RECORD —
        never from current slot state. Rows whose recorded request
        already retired (an earlier sweep saw its last token) are
        skipped: their slot and mirrors may belong to a newer admission.
        The replayed retirement rule is the device's own (eos match or
        emitted ≥ max_new), so host and carry agree exactly."""
        if rec is None:
            return
        t0 = time.perf_counter() if self._goodput is not None else 0.0
        ys = np.asarray(rec["ys"])
        if self._goodput is not None:
            self._goodput.consume_wait(time.perf_counter() - t0)
        if rec["qerr"] is not None:
            self._note_qerr(rec["qerr"])
        now = time.monotonic()
        n = self.scfg.slots
        emitted_total, pos_sum = 0, 0.0
        if rec["kind"] == "micro":
            for slot in range(n):
                if not rec["cand"][slot]:
                    continue
                req = rec["reqs"][slot]
                if req is None or req.status != RUNNING:
                    continue
                for j in range(ys.shape[0]):
                    tok = int(ys[j, slot])
                    req.tokens.append(tok)
                    emitted_total += 1
                    pos_sum += float(rec["pos0"][slot]) + j
                    self._positions[slot] += 1
                    self._last_token[slot] = tok
                    if req.first_token_t is None:
                        req.first_token_t = now
                        self._obs_first_token(req)
                    if req.finished:
                        break
                if req.finished:
                    self._retire(slot)
                    finished.append(req.rid)
            if self._goodput is not None:
                self._goodput.work_counts(emitted_total, pos_sum)
                self._goodput.emitted(emitted_total)
            return
        for slot in rec["decode"]:
            req = rec["reqs"][slot]
            if req is None or req.status != RUNNING:
                continue
            tok = int(ys[slot])
            req.tokens.append(tok)
            emitted_total += 1
            self._positions[slot] += 1
            self._last_token[slot] = tok
            if req.first_token_t is None:
                req.first_token_t = now
                self._obs_first_token(req)
            if req.finished:
                self._retire(slot)
                finished.append(req.rid)
        for slot, off, c, pos, completing in rec["rows"]:
            req = rec["reqs"][slot]
            if req is None or req.status != RUNNING:
                continue
            self._positions[slot] = pos + c
            self.prefill_chunks += 1
            if not completing:
                continue
            self.prefills += 1               # prompt complete: first token
            tok = int(ys[n + off + c - 1])
            req.tokens.append(tok)
            emitted_total += 1
            self._last_token[slot] = tok
            if req.first_token_t is None:
                req.first_token_t = now
                self._obs_first_token(req)
            if req.finished:
                self._retire(slot)
                finished.append(req.rid)
        if self._goodput is not None:
            self._goodput.work_counts(*rec["work"])
            self._goodput.emitted(emitted_total)

    # -- scheduler internals -------------------------------------------------

    def _prefilling(self, slot: int) -> bool:
        req = self._slots[slot]
        return req is not None and \
            int(self._positions[slot]) < int(self._prefill_target[slot])

    def _prefilling_planned(self, slot: int) -> bool:
        """Prefilling as of the last DISPATCH (overlap mode): the chunk
        program that completes this slot's prompt may still be in
        flight, but no further prefill work remains to plan."""
        req = self._slots[slot]
        return req is not None and \
            int(self._planned_pos[slot]) < int(self._prefill_target[slot])

    def _context_ids(self, req: Request) -> np.ndarray:
        return np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])

    def _sample_one(self, req: Request, logits) -> int:
        tok = self._gp_timed(
            self._prefill_sample_fn,
            logits, jnp.asarray([req.temperature], jnp.float32),
            jnp.asarray([req.top_p], jnp.float32), req.key,
            jnp.int32(len(req.tokens)))
        return int(tok[0])

    def _reserve(self, n: int, spare: int) -> Optional[List[int]]:
        """``n`` blocks with ``spare`` more left free afterwards, evicting
        refcount-0 cached blocks (LRU) if the free list alone can't cover
        it. None (nothing taken) when even eviction can't."""
        shortfall = n + spare - self.allocator.available
        if shortfall > 0 and self._pcache is not None:
            self._pcache.evict(shortfall)
        if self.allocator.available < n + spare:
            return None
        return self.allocator.alloc(n)

    def _fleet_import(self, ctx: np.ndarray, have: int) -> List[int]:
        """Import the consecutive full-block tail of ``ctx`` that the
        local prefix cache missed (``have`` = local hit depth in blocks)
        from the tiers below HBM — host RAM first, then the fleet KV
        plane. Any failure — index hole, stale entry (missing object),
        torn payload, pool pressure — STOPS the import and the remaining
        tail prefills locally; a wrong stream is impossible because a
        payload is only adopted under the hash naming its exact token
        prefix. Returns the imported physical blocks in chain order (the
        caller appends them to its cached-prefix list; their allocation
        refcount is the admitting slot's reference). ``hit_blocks``
        counts imports from EITHER rung; ``stats()['tiering']``'s
        promoted_blocks is the host-resident subset."""
        hashes = chain_block_hashes(ctx, self.scfg.block_size)
        want = hashes[have:]
        if not want:
            return []
        t0 = time.perf_counter()
        imported = self._import_hash_chain(want)
        self.fleet_hit_blocks += len(imported)
        self.fleet_miss_blocks += len(want) - len(imported)
        if imported:
            self.fleet_import_requests += 1
            if self._h_kv_import is not None:
                self._h_kv_import.observe(time.perf_counter() - t0)
        return imported

    def _import_hash_chain(self, want: List[bytes]) -> List[int]:
        """The fetch+write+adopt core shared by admission imports and
        prefetch-ahead hints: resolve ``want`` (consecutive chained
        hashes) down the hierarchy — host tier first (RAM beats the
        bucket by orders of magnitude), then the fleet index for the
        remaining tail — fetch each payload, write the whole chain into
        freshly allocated local blocks in ONE batched dispatch, and
        adopt each under its hash. Returns the imported blocks (each at
        allocation refcount 1 AND cache-retained — the caller keeps the
        ref for a slot table, or drops it to leave the block cached).
        Chains clamp to ``max_blocks_per_slot`` — the batched write's
        fixed pad width (admission chains can never exceed it; a
        router-supplied prefetch hint CAN, e.g. from a pool with a
        larger max_len, and must degrade to a shorter import, not an
        error after blocks were already allocated)."""
        want = want[:self.scfg.max_blocks_per_slot]
        if not want:
            return []
        payloads: List[Tuple[bytes, List[dict]]] = []
        if self._host_tier is not None:
            # Promotion proper: the consecutive leading run whose bytes
            # are host-resident. A mid-chain miss falls through to the
            # fleet below — the chain stays consecutive either way.
            for h in want:
                data = self._host_tier.get(h)
                if data is None:
                    break
                values = split_block_bytes(data, self.cfg, self.scfg)
                if values is None:
                    break         # foreign payload → try the next rung
                payloads.append((h, values))
        n_promoted = len(payloads)
        rest = want[n_promoted:]
        if self._fleet is not None and rest:
            try:
                n_hit = self._fleet.lookup_chain(rest)
            except OSError:
                n_hit = 0
            for h in rest[:n_hit]:
                data = self._fleet.fetch(h)
                if data is None:
                    break         # stale index entry → local prefill
                values = split_block_bytes(data, self.cfg, self.scfg)
                if values is None:
                    break         # foreign/torn payload → local prefill
                payloads.append((h, values))
        imported: List[int] = []
        for _ in payloads:
            got = self._reserve(1, 0)
            if got is None:
                break             # pool pressure → prefill what's left
            imported.append(got[0])
        payloads = payloads[:len(imported)]
        if imported:
            # ONE padded dispatch writes the whole chain; pad rows target
            # the scratch block (harmless by definition). The pad width
            # is FIXED at max_blocks_per_slot (no chain can be longer),
            # so exactly one import program ever compiles — a varying
            # width would recompile mid-traffic and stall every running
            # slot for the compile, the exact tail latency the batched
            # write exists to avoid.
            n = len(imported)
            pad = self.scfg.max_blocks_per_slot
            dsts = np.full((pad,), SCRATCH_BLOCK, np.int32)
            dsts[:n] = imported
            stacked = [
                {name: jnp.asarray(np.concatenate(
                    [np.stack([p[1][li][name] for p in payloads]),
                     np.zeros((pad - n,) + leaf.shape, leaf.dtype)])
                    if pad > n else
                    np.stack([p[1][li][name] for p in payloads]))
                 for name, leaf in layer.items()}
                for li, layer in enumerate(payloads[0][1])]
            self.pools = self._gp_timed(
                self._import_blocks_fn, self.pools, jnp.asarray(dsts),
                stacked)
            for (h, _), block in zip(payloads, imported):
                self._pcache.adopt(h, block)
        self.promoted_blocks += min(n_promoted, len(imported))
        return imported

    def prefetch_chain(self, hashes: List[bytes]) -> int:
        """Prefetch-ahead import (the router's next-turn hint): pull a
        published chain into the LOCAL prefix cache before any request
        references it, so the session's next turn admits on a warm cache
        instead of paying the fleet fetch on its TTFT path. Leading
        hashes already cached are skipped (consecutive — a mid-chain
        hole stops the prefetch exactly like an index hole stops an
        admission import); imported blocks are left cache-retained at
        refcount 0, the same state a released cached block sits in, so
        pool pressure can evict them LRU like anything else cached.
        Best-effort by contract: every failure arm degrades to a smaller
        (possibly empty) prefetch, never an error to the hinter. With a
        host tier attached the same hint warms HBM from host RAM
        (host→HBM promotion ahead of need) — the bucket→HBM prefetch
        generalized down the hierarchy."""
        if (self._fleet is None and self._host_tier is None) \
                or self._pcache is None or not hashes:
            return 0
        have = 0
        for h in hashes:
            if not self._pcache.has(h):
                break
            have += 1
        imported = self._import_hash_chain(list(hashes[have:]))
        for block in imported:
            # adopt() retained the block; dropping the allocation ref
            # leaves it cached at ref 0 (off the free list, evictable).
            self.allocator.decref(block)
        self.fleet_prefetch_blocks += len(imported)
        return len(imported)

    # lint: begin-tier-migrate — the demote STAGING path: nothing
    # between these markers may block on the device (block_until_ready
    # / device_get / np.asarray of a device value). Staging runs on the
    # step loop with a program in flight; the bytes force at the
    # consume edge (_finalize_demotions), where the host is already
    # blocked on the device. `make lint` (tier-1) enforces it, same
    # discipline as the overlap-dispatch region.

    def _demote_pass(self, limit: int = 8) -> None:
        """The NON-BLOCKING half of demotion: pick up to ``limit`` of
        the prefix cache's coldest retained ref-0 blocks (eviction's
        next victims — an idle session's blocks join this set the step
        its request releases) and stage their device slices toward the
        host tier. No readback happens here: the staged reads enqueue
        behind the in-flight program and force one consume edge later.
        A block whose bytes are ALREADY host-resident skips the copy
        and demotes immediately — re-demoting a resurrected block is
        free because its host bytes never left."""
        if self._host_tier is None or self._pcache is None:
            return
        budget = limit - len(self._pending_demotions)
        if budget <= 0:
            return
        for h, block in self._pcache.cold_entries(budget):
            if h in self._host_tier:
                self.allocator.mark_demoted(block)
                self.demoted_blocks += 1
                continue
            self._pending_demotions.append(
                (h, block, stage_block_arrays(self.pools, block)))

    # lint: end-tier-migrate

    def _finalize_demotions(self) -> None:
        """The BLOCKING half of demotion: force each staged entry to
        bytes, hand it to the host tier (which LRU-spills past its
        budget into the fleet bucket), and mark the HBM copy demoted —
        eviction-preferred, since its bytes now survive reclaim. Runs
        right AFTER the consume edge's program wait: the staged reads
        enqueued behind that program, so the forces find materialized
        buffers and cost ~nothing; in sync mode the device is idle
        after the step's readback and blocking is the normal state.
        Entries resurrected (incref'd) or evicted-and-recycled since
        staging are skipped — the ``cached_block`` identity check makes
        a wrong mark impossible (content addressing already makes a
        wrong PAYLOAD impossible)."""
        if not self._pending_demotions:
            return
        pending, self._pending_demotions = self._pending_demotions, []
        for h, block, staged in pending:
            if self._pcache.cached_block(h) != block \
                    or self.allocator.refcount(block) != 0:
                continue          # resurrected or recycled mid-flight
            self._host_tier.put(h, staged_block_to_bytes(staged))
            self.allocator.mark_demoted(block)
            self.demoted_blocks += 1

    def stage_cached_blocks(self, limit: int = 16,
                            skip=()) -> List[Tuple[str, List]]:
        """The NON-BLOCKING half of the publish path: up to ``limit`` hot
        ref-0 retained prefix-cache blocks as (hash hex, staged device
        slices) — no readback happens here, so the call is safe on the
        engine's critical path even with a program in flight (the slices
        enqueue behind it; pools donated to LATER programs reuse their
        buffers only after these reads complete). Retained ref-0 blocks
        are frozen — no slot can write them without a COW copy — and
        never sit in a dispatched table, so the staged values are exact.
        Force each entry with ``cache.staged_block_to_bytes`` OFF the
        critical path (a publisher thread, or after the next dispatch)."""
        if self._pcache is None:
            return []
        out: List[Tuple[str, List]] = []
        for h, block in self._pcache.hot_entries():
            if len(out) >= limit:
                break
            hash_hex = h.hex()
            if hash_hex in skip:
                continue
            out.append((hash_hex, stage_block_arrays(self.pools, block)))
        return out

    def export_cached_blocks(self, limit: int = 16,
                             skip=()) -> List[Tuple[str, bytes]]:
        """The publish half of the fleet KV plane: up to ``limit`` hot
        ref-0 retained prefix-cache blocks as (hash hex, payload bytes),
        hottest first, skipping hashes in ``skip`` (the client's
        already-published set). The blocking stage+force composition of
        :meth:`stage_cached_blocks` — callers that care about the
        engine's dispatch cadence stage on the critical path and force
        elsewhere; this remains the simple synchronous form."""
        return [(hash_hex, staged_block_to_bytes(staged))
                for hash_hex, staged in self.stage_cached_blocks(
                    limit=limit, skip=skip)]

    def _admit(self, admitted: list, finished: list) -> None:
        if self.scfg.prefill == "chunked":
            self._admit_chunked(admitted)
        else:
            self._admit_bucketed(admitted, finished)

    def _admit_chunked(self, admitted: list) -> None:
        """Assign a free slot + blocks; prompt ingestion happens across
        the following steps' chunk programs. At most ``prefill_slots``
        slots prefill at a time (default 1 — the historical one-slot
        behavior): admitting slots SHARE the step's ``chunk_tokens``
        budget oldest-first, so an admission burst packs several prompt
        tails into one program instead of serializing one slot per step
        — the admission-p99 lever (ISSUE 16)."""
        bs = self.scfg.block_size
        # In overlap mode the gate reads PLANNED positions: a completing
        # chunk already dispatched counts as done even though its sweep
        # lands next step — otherwise every admission would wait one
        # extra step for the mirror update and a burst would serialize
        # at half rate.
        prefilling = (self._prefilling_planned if self._overlap
                      else self._prefilling)
        while self._queue:
            if sum(prefilling(i) for i in range(self.scfg.slots)) \
                    >= self.scfg.prefill_slots:
                return
            slot = next(
                (i for i, r in enumerate(self._slots) if r is None), None)
            if slot is None:
                return
            pick = self._next_admit_index()
            req = self._queue[pick]
            # A resumed request's already-emitted tokens are CONTEXT here:
            # ingested through the same chunk programs as the prompt, then
            # generation continues at token index len(req.tokens).
            ctx = self._context_ids(req)
            plen = len(ctx)
            cached: List[int] = []
            # Adapter-bearing requests SKIP the prefix cache both ways:
            # their KV is adapter-dependent from layer 1 on (the LoRA
            # delta feeds the next layer's projections), so base-model
            # blocks must never seed them nor their blocks the cache.
            if self._pcache is not None and req.adapter_id is None:
                cached = self._pcache.lookup(ctx)          # increfs
                if self._fleet is not None \
                        or self._host_tier is not None:
                    # The blocks the LOCAL cache missed may exist in the
                    # fleet: import them by content hash instead of
                    # prefilling them (each imported block lands in the
                    # local cache too, so the fleet is consulted once per
                    # prefix, not once per request).
                    cached += self._fleet_import(ctx, len(cached))
            # The last prompt token is ALWAYS recomputed (its logits seed
            # the first sample), so a whole-prompt hit caps at plen - 1 —
            # and that one write lands inside the final shared block, the
            # copy-on-write case (cow below).
            cached_len = min(len(cached) * bs, plen - 1)
            cow = 1 if cached_len < len(cached) * bs else 0
            need = self.scfg.blocks_for(plen) - len(cached)
            got = self._reserve(need + cow,
                                1 if self.n_active else 0)
            if got is None:
                for b in cached:
                    self.allocator.decref(b)
                return
            del self._queue[pick]
            table = np.zeros((self.scfg.max_blocks_per_slot,), np.int32)
            table[:len(cached)] = cached
            if need:
                table[len(cached):len(cached) + need] = got[:need]
            if cow:
                # COW the final shared block: private copy, rewire the
                # table, drop our ref on the donor (it stays cached, its
                # bytes untouched — pinned by the property test).
                src = int(table[cached_len // bs])
                dst = got[need]
                self.pools = self._gp_timed(
                    self._copy_block_fn,
                    self.pools, jnp.int32(src), jnp.int32(dst))
                table[cached_len // bs] = dst
                self.allocator.decref(src)
                self.cow_copies += 1
            if cached:
                self.prefix_hit_requests += 1
            self.prefix_hit_blocks += len(cached)
            self.prefix_miss_blocks += plen // bs - len(cached)
            self.prefix_tokens_saved += cached_len
            req.status = RUNNING
            self._slots[slot] = req
            self._admit_counter += 1
            self._admit_seq[slot] = self._admit_counter
            self._slot_keys[slot] = np.asarray(req.key, np.uint32)
            self._tables[slot] = table
            self._positions[slot] = cached_len
            self._prefill_target[slot] = plen
            self._last_token[slot] = 0
            self._draft_pos[slot] = 0
            self._bind_adapter(slot, req)
            if self._overlap:
                # The slot's planned device state restarts with the new
                # occupant: any still-unswept program dispatched against
                # the previous request runs this row dead (record-skip
                # discipline), so its stale planned advance must not
                # leak into the new request's prefill plan.
                self._planned_pos[slot] = cached_len
                self._planned_emitted[slot] = len(req.tokens)
            admitted.append(req.rid)
            self._obs_admit(req, cached_tokens=cached_len)

    def _admit_bucketed(self, admitted: list, finished: list) -> None:
        """Legacy PR 5 admission: the whole prompt (plus any resumed-token
        context) through one padded prefill program, first token sampled
        immediately."""
        while self._queue:
            slot = next(
                (i for i, r in enumerate(self._slots) if r is None), None)
            if slot is None:
                return
            pick = self._next_admit_index()
            req = self._queue[pick]
            ctx = self._context_ids(req)
            need = self.scfg.blocks_for(len(ctx))
            # Keep one spare so the running set can cross its next block
            # boundary without an instant preemption; an idle engine admits
            # with no spare (a solo request can always grow into the pool
            # its own submit-time validation reserved).
            blocks = self._reserve(need, 1 if self.n_active else 0)
            if blocks is None:
                return
            del self._queue[pick]
            self._obs_admit(req)
            bucket = self.scfg.bucket_for(len(ctx))
            table = np.zeros((self.scfg.max_blocks_per_slot,), np.int32)
            table[:need] = blocks
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :len(ctx)] = ctx
            self._bind_adapter(slot, req)
            logits = self._run_program(
                self._prefill_fn,
                self._model_params(self._slot_lora_blocks[slot][None],
                                   self._slot_lora_scale[slot:slot + 1],
                                   gen=req.generation),
                jnp.asarray(padded),
                jnp.int32(len(ctx)), jnp.asarray(table))
            if self._quantized:
                self.quantized_block_writes += need
            if self._goodput is not None:
                self._goodput.work_span(len(ctx))
                self._goodput.emitted(1)
            self.prefills += 1
            first = self._sample_one(req, logits)
            now = time.monotonic()
            req.status = RUNNING
            req.tokens.append(first)
            if req.first_token_t is None:
                req.first_token_t = now
                self._obs_first_token(req)
            self._slots[slot] = req
            self._admit_counter += 1
            self._admit_seq[slot] = self._admit_counter
            self._slot_keys[slot] = np.asarray(req.key, np.uint32)
            self._tables[slot] = table
            self._positions[slot] = len(ctx)
            self._prefill_target[slot] = len(ctx)
            self._last_token[slot] = first
            self._draft_pos[slot] = 0
            admitted.append(req.rid)
            if req.finished:
                self._retire(slot)
                finished.append(req.rid)

    def _next_admit_index(self) -> int:
        """Slack-ordered admission (class-then-EDF, the router pump's
        key): the queue index to admit next — higher protection class
        first, then earliest deadline, deadline-less requests after
        every deadlined one of their class, FIFO among equals. Class
        outranks the deadline because the ladder makes degraded
        best_effort work CHEAP — same-deadline cheap work would
        otherwise tie with premium and win by arrival, starving the
        class the brownout exists to protect. With no SLA fields in
        the queue every key ties and the min is index 0: exactly the
        historical FIFO, bit for bit (admission order cannot change
        token values anyway — sampling is keyed by (key, index) — but
        the no-SLA schedule itself is also preserved). A preempted
        request re-queued at the head keeps winning ties at index 0."""
        return min(range(len(self._queue)),
                   key=lambda i: (
                       -class_rank(getattr(
                           self._queue[i], "slo_class", DEFAULT_CLASS)),
                       self._queue[i].deadline is None,
                       self._queue[i].deadline or 0.0, i))

    def _ensure_blocks(self, widths: Optional[np.ndarray] = None) -> None:
        """Every active slot gets blocks covering its next ``widths[i]``
        writes (default 1; a prefill chunk or a speculative span needs
        more) — evicting refcount-0 cached blocks first, then preempting
        the least-protected, most-slack, youngest running request
        (requeued at the head, restart-from-scratch recompute) when the
        pool is truly dry."""
        for slot in sorted(range(self.scfg.slots),
                           key=lambda i: self._admit_seq[i]):
            req = self._slots[slot]
            if req is None:
                continue
            w = int(widths[slot]) if widths is not None else 1
            if not w:                     # spec-held row: nothing to write
                continue
            pos = int(self._positions[slot])
            last_block = (pos + max(w, 1) - 1) // self.scfg.block_size
            preempted_self = False
            for block_i in range(pos // self.scfg.block_size,
                                 last_block + 1):
                while self._tables[slot, block_i] == SCRATCH_BLOCK:
                    got = self._reserve(1, 0)
                    if got is not None:
                        self._tables[slot, block_i] = got[0]
                        break
                    # Victim order: lowest protection class first, then
                    # most remaining slack (deadline-less = infinite
                    # slack), then the historical youngest-slot rule as
                    # the tiebreak. All-default requests (standard, no
                    # deadline) tie on the first two terms, so the pick
                    # reduces exactly to the youngest rule.
                    victim = max(
                        (i for i, r in enumerate(self._slots)
                         if r is not None),
                        key=lambda i: (
                            -class_rank(self._slots[i].slo_class),
                            float("inf")
                            if self._slots[i].deadline is None
                            else self._slots[i].deadline,
                            self._admit_seq[i]))
                    self._preempt(victim)
                    if victim == slot:
                        preempted_self = True
                        break  # this slot itself was youngest — requeued
                    if self.n_active <= 1 and self.allocator.available == 0 \
                            and (self._pcache is None
                                 or self._pcache.evict(1) == 0):
                        raise RuntimeError(
                            "KV pool too small for a single request — "
                            "raise n_blocks")
                if preempted_self:
                    break

    def _preempt(self, slot: int) -> None:
        req = self._slots[slot]
        req.preemptions += 1
        self.preemption_count += 1
        req.status = QUEUED
        self._obs_interrupt(req, "preempted")
        if self._goodput is not None:
            # The rolled-back tokens were emitted work the recompute
            # repeats — the goodput ratio's preemption discount.
            self._goodput.wasted_preempt(len(req.tokens) - req.resume_from)
        # Release BEFORE clearing tokens: _release registers full blocks
        # with the prefix cache under the ids that produced their KV
        # (prompt + generated so far), so the hash list and the block list
        # must line up. The keyed sampling stream reproduces the same
        # tokens on re-admission; TTFT restarts honestly. A resumed
        # request rolls back only to its imported prefix — those tokens
        # are context from another engine's life, not this engine's to
        # regenerate.
        self._release(slot)
        del req.tokens[req.resume_from:]
        req.first_token_t = None
        self._queue.appendleft(req)
        self._obs_queue(req, requeued=True)

    # -- fused steps ---------------------------------------------------------

    def _quant_layout(self, tables: np.ndarray, positions: np.ndarray,
                      valid: np.ndarray) -> Tuple:
        """Host half of int8 append for one fused step: the deduped list
        of physical blocks the step writes (``touched``), each block's
        valid-token count after the step (``filled`` — rows past it are
        garbage the requantize zeroes), and every token's (touched-index,
        in-block offset) pair. Dedup matters: packed-chunk rows share one
        slot's table, so several rows append into the SAME block — the
        staging scatter in :func:`quantized_append` lands them at
        distinct offsets of one staged copy, which a per-row write could
        not do. Invalid tokens point at the trailing pad entry (scratch,
        ``filled`` 0). ``positions``/``valid``: (rows, w); ``tables``:
        (rows, max_blocks)."""
        bs = self.scfg.block_size
        rows, w = positions.shape
        T = rows * w + 1
        pos = np.asarray(positions, np.int64).reshape(-1)
        val = np.asarray(valid, bool).reshape(-1)
        # Physical block each token writes (invalid rows index harmlessly
        # through position 0; the `val` mask drops them below). Fully
        # vectorized — this runs before EVERY quantized fused step, so a
        # Python per-token loop would sit on the latency path the kernel
        # exists to shorten.
        blocks = np.asarray(tables)[np.arange(rows).repeat(w), pos // bs]
        uniq, inv = np.unique(blocks[val], return_inverse=True)
        touched = np.zeros(T, np.int32)
        touched[:len(uniq)] = uniq
        filled = np.zeros(T, np.int32)
        np.maximum.at(filled, inv, pos[val] % bs + 1)
        wt = np.full(rows * w, T - 1, np.int32)
        wt[val] = inv
        wo = np.zeros(rows * w, np.int32)
        wo[val] = pos[val] % bs
        self.quantized_block_writes += len(uniq)
        return (jnp.asarray(touched), jnp.asarray(filled),
                jnp.asarray(wt), jnp.asarray(wo))

    def _note_qerr(self, qerr) -> None:
        """Debug mode tracks the worst per-element write-quantization
        error actually observed (an extra scalar readback per step —
        debug-only on purpose); outside debug the device value is simply
        never read back."""
        if self.debug:
            self.max_quant_error = max(self.max_quant_error, float(qerr))

    def _run_program(self, fn, *args, qa=None):
        """Dispatch one fused step program against the engine pools: the
        ONE place that splices the int8 write layout (``qa``; None for
        programs that derive it in-program, like bucketed prefill) before
        the donated pools and peels the quantized variants' extra
        max-quant-error output. Returns the program's leading output."""
        t0 = time.perf_counter() if self._goodput is not None else 0.0
        if self._quantized:
            if qa is not None:
                out, self.pools, qerr = fn(*args, qa, self.pools)
            else:
                out, self.pools, qerr = fn(*args, self.pools)
            self._note_qerr(qerr)
        else:
            out, self.pools = fn(*args, self.pools)
        if self._goodput is not None:
            self._goodput.program(time.perf_counter() - t0)
        return out

    def _all_greedy(self) -> bool:
        return all(r is None or r.temperature == 0 for r in self._slots)

    def _temps_tops(self):
        """Per-slot (temperature, top_p) arrays for the sampling programs
        (empty slots: greedy/identity — their outputs are discarded)."""
        temps = np.array(
            [r.temperature if r else 0.0 for r in self._slots], np.float32)
        tops = np.array(
            [r.top_p if r else 1.0 for r in self._slots], np.float32)
        return temps, tops

    def _gen_ok(self, req: Optional[Request]) -> bool:
        """Does this slot participate in the CURRENT dispatch? A slot
        is masked out when step() is partitioning by generation and the
        request is pinned to a different one."""
        return req is not None and (self._gen_filter is None
                                    or req.generation == self._gen_filter)

    def _dispatch_gen(self) -> int:
        """Which generation's weights the next program runs under: the
        partition being dispatched when step() is mid-partition, else
        the single generation with live streams (post-swap streams
        still draining), else the active generation."""
        if self._gen_filter is not None:
            return self._gen_filter
        live = {g for g, c in self._gen_streams.items() if c}
        if len(live) == 1:
            return next(iter(live))
        return self.generation

    def _model_params(self, blocks=None, scales=None,
                      gen: Optional[int] = None) -> Params:
        """The params pytree a fused program closes over: the dispatch
        generation's weights, plus — when LoRA is on — the adapter pool
        and the per-row gather tables under the ``"lora"`` key (the
        model fns read it with ``params.get("lora")``, so a LoRA-free
        engine passes the identical pytree it always did and keeps its
        bit-exact pins). ``blocks``/``scales`` default to the per-slot
        tables; packed programs pass their own per-row expansion."""
        base = self._gen_params[self._dispatch_gen() if gen is None
                                else gen]
        if not self._lora_on:
            return base
        if blocks is None:
            blocks = self._slot_lora_blocks
        if scales is None:
            scales = self._slot_lora_scale
        if not np.asarray(scales).any():
            # No row in this dispatch carries an adapter (every slot is
            # scratch-bound, scale 0) — drop the LoRA branch entirely
            # and run the LoRA-free program. Bit-safe: scale-0 rows
            # contribute exactly 0.0 either way. This is what pins the
            # adapter-less overhead at ~0: a LoRA-ENABLED engine serving
            # only base traffic dispatches the same program a LoRA-free
            # engine does, paying for the pool only when a registered
            # adapter is actually in the batch.
            return base
        return {**base, "lora": (self._lora_pool,
                                 jnp.asarray(blocks, jnp.int32),
                                 jnp.asarray(scales, jnp.float32))}

    def _decode(self, finished: list) -> None:
        self._ensure_blocks()
        active = np.array([self._gen_ok(r) for r in self._slots])
        if not active.any():
            return
        positions = np.where(active, self._positions, 0)
        qa = (self._quant_layout(self._tables, positions[:, None],
                                 active[:, None])
              if self._quantized else None)
        if self._all_greedy():
            toks = self._run_program(
                self._decode_greedy_fn, self._model_params(),
                jnp.asarray(self._last_token), jnp.asarray(positions),
                jnp.asarray(self._tables), jnp.asarray(active), qa=qa)
        else:
            temps, tops = self._temps_tops()
            ngen = np.array([len(r.tokens) if r else 0 for r in self._slots],
                            np.int32)
            toks = self._run_program(
                self._decode_fn, self._model_params(),
                jnp.asarray(self._last_token), jnp.asarray(positions),
                jnp.asarray(self._tables), jnp.asarray(active),
                jnp.asarray(temps), jnp.asarray(tops),
                jnp.asarray(self._slot_keys), jnp.asarray(ngen), qa=qa)
        self.decode_steps += 1
        if self._goodput is not None:
            # positions is masked to 0 at inactive rows, so its plain sum
            # is the active rows' position sum.
            n_act = int(active.sum())
            self._goodput.work_counts(n_act, float(positions.sum()))
            self._goodput.emitted(n_act)
        toks = np.asarray(toks)
        now = time.monotonic()
        for slot, req in enumerate(self._slots):
            if not self._gen_ok(req):
                continue
            tok = int(toks[slot])
            req.tokens.append(tok)
            if req.first_token_t is None:
                req.first_token_t = now
                self._obs_first_token(req)
            self._positions[slot] += 1
            self._last_token[slot] = tok
            if req.finished:
                self._retire(slot)
                finished.append(req.rid)

    def _micro_spans(self) -> np.ndarray:
        """Per-slot token span of the next micro-step: min(micro_k,
        remaining max_new) for running slots, 0 for empty ones — both the
        block-reservation widths and the in-program retirement limits."""
        spans = np.zeros((self.scfg.slots,), np.int32)
        for i, req in enumerate(self._slots):
            if self._gen_ok(req):
                spans[i] = min(self.scfg.micro_k,
                               req.max_new_tokens - len(req.tokens))
        return spans

    def _micro_quant_layout(self, positions: np.ndarray,
                            spans: np.ndarray) -> Tuple:
        """Stacked per-iteration write layouts for a quantized micro-step:
        iteration j's layout is exactly the K=1 step's at ``positions +
        j`` over the slots whose span covers j — laid out as if every
        such slot lives through its span. A slot that retires on eos
        mid-span diverges from that assumption only inside its OWN
        exclusively-owned blocks: the garbage rows land past its last
        valid position, the partial block holding them is never
        registered with the prefix cache and frees at the host sweep, so
        no bytes any other reader sees differ from a K=1 schedule."""
        parts = [self._quant_layout(
            self._tables, (positions + j)[:, None],
            (spans > j)[:, None]) for j in range(self.scfg.micro_k)]
        return tuple(jnp.stack([p[i] for p in parts])
                     for i in range(4))

    def _micro_decode(self, finished: list) -> None:
        """K-token fused micro-step (ROADMAP item 4): ONE dispatch runs
        ``micro_k`` sequential decode iterations with in-program
        eos/length retirement; the host sweeps the (K, slots) token
        block ONCE — retire, stats, and the goodput charge all happen
        per micro-step, not per token. Token streams are bit-identical
        (greedy) / key-identical (sampled) to K=1: each iteration is the
        same arithmetic at the same positions with the same keys, and a
        retired slot's remaining iterations are masked exactly like
        inactive slots (writes land in scratch, outputs unread)."""
        self._ensure_blocks(self._micro_spans())
        if not self.n_active:
            return
        spans = self._micro_spans()       # preemption may have freed slots
        active = spans > 0
        positions = np.where(active, self._positions, 0)
        eos = np.array(
            [r.eos_token if r is not None and r.eos_token is not None
             else -1 for r in self._slots], np.int32)
        qa = (self._micro_quant_layout(positions, spans)
              if self._quantized else None)
        if self._all_greedy():
            toks = self._run_program(
                self._micro_greedy_fn, self._model_params(),
                jnp.asarray(self._last_token), jnp.asarray(positions),
                jnp.asarray(self._tables), jnp.asarray(active),
                jnp.asarray(spans), jnp.asarray(eos), qa=qa)
        else:
            temps, tops = self._temps_tops()
            ngen = np.array(
                [len(r.tokens) if r else 0 for r in self._slots], np.int32)
            toks = self._run_program(
                self._micro_sample_fn, self._model_params(),
                jnp.asarray(self._last_token), jnp.asarray(positions),
                jnp.asarray(self._tables), jnp.asarray(active),
                jnp.asarray(spans), jnp.asarray(eos), jnp.asarray(temps),
                jnp.asarray(tops), jnp.asarray(self._slot_keys),
                jnp.asarray(ngen), qa=qa)
        self.decode_steps += 1
        self.micro_steps += 1
        toks = np.asarray(toks)           # (micro_k, slots)
        now = time.monotonic()
        emitted_total, pos_sum = 0, 0.0
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            for j in range(int(spans[slot])):
                tok = int(toks[j, slot])
                req.tokens.append(tok)
                emitted_total += 1
                pos_sum += float(positions[slot]) + j
                self._positions[slot] += 1
                self._last_token[slot] = tok
                if req.first_token_t is None:
                    req.first_token_t = now
                    self._obs_first_token(req)
                if req.finished:
                    break
            if req.finished:
                self._retire(slot)
                finished.append(req.rid)
        if self._goodput is not None:
            # One dispatch (already counted by _run_program) did
            # emitted_total tokens of work — dispatches_per_token and
            # MFU stay honest at K > 1 because the charge is per VALID
            # token, same convention as the per-token step's.
            self._goodput.work_counts(emitted_total, pos_sum)
            self._goodput.emitted(emitted_total)

    def _chunk_step(self, finished: list) -> None:
        """ONE fused iteration: the admitting slot ingests its next prompt
        chunk (≤ chunk_tokens positions) while every decode-phase slot
        advances its token — the Sarathi fold that bounds running slots'
        inter-token stall by one chunk instead of one whole prompt.

        The step is TOKEN-PACKED: the program is the plain decode step at
        batch ``slots + chunk_tokens`` — rows 0..slots-1 are the decode
        slots (one token each) and rows slots.. are the admitting slots'
        chunks (oldest-admission-first under the one shared budget when
        ``prefill_slots > 1``), one token per row, each row carrying its
        owning slot's block table. The
        per-step token budget is therefore exactly slots + chunk_tokens
        positions of compute (a padded (slots, chunk) layout would pay
        slots × chunk — width for every row), and the program is the SAME
        jitted decode function, merely specialized at the packed batch.
        In-chunk causality needs no extra machinery: every row scatters
        its k/v before any row gathers, and the position mask gives each
        chunk token exactly its predecessors."""
        n, W = self.scfg.slots, self.scfg.chunk_tokens

        def chunk_widths() -> np.ndarray:
            # With spec on, decode rows are HELD here (width 0) — the spec
            # round this same scheduler step advances them instead, keeping
            # every sampled token on the position-keyed spec streams.
            # Prefilling slots SHARE the one chunk budget oldest-first
            # (prefill_slots > 1): a slot the budget can't reach this
            # step simply waits — never more than W prompt positions of
            # work ride one program.
            w = np.zeros((n,), np.int32)
            budget = W
            for i in sorted(range(n), key=lambda j: self._admit_seq[j]):
                req = self._slots[i]
                if not self._gen_ok(req):
                    continue
                pos = int(self._positions[i])
                target = int(self._prefill_target[i])
                if pos < target:
                    c = min(budget, target - pos)
                    w[i] = c
                    budget -= c
                elif not self._spec_on:
                    w[i] = 1
            return w

        self._ensure_blocks(chunk_widths())
        if not self.n_active:
            return
        widths = chunk_widths()           # preemption may have freed slots
        if not widths.max():              # the ingesting slot was preempted
            return
        # Admitting slots with a chunk share this step, oldest first —
        # each one's rows carry ITS OWN block table, so several prompt
        # tails pack into the one program.
        pres = [i for i in sorted(range(n), key=lambda j: self._admit_seq[j])
                if self._prefilling(i) and widths[i]]
        R = n + W
        tokens = np.zeros((R,), np.int32)
        positions = np.zeros((R,), np.int32)
        tables = np.zeros((R, self.scfg.max_blocks_per_slot), np.int32)
        active = np.zeros((R,), bool)
        temps = np.zeros((R,), np.float32)
        tops = np.ones((R,), np.float32)
        keys = np.zeros((R, 2), np.uint32)
        ngen = np.zeros((R,), np.int32)
        tables[:n] = self._tables
        temps[:n], tops[:n] = self._temps_tops()
        for i, req in enumerate(self._slots):
            if req is None or not widths[i] or i in pres:
                continue
            tokens[i] = self._last_token[i]
            positions[i] = self._positions[i]
            active[i] = True
            keys[i], ngen[i] = self._slot_keys[i], len(req.tokens)
        rows = {}                     # slot -> (row offset, c, pos)
        off = 0
        for i in pres:
            req = self._slots[i]
            pos, c = int(self._positions[i]), int(widths[i])
            ctx = self._context_ids(req)       # prompt + any resumed prefix
            tokens[n + off:n + off + c] = ctx[pos:pos + c]
            positions[n + off:n + off + c] = np.arange(pos, pos + c)
            tables[n + off:n + off + c] = self._tables[i]
            active[n + off:n + off + c] = True
            temps[n + off:n + off + c] = req.temperature
            tops[n + off:n + off + c] = req.top_p
            keys[n + off:n + off + c] = self._slot_keys[i]
            # The post-prefill sample rides fold_in(key, len(tokens)) —
            # 0 for a fresh admission (the same draw a bucketed admission
            # makes), the resumed-token count for resume_inflight imports.
            ngen[n + off:n + off + c] = len(req.tokens)
            rows[i] = (off, c, pos)
            off += c
        pos_masked = np.where(active, positions, 0)
        lblocks = lscales = None
        if self._lora_on:
            # Per-row adapter tables for the packed batch: decode rows
            # keep their slot's rows, each chunk row inherits its owning
            # slot's.
            lblocks = np.zeros((R, self.cfg.n_layers), np.int32)
            lscales = np.zeros((R,), np.float32)
            lblocks[:n] = self._slot_lora_blocks
            lscales[:n] = self._slot_lora_scale
            for i, (off, c, _pos) in rows.items():
                lblocks[n + off:n + off + c] = self._slot_lora_blocks[i]
                lscales[n + off:n + off + c] = self._slot_lora_scale[i]
        qa = (self._quant_layout(tables, pos_masked[:, None],
                                 active[:, None])
              if self._quantized else None)
        if self._all_greedy():
            toks = self._run_program(
                self._decode_greedy_fn,
                self._model_params(lblocks, lscales), jnp.asarray(tokens),
                jnp.asarray(pos_masked), jnp.asarray(tables),
                jnp.asarray(active), qa=qa)
        else:
            toks = self._run_program(
                self._decode_fn,
                self._model_params(lblocks, lscales), jnp.asarray(tokens),
                jnp.asarray(pos_masked), jnp.asarray(tables),
                jnp.asarray(active), jnp.asarray(temps),
                jnp.asarray(tops), jnp.asarray(keys),
                jnp.asarray(ngen), qa=qa)
        self.chunk_steps += 1
        if self._goodput is not None:
            self._goodput.work_counts(int(active.sum()),
                                      float(pos_masked.sum()))
        toks = np.asarray(toks)
        now = time.monotonic()
        for i, req in enumerate(self._slots):
            if req is None or not widths[i]:        # empty or spec-held row
                continue
            if i in rows:                           # prefill rows
                off, c, pos = rows[i]
                self._positions[i] = pos + c
                self.prefill_chunks += 1
                if pos + c < int(self._prefill_target[i]):
                    continue                        # mid-prompt: no token
                self.prefills += 1                  # prompt complete
                tok = int(toks[n + off + c - 1])    # last chunk row's sample
            else:                                   # decode row
                self._positions[i] = int(self._positions[i]) + 1
                tok = int(toks[i])
            req.tokens.append(tok)
            if self._goodput is not None:
                self._goodput.emitted(1)
            if req.first_token_t is None:
                req.first_token_t = now
                self._obs_first_token(req)
            self._last_token[i] = tok
            if req.finished:
                self._retire(i)
                finished.append(req.rid)

    # -- speculative decoding ------------------------------------------------

    def _spec_step(self, finished: list) -> None:
        """One speculative round: the draft proposes up to ``spec_k``
        tokens per slot (greedy — its proposal distribution is a point
        mass, so rejection sampling reduces to accept-with-prob-p(d)), ONE
        fused target step scores all k+1 positions, and the host commits
        the accepted prefix + one bonus/replacement token in place."""
        n, k = self.scfg.slots, self.scfg.spec_k
        # De-speculation (the degrade ladder's no-spec rung): cap the
        # draft width at zero INSIDE the spec step rather than falling
        # back to the plain decode path — the NOTE below is why. The
        # saved work is the draft catchup/propose forward passes; the
        # target scoring round (width 1) still carries every stream.
        if not self.spec_enabled:
            k = 0
        bs = self.scfg.block_size

        def live(i: int) -> bool:
            # Mid-prompt slots advance through the chunk program, never a
            # spec round — their row here stays fully masked. Slots pinned
            # to another generation wait for their own partition.
            return self._gen_ok(self._slots[i]) and not self._prefilling(i)

        def eff() -> np.ndarray:
            ke = np.zeros((n,), np.int32)
            for i, req in enumerate(self._slots):
                if not live(i):
                    continue
                remaining = req.max_new_tokens - len(req.tokens)
                # emitted ≤ ke+1 must stay within remaining, and the last
                # scored position must stay inside the slot's table.
                cap = self.scfg.max_blocks_per_slot * bs - 1 \
                    - int(self._positions[i])
                ke[i] = max(0, min(k, remaining - 1, cap))
            return ke

        want = eff()
        self._ensure_blocks(np.asarray(
            [want[i] + 1 if live(i) else 0 for i in range(n)], np.int32))
        if not any(live(i) for i in range(n)):
            return
        k_eff = eff()                      # preemption may have freed slots
        # NOTE: even an all-zero k_eff round scores through the spec
        # program (width 1 valid), so a sampled request's tokens always
        # ride the position-keyed spec streams — never a mix with the
        # plain sampler that would make the stream schedule-dependent.
        if self.spec_enabled:
            self._draft_catchup()
            proposals = self._draft_propose(k_eff)
        else:
            # Disabled: no draft forward passes at all (catchup is
            # self-healing on re-enable — it feeds every token the
            # draft cache missed). k_eff is all zero, so nothing below
            # reads a proposal.
            proposals = np.zeros((n, 1), np.int32)

        tokens = np.zeros((n, k + 1), np.int32)
        positions = np.zeros((n, k + 1), np.int32)
        valid = np.zeros((n, k + 1), bool)
        for i, req in enumerate(self._slots):
            if not live(i):
                continue
            ke, pos = int(k_eff[i]), int(self._positions[i])
            tokens[i, 0] = self._last_token[i]
            tokens[i, 1:ke + 1] = proposals[i, :ke]
            positions[i, :ke + 1] = np.arange(pos, pos + ke + 1)
            valid[i, :ke + 1] = True
        qa = (self._quant_layout(self._tables,
                                 np.where(valid, positions, 0), valid)
              if self._quantized else None)
        if self._all_greedy():
            scored = self._run_program(
                self._spec_greedy_fn, self._model_params(), jnp.asarray(tokens),
                jnp.asarray(positions), jnp.asarray(valid),
                jnp.asarray(self._tables), qa=qa)
            probs = None
            scored = np.asarray(scored)
        else:
            temps, tops = self._temps_tops()
            probs = self._run_program(
                self._spec_probs_fn, self._model_params(), jnp.asarray(tokens),
                jnp.asarray(positions), jnp.asarray(valid),
                jnp.asarray(self._tables), jnp.asarray(temps),
                jnp.asarray(tops), qa=qa)
            probs = np.asarray(probs)
            scored = None
            uniforms = np.asarray(self._gp_timed(
                self._spec_uniform_fn,
                jnp.asarray(self._slot_keys), jnp.asarray(positions)))
        self.spec_rounds += 1
        if self._goodput is not None:
            # positions is 0 outside the valid mask, so the plain sum is
            # the valid entries' position sum.
            self._goodput.work_counts(int(valid.sum()),
                                      float(positions.sum()))
        now = time.monotonic()
        for i, req in enumerate(self._slots):
            if not live(i):
                continue
            ke, pos = int(k_eff[i]), int(self._positions[i])
            if scored is not None or req.temperature == 0:
                row = scored[i] if scored is not None \
                    else probs[i].argmax(-1)
                a = 0
                while a < ke and proposals[i, a] == row[a]:
                    a += 1
                emitted = [int(t) for t in row[:a + 1]]
            else:
                emitted = self._spec_accept_sampled(
                    probs[i], proposals[i], ke, uniforms[i])
                a = len(emitted) - 1
            self.spec_proposed += ke
            self.spec_accepted += a
            # eos / max_new truncation — both imply this slot retires now.
            lim = req.max_new_tokens - len(req.tokens)
            emitted = emitted[:lim]
            if req.eos_token is not None and req.eos_token in emitted:
                emitted = emitted[:emitted.index(req.eos_token) + 1]
            m = len(emitted)
            req.tokens.extend(emitted)
            if self._goodput is not None:
                self._goodput.emitted(m)
                self._goodput.wasted_spec(ke - a)
            if req.first_token_t is None:
                req.first_token_t = now
                self._obs_first_token(req)
            self._positions[i] = pos + m
            self._last_token[i] = emitted[-1]
            # Draft KV is valid through position pos + min(m, ke) - 1; a
            # full accept leaves the draft one token behind (it never fed
            # its own last proposal) — the next round's catch-up feeds it.
            self._draft_pos[i] = pos + min(m, ke)
            if req.finished:
                self._retire(i)
                finished.append(req.rid)

    @staticmethod
    def _inv_cdf(p: np.ndarray, u: float) -> int:
        c = np.cumsum(p, dtype=np.float64)
        total = c[-1] if c[-1] > 0 else 1.0
        return int(min(np.searchsorted(c / total, u, side="right"),
                       len(p) - 1))

    def _spec_accept_sampled(self, probs: np.ndarray, proposals: np.ndarray,
                             ke: int, uniforms: np.ndarray) -> List[int]:
        """Standard rejection sampling against the target distribution
        ``probs[j]`` (already tempered + top_p-filtered in-program). The
        greedy draft's proposal distribution is a point mass, so proposal
        ``d`` is accepted with probability p(d) and a rejection samples the
        residual p-without-d renormalized — the emitted stream is
        distribution-exact vs non-speculative sampling. ``uniforms[j]`` is
        the (accept coin, residual/bonus inverse-CDF draw) pair keyed by
        (request, absolute position j) — position-keyed, so a preempted-
        and-replayed request makes identical accept decisions regardless
        of schedule or accept history."""
        emitted: List[int] = []
        for j in range(ke):
            d = int(proposals[j])
            u_accept, u_res = uniforms[j]
            if u_accept < probs[j, d]:
                emitted.append(d)
                continue
            residual = probs[j].astype(np.float64).copy()
            residual[d] = 0.0
            if residual.sum() <= 0:
                emitted.append(int(probs[j].argmax()))
            else:
                emitted.append(self._inv_cdf(residual, u_res))
            return emitted
        u_bonus = uniforms[ke, 0]
        emitted.append(self._inv_cdf(probs[ke].astype(np.float64), u_bonus))
        return emitted

    def _draft_catchup(self) -> None:
        """Feed the draft cache every context token it has not seen —
        prompt ingestion right after admission (chunk_tokens per program
        call) and the 1-2 token catch-up after each committed round."""
        n, W = self.scfg.slots, self.scfg.chunk_tokens
        while True:
            need = [i for i in range(n) if self._slots[i] is not None
                    and int(self._draft_pos[i]) < int(self._positions[i])]
            if not need:
                return
            tokens = np.zeros((n, W), np.int32)
            positions = np.zeros((n, W), np.int32)
            valid = np.zeros((n, W), bool)
            last_idx = np.zeros((n,), np.int32)
            for i in need:
                dp = int(self._draft_pos[i])
                c = min(W, int(self._positions[i]) - dp)
                ctx = self._context_ids(self._slots[i])
                tokens[i, :c] = ctx[dp:dp + c]
                positions[i, :c] = np.arange(dp, dp + c)
                valid[i, :c] = True
                last_idx[i] = c - 1
                self._draft_pos[i] = dp + c
            _, self._draft_pools = self._gp_timed(
                self._draft_chunk_fn,
                self.draft_params, jnp.asarray(tokens),
                jnp.asarray(positions), jnp.asarray(valid),
                jnp.asarray(last_idx), self._draft_tables,
                self._draft_pools)

    def _draft_propose(self, k_eff: np.ndarray) -> np.ndarray:
        """Greedy draft proposals: up to ``k_eff[i]`` sequential tokens per
        slot through the batched draft decode step (rows past their own
        k_eff go inactive — writes land in scratch)."""
        n, kmax = self.scfg.slots, int(k_eff.max())
        cur = self._last_token.copy()
        dpos = self._positions.copy()
        out = np.zeros((n, max(kmax, 1)), np.int32)
        for j in range(kmax):
            act = np.array([self._slots[i] is not None and k_eff[i] > j
                            for i in range(n)])
            toks, self._draft_pools = self._gp_timed(
                self._draft_decode_fn,
                self.draft_params, jnp.asarray(cur),
                jnp.asarray(np.where(act, dpos, 0)), self._draft_tables,
                jnp.asarray(act), self._draft_pools)
            toks = np.asarray(toks)
            for i in range(n):
                if act[i]:
                    out[i, j] = toks[i]
                    cur[i] = toks[i]
                    dpos[i] += 1
        return out

    # -- release / retire ----------------------------------------------------

    def _release(self, slot: int) -> None:
        """Free the slot's blocks and clear its row — same step it ends.
        With the prefix cache on, every FULL block of valid KV is first
        offered to the cache (registered under its chained content hash, or
        deduped onto an existing entry), so the decref leaves shareable
        blocks cached instead of free."""
        req = self._slots[slot]
        live = self._tables[slot][self._tables[slot] != SCRATCH_BLOCK]
        if self._lora_on:
            if req is not None and req.adapter_id is not None:
                entry = self._adapters.get(req.adapter_id)
                if entry is not None and entry["refs"]:
                    entry["refs"] -= 1
            self._slot_lora_blocks[slot] = 0
            self._slot_lora_scale[slot] = 0.0
        if self._pcache is not None and req is not None \
                and req.adapter_id is None:
            n_valid = int(self._positions[slot])
            n_full = n_valid // self.scfg.block_size
            if n_full:
                ids = self._context_ids(req)[:n_valid]
                self._pcache.register(
                    ids, [int(b) for b in self._tables[slot, :n_full]])
        for b in live:
            self.allocator.decref(int(b))
        self._tables[slot] = 0
        self._positions[slot] = 0
        self._prefill_target[slot] = 0
        self._last_token[slot] = 0
        self._draft_pos[slot] = 0
        self._slots[slot] = None

    def _retire(self, slot: int) -> None:
        req = self._slots[slot]
        req.status = DONE
        req.finish_t = time.monotonic()
        self._release(slot)
        self._gen_release(req)
        self._obs_retire(req)

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        """Scheduler counters + the KV cost model (docs/parity.md)."""
        from tpu_task.ml.serving.cache import dense_cache_bytes

        out = {
            "steps": self.steps,
            "decode_steps": self.decode_steps,
            # Dispatch amortization (ROADMAP item 4): the configured K
            # and how many K-wide fused micro dispatches actually ran —
            # the measured dispatches/token gauge lives in
            # stats()["goodput"] when obs is on.
            "micro_k": self.scfg.micro_k,
            "micro_steps": self.micro_steps,
            "chunk_steps": self.chunk_steps,
            # The asynchronous loop (ISSUE 16): whether the overlapped
            # dispatch/consume pipeline ran, how many admitting slots may
            # share a chunk program, and how often pool pressure forced
            # a flush back to the synchronous edge.
            "overlap": self._overlap,
            "prefill_slots": self.scfg.prefill_slots,
            "overlap_flushes": self.overlap_flushes,
            "prefills": self.prefills,
            "prefill_chunks": self.prefill_chunks,
            "recompute_preemptions": self.preemption_count,
            "tp": self.tp,
            "ep": self.ep,
            # Which paged attention the fused steps COMPILED with — a
            # silent auto-fallback to the gather path is visible here, so
            # benches and soaks record which path actually ran.
            "decode_impl": self.decode_impl,
            "draft_decode_impl": self.draft_decode_impl,
            "kv_quant": {
                "kv_dtype": self.scfg.kv_dtype
                or str(jnp.dtype(self.cfg.dtype)),
                "quantized_block_writes": self.quantized_block_writes,
                # Worst per-element |dequant - value| actually observed;
                # tracked only in debug mode (TPU_TASK_CHECKIFY=1 — the
                # per-step scalar readback is the cost), None otherwise.
                "max_quant_error_observed":
                    self.max_quant_error if self.debug else None,
            },
            "kv_bytes_per_token": kv_token_bytes(self.cfg, self.scfg),
            "kv_blocks_high_water": self.allocator.high_water,
            "kv_high_water_bytes": paged_cache_bytes(
                self.cfg, self.scfg, self.allocator.high_water),
            "kv_pool_bytes": paged_cache_bytes(
                self.cfg, self.scfg, self.scfg.n_blocks),
            "kv_pool_bytes_per_shard": kv_shard_bytes(
                self.cfg, self.scfg, self.scfg.n_blocks, self.tp),
            "kv_dense_worst_case_bytes": dense_cache_bytes(
                self.cfg, self.scfg.slots, self.scfg.max_len),
            "prefix_cache": {
                "enabled": self._pcache is not None,
                "miss_blocks": self.prefix_miss_blocks,
                "hit_requests": self.prefix_hit_requests,
                "tokens_saved": self.prefix_tokens_saved,
                # Block-level hits ARE the saved prefill blocks — one key.
                "blocks_saved": self.prefix_hit_blocks,
                "cow_copies": self.cow_copies,
                "cached_blocks": len(self._pcache) if self._pcache else 0,
                "shared_blocks": (self._pcache.shared_blocks()
                                  if self._pcache else 0),
                "evictions": (self._pcache.evictions
                              if self._pcache else 0),
            },
            "tiering": {
                # The HBM → host RAM → bucket hierarchy (ROADMAP item
                # 3). demoted: HBM blocks whose bytes were copied down
                # to the host tier; promoted: blocks imported back into
                # HBM from host RAM (the fleet counters below cover the
                # bucket rung); the host_* fields are the tier's own
                # view including its spill tail into the bucket.
                "enabled": self._host_tier is not None,
                "host_offload_blocks": self.scfg.host_offload_blocks,
                "demoted_blocks": self.demoted_blocks,
                "promoted_blocks": self.promoted_blocks,
                "demoted_resident": self.allocator.demoted,
                "pending_demotions": len(self._pending_demotions),
                **({f"host_{k}": v
                    for k, v in self._host_tier.stats().items()}
                   if self._host_tier is not None else {}),
            },
            "kvfleet": {
                "enabled": self._fleet is not None,
                # Admission-side: blocks imported from (resp. missed in)
                # the fleet plane instead of being prefilled locally.
                "hit_blocks": self.fleet_hit_blocks,
                "miss_blocks": self.fleet_miss_blocks,
                "import_requests": self.fleet_import_requests,
                # Prefetch-ahead imports (router next-turn hints):
                # blocks pulled into the local cache BEFORE any
                # request referenced them.
                "prefetch_blocks": self.fleet_prefetch_blocks,
                # Publisher-side (client-owned): what this replica shipped
                # out and pulled in, in bytes.
                "published_blocks": getattr(
                    self._fleet, "published_blocks", 0),
                "bytes_shipped": getattr(self._fleet, "bytes_shipped", 0),
                "bytes_fetched": getattr(self._fleet, "bytes_fetched", 0),
            },
            "spec": {
                "k": self.scfg.spec_k,
                "rounds": self.spec_rounds,
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "accept_rate": round(
                    self.spec_accepted / self.spec_proposed, 4)
                if self.spec_proposed else 0.0,
            },
            # Hot-swap state: the ACTIVE weight generation, how many
            # rolls this engine has absorbed, and the per-generation
            # in-flight stream counts — more than one key here means a
            # roll is mid-flight (old streams draining under old
            # weights).
            "generation": self.generation,
            "adapters": {
                "enabled": self._lora_on,
                "rank": self.scfg.lora_rank,
                "pool_blocks": self.scfg.n_adapter_blocks,
                "registered": self.adapters_registered,
                "resident": sum(
                    1 for e in self._adapters.values()
                    if e["blocks"] is not None),
                "loads": self.adapter_loads,
                "evictions": self.adapter_evictions,
                "pool_high_water": (self._lora_alloc.high_water
                                    if self._lora_alloc else 0),
                "param_swaps": self.param_swaps,
                "stale_generation_streams": self.stale_generation_streams,
                "generations": {str(g): c
                                for g, c in sorted(self._gen_streams.items())},
            },
        }
        if self._obs is not None:
            # The registry IS the export path (PR 11): step wall / TTFT /
            # inter-token histograms plus every counter above as lazy
            # gauges, one name and one type each.
            out["obs"] = self._obs.metrics.snapshot()
        if self._goodput is not None:
            # Convenience view of the goodput.* registry names (PR 12):
            # goodput ratio, MFU, and the in-program vs host-gap split.
            out["goodput"] = self._goodput.snapshot()
        return out
