"""Paged-cache forward passes: bucketed prefill + one-step batched decode.

Both functions run the TRAINING block (``transformer._block``) with an
attention closure over the paged pool, exactly as the dense decode path
does — every projection, norm, rope application, and residual is shared,
and the attention itself goes through the one grouped-query cached core
(``ml.ops.attention.gqa_cached_attention``). The only paged-specific code
is addressing: scatter new k/v into flat pool slots through the block
table, gather the logical-order (slots, L, kv, d) view back out. That is
what makes the paged/dense parity contract bit-exact at fp32 (see
docs/parity.md): identical arithmetic over identical valid entries, and
masked entries contribute an exact 0.0 either way.

Shapes are static everywhere: prefill compiles once per
``(bucket, max_blocks)`` and decode once per ``(slots, max_blocks)`` — a
handful of programs serve every request mix, the serving-side analogue of
``generate``'s one-compiled-program discipline.

Two static knobs thread through every fused step (ROADMAP item 3), both
chosen by the engine at construction, never per call:

- ``attn_impl``: ``"xla"`` keeps the gather+dense decode attention above
  byte-for-byte (the bit-exact fp32 reference); ``"pallas"``/
  ``"interpret"`` route the SAME scatter-then-attend contract through the
  block-table-walking kernel (``ml.ops.paged_attention``) that never
  materializes the gathered buffer.
- quantized pools (``kv_dtype="int8"``/``"fp8"``/``"int4"`` — detected
  from the pool layout): the scatter becomes
  :func:`~tpu_task.ml.serving.cache.quantized_append`
  (per-block requantization driven by the host-computed ``qa`` arrays)
  and every step additionally returns the max quantization error of its
  writes — computed only when the engine's debug mode sets the static
  ``measure_qerr`` flag (otherwise the output is a constant 0.0, so the
  hot path never pays for the measurement). int4 (PR 17) needs nothing
  new here: :func:`pool_is_quantized` keys off the scale sidecar, which
  packed pools carry like int8's, and ``quantized_append``/the kernels
  read the packing off the pool dtype (uint8 IS the int4 marker) — the
  functions below are dtype-agnostic by construction.
"""

from __future__ import annotations

import functools
from typing import List

import jax
import jax.numpy as jnp

from tpu_task.ml.models.decoding import _top_p_filter, bounds_guard
from tpu_task.ml.models.transformer import (
    Params,
    TransformerConfig,
    _block,
    _rmsnorm,
    embed_lookup,
)
from tpu_task.ml.ops.attention import gqa_cached_attention
from tpu_task.ml.ops.paged_attention import paged_attention
from tpu_task.ml.serving.cache import (
    flat_pool,
    gather_kv,
    quantized_append,
    token_slots,
)
from tpu_task.ml.serving.lora import apply_lora


def pool_is_quantized(pools: List[dict]) -> bool:
    """Whether the pool pytree carries quantized-code scale sidecars —
    the shared int8/fp8/int4 discriminator every paged program keys off
    (the code dtype — and, for uint8 pools, the int4 nibble packing —
    is read off the pool arrays)."""
    return "k_scale" in pools[0]


def serving_moe_fn(cfg: TransformerConfig, mesh):
    """The expert-parallel MoE dispatch for the fused serving steps — or
    None when there is nothing to dispatch over (no MoE layers, no mesh,
    or no ``ep`` axis wider than 1), in which case ``_block`` falls back
    to the dense-dispatch reference (``moe.apply_dense``), the exact
    single-chip arithmetic every sharded stream is pinned against.

    The dispatch is :func:`tpu_task.ml.models.moe.apply_sharded` — the
    SAME all_to_all program training uses — specialized for serving:

    - **Row layout**: every fused step's activations are (rows, w, d)
      with rows ∈ {slots, slots + chunk_tokens, 1} and w ∈ {1, bucket,
      k+1}; the dispatch flattens to (rows·w, 1, d) token rows, pads to
      an ep multiple with zero rows (static shapes — one program per
      step geometry, like everything else serving compiles), shards the
      token axis over ep, and un-pads on the way out. The dense compute
      between MoE layers stays on the jit/SPMD path — only the expert
      FFN enters shard_map.
    - **Droplessness**: capacity is pinned to the per-shard token count,
      so every row — real, masked-inactive, or pad — holds a capacity
      slot and none can evict another. That is what makes the ep path's
      greedy streams identical to the dense dispatch (which has no
      capacity limit at all): per token, both compute the same
      gate-weighted expert dot products; a capacity drop would be the
      one divergence, so it is made impossible by construction.
    - **tp×ep**: with a ``tp`` axis in the mesh the expert weights'
      hidden dim additionally shards over tp (the registry's
      ``("expert", "embed", "mlp")`` placement consumed in place — no
      per-step all-gather of expert weights), completed by one psum.
    - The router aux loss is computed (shared code path) and discarded
      by serving — decode has no loss to regularize."""
    if mesh is None or cfg.moe_every <= 0:
        return None
    from tpu_task.ml.models import moe
    from tpu_task.ml.parallel.sharding import mesh_axis_size

    ep = mesh_axis_size(mesh, "ep")
    if ep == 1:
        return None
    if cfg.n_experts % ep:
        raise ValueError(
            f"n_experts {cfg.n_experts} not divisible by ep={ep} "
            f"(mesh axes {tuple(mesh.axis_names)}): expert weights shard "
            "one group per ep shard")
    mcfg = cfg.moe_cfg
    tp_axis = "tp" if mesh_axis_size(mesh, "tp") > 1 else None

    def fn(layer, h):
        b, s, d = h.shape
        rows = b * s
        pad = (-rows) % ep
        flat = h.reshape(rows, 1, d)
        if pad:
            # jnp.pad, NOT concatenate-with-zeros: under an outer jit on
            # a tp×ep mesh, XLA SPMD (jax 0.4.x CPU) miscompiles a
            # concatenate feeding the shard_map's token slicing (every
            # row's values corrupt, not just low bits — caught by the
            # ep-vs-dense stream pin); pad lowers to a clean slice.
            flat = jnp.pad(flat, ((0, pad), (0, 0), (0, 0)))
        out, aux = moe.apply_sharded(
            layer, mcfg, flat, mesh, batch_axes=("ep",), tp_axis=tp_axis,
            capacity=(rows + pad) // ep)
        return out[:rows].reshape(b, s, d), aux

    return fn


def _fold_qerr(qerrs: List[jax.Array]) -> jax.Array:
    """Max write-quantization error across a step's layers."""
    return functools.reduce(jnp.maximum, qerrs)


def paged_prefill(params: Params, cfg: TransformerConfig, tokens, length,
                  block_table, pools: List[dict], *,
                  measure_qerr: bool = False, moe_fn=None):
    """One request's prompt through the model, writing its k/v into the
    paged pool. ``tokens``: (1, bucket) right-padded to a prefill bucket;
    ``length``: the real prompt length (may be traced — one compile per
    bucket, not per length); ``block_table``: (max_blocks,) int32 with the
    prompt's blocks allocated. Returns (last-real-position logits
    (1, vocab) float32, updated pools).

    A fresh slot attends only itself, so prefill attention is causal
    self-attention over the bucket via the shared core — no gather. Pad
    rows (p >= length) compute garbage q/k/v: their writes land either in
    the tail of the slot's own last allocated block (overwritten by the
    real token before any unmasked read — decode writes position p before
    attending it) or, beyond the allocated region, in the scratch block;
    their attention rows are never read (logits are gathered at
    length - 1, and pads sit at positions > every real row's mask).

    A quantized pool changes only the WRITE: the prompt's blocks quantize
    in one :func:`quantized_append` per layer (the write layout —
    touched/filled/offsets — is derivable in-program from ``length``, no
    host arrays needed), the prompt still attends its own exact
    activations, and the step returns (logits, pools, max quant error)."""
    b, s = tokens.shape
    block_size = pools[0]["k"].shape[1]
    quantized = pool_is_quantized(pools)
    bounds_guard(length <= block_table.shape[0] * block_size,
                 "prefill overflow: length {length} exceeds the slot's "
                 "block-table capacity {cap}",
                 length=jnp.asarray(length),
                 cap=jnp.asarray(block_table.shape[0] * block_size))
    positions = jnp.arange(s)
    write_idx = token_slots(block_table, positions, block_size)
    x = embed_lookup(params["embed"].astype(cfg.dtype), tokens)
    lora = params.get("lora")
    new_pools: List[dict] = []
    qerrs: List[jax.Array] = []
    for layer_i, (layer, pool) in enumerate(zip(params["layers"], pools)):
        updated: dict = {}

        def attn_fn(q, k, v, pool=pool, updated=updated):
            if quantized:
                # Rows past `length` land at offsets >= their block's
                # filled count (or in wholly-dead scratch entries) and are
                # zeroed by the requantize, so prompt padding cannot
                # inflate a block's scale.
                filled = jnp.clip(
                    length - jnp.arange(block_table.shape[0]) * block_size,
                    0, block_size)
                upd, err = quantized_append(
                    pool, k[0], v[0], block_table,
                    filled, positions // block_size,
                    positions % block_size, measure_error=measure_qerr)
                updated.update(upd)
                qerrs.append(err)
            else:
                updated["k"] = flat_pool(pool["k"]).at[write_idx].set(
                    k[0]).reshape(pool["k"].shape)
                updated["v"] = flat_pool(pool["v"]).at[write_idx].set(
                    v[0]).reshape(pool["v"].shape)
            return gqa_cached_attention(q, k, v, positions)

        x_in = x
        x, _aux = _block(x, layer, cfg, attn_fn, positions=positions,
                         moe_fn=moe_fn)
        if lora is not None:
            # Parallel adapter branch around the unmodified block:
            # h += ((x @ A) * scale) @ B, gathered per row from the paged
            # adapter pool; block 0 is all-zero, so a lora-less row adds
            # an exact 0.0 (the rank-0 no-op contract, docs/parity.md).
            lpool, lblocks, lscales = lora
            x = x + apply_lora(x_in, lpool, lblocks[:, layer_i], lscales)
        new_pools.append(updated)
    x = _rmsnorm(x, params["final_norm"])
    logits = x[:, length - 1] @ params["unembed"].astype(cfg.dtype)
    if quantized:
        return logits.astype(jnp.float32), new_pools, _fold_qerr(qerrs)
    return logits.astype(jnp.float32), new_pools


def paged_decode_step(params: Params, cfg: TransformerConfig, tokens,
                      positions, block_tables, active, pools: List[dict],
                      qa=None, *, attn_impl: str = "xla", mesh=None,
                      measure_qerr: bool = False, moe_fn=None):
    """ONE decode step across every slot: each slot's last token in, each
    slot's next-token logits out. ``tokens``: (slots,) int32; ``positions``:
    (slots,) — the absolute position each new token occupies (per-slot: no
    two slots need be at the same depth, THE continuous-batching property);
    ``block_tables``: (slots, max_blocks) int32; ``active``: (slots,) bool —
    inactive slots still compute (static shapes) but write only scratch and
    their outputs are discarded by the host scheduler. Returns
    ((slots, vocab) float32 logits, updated pools) — plus the max write
    quantization error when the pool is int8 (``qa`` carries the
    host-computed write layout; see :func:`quantized_append`)."""
    slots = tokens.shape[0]
    block_size = pools[0]["k"].shape[1]
    quantized = pool_is_quantized(pools)
    capacity = block_tables.shape[1] * block_size
    if quantized and qa is None:
        raise ValueError(
            "quantized (int8) pools need the host-computed `qa` write "
            "layout (touched, filled, wt, wo) — see "
            "cache.quantized_append; ServingEngine derives it per step "
            "(_quant_layout)")
    bounds_guard(jnp.all(jnp.where(active, positions, 0) < capacity),
                 "decode overflow: a slot position reached the block-table "
                 "capacity {cap}", cap=jnp.asarray(capacity))
    pos2d = positions[:, None]
    write_idx = jnp.where(
        active, token_slots(block_tables, positions, block_size), 0)
    x = embed_lookup(params["embed"].astype(cfg.dtype), tokens[:, None])
    lora = params.get("lora")
    new_pools: List[dict] = []
    qerrs: List[jax.Array] = []
    for layer_i, (layer, pool) in enumerate(zip(params["layers"], pools)):
        updated: dict = {}

        def attn_fn(q, k, v, pool=pool, updated=updated):
            # Scatter this step's k/v (slots, 1, kv, d), THEN attend — the
            # new token must attend itself, same order as the dense path.
            if quantized:
                upd, err = quantized_append(pool, k[:, 0], v[:, 0], *qa,
                                            measure_error=measure_qerr)
                updated.update(upd)
                qerrs.append(err)
                return paged_attention(
                    q, upd["k"], upd["v"], block_tables, pos2d,
                    upd["k_scale"], upd["v_scale"], impl=attn_impl,
                    mesh=mesh)
            kf = flat_pool(pool["k"]).at[write_idx].set(k[:, 0])
            vf = flat_pool(pool["v"]).at[write_idx].set(v[:, 0])
            updated["k"] = kf.reshape(pool["k"].shape)
            updated["v"] = vf.reshape(pool["v"].shape)
            if attn_impl != "xla":
                return paged_attention(
                    q, updated["k"], updated["v"], block_tables, pos2d,
                    impl=attn_impl, mesh=mesh)
            k_view = gather_kv(kf, block_tables, block_size)
            v_view = gather_kv(vf, block_tables, block_size)
            return gqa_cached_attention(q, k_view, v_view, pos2d)

        x_in = x
        x, _aux = _block(x, layer, cfg, attn_fn, positions=pos2d,
                         moe_fn=moe_fn)
        if lora is not None:
            lpool, lblocks, lscales = lora
            x = x + apply_lora(x_in, lpool, lblocks[:, layer_i], lscales)
        new_pools.append(updated)
    x = _rmsnorm(x, params["final_norm"])
    logits = x[:, -1] @ params["unembed"].astype(cfg.dtype)
    if quantized:
        return logits.astype(jnp.float32), new_pools, _fold_qerr(qerrs)
    return logits.astype(jnp.float32), new_pools


def greedy_decode_step(params: Params, cfg: TransformerConfig, tokens,
                       positions, block_tables, active, pools, qa=None, *,
                       attn_impl: str = "xla", mesh=None,
                       measure_qerr: bool = False, moe_fn=None):
    """Fused decode + argmax: the greedy fast path of the engine — when
    every active slot decodes at temperature 0 the sampler reduces to one
    argmax and the step program carries no sort/cumsum/key-fold. Returns
    ((slots,) int32 next tokens, pools[, max quant error])."""
    out = paged_decode_step(
        params, cfg, tokens, positions, block_tables, active, pools, qa,
        attn_impl=attn_impl, mesh=mesh, measure_qerr=measure_qerr,
        moe_fn=moe_fn)
    toks = jnp.argmax(out[0], axis=-1).astype(jnp.int32)
    return (toks,) + tuple(out[1:])


# -- K-token fused micro-steps (dispatch amortization, ROADMAP item 4) -------

def _micro_scan(params: Params, cfg: TransformerConfig, tokens, positions,
                block_tables, active, limits, eos, pools, qa, micro_k: int,
                sampler, attn_impl: str, mesh, measure_qerr: bool,
                moe_fn=None, emitted0=None, return_carry: bool = False):
    """Run ``micro_k`` SEQUENTIAL decode iterations inside one program —
    the engine's per-token host loop folded into a ``lax.scan`` whose
    body is exactly :func:`paged_decode_step` plus the sampler plus the
    retirement bookkeeping the host used to do between dispatches:

    - iteration j samples slot i's next token iff the slot is still
      ``alive`` (entered active, has not hit eos or its length limit);
    - retirement is IN-PROGRAM masking: a slot whose sampled token is
      its eos (``eos[i]`` ≥ 0) or whose emitted count reaches
      ``limits[i]`` flips its alive bit, and every later iteration
      treats it exactly like an inactive decode slot — position masked
      to 0, k/v writes redirected to scratch, outputs garbage the host
      sweep never reads;
    - positions advance by 1 per emitted token, so iteration j writes
      absolute position ``positions[i] + j`` — byte-identical addressing
      to j separate steps.

    ``sampler(logits, alive, j)`` returns (slots,) int32 next tokens —
    argmax for the greedy program, the keyed sampler for the sampled one
    (its per-token key is folded in-program from the iteration's
    n_generated, the SAME ``fold_in(request_key, token_index)`` stream
    K=1 draws, which is what makes K a pure scheduling knob: greedy
    streams are bit-identical and sampled streams key-identical to K=1).

    Quantized pools thread a STACKED ``qa`` (leading dim ``micro_k``,
    one host-computed write layout per iteration, laid out as if every
    entering slot lives through its span — a mid-span retiree's
    remaining layout rows touch only its own exclusively-owned blocks,
    whose garbage requantization is unread by construction: the partial
    block is never cache-registered and frees at the host sweep).

    Returns ((micro_k, slots) int32 tokens, pools[, max quant error]).
    The host recovers each slot's valid prefix from the tokens alone —
    it knows eos and the limits, so validity needs no extra output.

    The overlapped engine threads the loop state PROGRAM TO PROGRAM
    instead of rebuilding it from host mirrors each dispatch:
    ``emitted0`` seeds the emitted counter (the carry convention is then
    ABSOLUTE — emitted ≡ the request's total generated-token count and
    ``limits`` ≡ max_new_tokens, which emits the identical tokens: with
    relative limits ``span = min(K, remaining)``, ``emitted_rel ≥ span``
    fires exactly when ``emitted_abs ≥ max_new`` inside the K
    iterations) and ``return_carry=True`` additionally returns the final
    (tok, pos, alive, emitted) carry as device arrays, so the next
    micro-step's inputs never round-trip through the host."""
    quantized = pool_is_quantized(pools)
    if quantized and qa is None:
        raise ValueError(
            "quantized (int8/fp8) pools need the host-computed stacked "
            "`qa` write layouts (one per micro iteration) — see "
            "ServingEngine._micro_quant_layout")

    def body(carry, qa_j):
        tok, pos, alive, emitted, pools = carry
        out = paged_decode_step(
            params, cfg, tok, jnp.where(alive, pos, 0), block_tables,
            alive, pools, qa_j, attn_impl=attn_impl, mesh=mesh,
            measure_qerr=measure_qerr, moe_fn=moe_fn)
        logits, pools = out[0], out[1]
        nxt = sampler(logits, alive, emitted)
        emitted = emitted + alive.astype(jnp.int32)
        done = alive & (((eos >= 0) & (nxt == eos)) | (emitted >= limits))
        tok = jnp.where(alive, nxt, tok)
        pos = pos + alive.astype(jnp.int32)
        alive = alive & ~done
        ys = (nxt, out[2]) if quantized else (nxt,)
        return (tok, pos, alive, emitted, pools), ys

    init = (tokens, positions, active,
            jnp.zeros_like(positions) if emitted0 is None else emitted0,
            pools)
    if quantized:
        (tok, pos, alive, emitted, pools), ys = jax.lax.scan(body, init, qa)
        qerr = jnp.max(ys[1])
        if return_carry:
            return ys[0], (tok, pos, alive, emitted), pools, qerr
        return ys[0], pools, qerr
    (tok, pos, alive, emitted, pools), ys = jax.lax.scan(
        body, init, None, length=micro_k)
    if return_carry:
        return ys[0], (tok, pos, alive, emitted), pools
    return ys[0], pools


def micro_decode_greedy(params: Params, cfg: TransformerConfig, tokens,
                        positions, block_tables, active, limits, eos,
                        pools, qa=None, *, micro_k: int,
                        attn_impl: str = "xla", mesh=None,
                        measure_qerr: bool = False, moe_fn=None):
    """Greedy K-token micro-step: ``micro_k`` fused decode+argmax
    iterations, ONE dispatch, ONE (micro_k, slots) readback — the
    steady-state program that takes dispatch overhead from one-per-token
    to one-per-K-tokens. Bit-identical tokens to ``micro_k`` separate
    :func:`greedy_decode_step` calls (docs/parity.md "Dispatch
    amortization")."""
    def sampler(logits, alive, emitted):
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    return _micro_scan(params, cfg, tokens, positions, block_tables,
                       active, limits, eos, pools, qa, micro_k, sampler,
                       attn_impl, mesh, measure_qerr, moe_fn=moe_fn)


def micro_decode_sample(params: Params, cfg: TransformerConfig, tokens,
                        positions, block_tables, active, limits, eos,
                        temperature, top_p, slot_keys, n_generated, pools,
                        qa=None, *, micro_k: int, attn_impl: str = "xla",
                        mesh=None, measure_qerr: bool = False,
                        moe_fn=None):
    """Sampled K-token micro-step: per-iteration keys fold in-program
    from the running n_generated (``fold_in(slot_keys[i], ngen)``) — the
    identical per-token key stream K=1's ``decode_and_sample`` draws, so
    a request's sampled stream is the same at any K (key-identity, the
    sampling half of the dispatch-amortization contract)."""
    def sampler(logits, alive, emitted):
        keys = jax.vmap(jax.random.fold_in)(
            slot_keys, n_generated + emitted)
        return sample_tokens(logits, temperature, top_p, keys)

    return _micro_scan(params, cfg, tokens, positions, block_tables,
                       active, limits, eos, pools, qa, micro_k, sampler,
                       attn_impl, mesh, measure_qerr, moe_fn=moe_fn)


# -- carry-threaded programs (the overlapped engine loop, ROADMAP item 4) ----
#
# The async engine never reads the loop state back between dispatches:
# each program takes the previous program's (tok, pos, alive, emitted)
# carry as device arrays and returns the next one, so the host's only
# blocking edge is the (K, slots) token readback it sweeps — and that
# sweep runs while the device executes the NEXT (already dispatched)
# program. The carry convention is ABSOLUTE: ``emitted`` is the
# request's total generated count (== len(req.tokens)) and ``limits``
# is max_new_tokens, so a carry rebuilt from host mirrors after any
# full sweep is exactly the device's (docs/parity.md "Async overlap").


def micro_carry_greedy(params: Params, cfg: TransformerConfig, tok, pos,
                       alive, emitted, block_tables, limits, eos, pools,
                       qa=None, *, micro_k: int, attn_impl: str = "xla",
                       mesh=None, measure_qerr: bool = False, moe_fn=None):
    """Greedy K-token micro-step with the loop carry threaded in AND out
    — :func:`micro_decode_greedy` emitting the identical tokens (same
    scan body, absolute instead of relative retirement limits), plus the
    final (tok, pos, alive, emitted) carry for the next dispatch.
    Returns ((micro_k, slots) tokens, carry, pools[, max quant err])."""
    def sampler(logits, alive_, emitted_):
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    return _micro_scan(params, cfg, tok, pos, block_tables, alive, limits,
                       eos, pools, qa, micro_k, sampler, attn_impl, mesh,
                       measure_qerr, moe_fn=moe_fn, emitted0=emitted,
                       return_carry=True)


def micro_carry_sample(params: Params, cfg: TransformerConfig, tok, pos,
                       alive, emitted, block_tables, limits, eos,
                       temperature, top_p, slot_keys, pools, qa=None, *,
                       micro_k: int, attn_impl: str = "xla", mesh=None,
                       measure_qerr: bool = False, moe_fn=None):
    """Sampled K-token micro-step with the carry threaded through. The
    carry's absolute ``emitted`` IS each slot's n_generated, so the
    per-iteration key is ``fold_in(slot_keys[i], emitted)`` directly —
    the same per-token key stream every other sampler draws."""
    def sampler(logits, alive_, emitted_):
        keys = jax.vmap(jax.random.fold_in)(slot_keys, emitted_)
        return sample_tokens(logits, temperature, top_p, keys)

    return _micro_scan(params, cfg, tok, pos, block_tables, alive, limits,
                       eos, pools, qa, micro_k, sampler, attn_impl, mesh,
                       measure_qerr, moe_fn=moe_fn, emitted0=emitted,
                       return_carry=True)


def _chunk_carry(params: Params, cfg: TransformerConfig, tok, pos, alive,
                 emitted, ctoks, cpos, cvalid, block_tables, limits, eos,
                 promote_row, promote_pos, promote_ngen, pools, qa,
                 sampler, attn_impl: str, mesh, measure_qerr: bool,
                 moe_fn=None):
    """The carry-threaded packed chunk step: ONE decode pass at batch
    ``slots + chunk_tokens`` where rows 0..slots-1 advance the carry
    (width-1 decode with in-program retirement — the K=1 micro body) and
    rows slots.. ingest prompt chunks from host-supplied arrays (each
    chunk row carries its OWN slot's table row, so several admissions
    pack into one program). ``promote_row[i] >= 0`` marks slot ``i`` as
    COMPLETING its prefill this step: the program lifts that (absolute)
    chunk row's sampled token into the carry as the slot's first
    generated token, sets its position to ``promote_pos[i]`` (the
    prefill target) and its emitted count to ``promote_ngen[i] + 1``,
    and applies the same eos/limit retirement every decode row gets —
    so a newly admitted request joins the NEXT program's decode rows
    without the host ever touching the in-flight one. Returns
    ((slots + chunk_tokens,) sampled tokens, carry, pools[, qerr])."""
    n = tok.shape[0]
    W = ctoks.shape[0]
    R = n + W
    quantized = pool_is_quantized(pools)
    # Static-slice packing (.at[].set), NOT jnp.concatenate: token-path
    # concatenates feeding shard_map are the documented jax 0.4.x CPU
    # SPMD miscompile (see serving_moe_fn) and the repo lint flags them.
    tokens = jnp.zeros((R,), jnp.int32).at[:n].set(tok).at[n:].set(ctoks)
    positions = jnp.zeros((R,), jnp.int32) \
        .at[:n].set(jnp.where(alive, pos, 0)) \
        .at[n:].set(jnp.where(cvalid, cpos, 0))
    active = jnp.zeros((R,), bool).at[:n].set(alive).at[n:].set(cvalid)
    out = paged_decode_step(
        params, cfg, tokens, positions, block_tables, active, pools, qa,
        attn_impl=attn_impl, mesh=mesh, measure_qerr=measure_qerr,
        moe_fn=moe_fn)
    nxt = sampler(out[0], emitted)                   # (R,) int32
    # Decode-row update: exactly the micro-scan body at K=1.
    new_tok = jnp.where(alive, nxt[:n], tok)
    new_emitted = emitted + alive.astype(jnp.int32)
    done = alive & (((eos >= 0) & (new_tok == eos))
                    | (new_emitted >= limits))
    new_pos = pos + alive.astype(jnp.int32)
    new_alive = alive & ~done
    # Promotion: completing prefill slots enter the carry with their
    # first sampled token — and the same retirement check a bucketed
    # admission's immediate first token gets (max_new == 1, or the
    # first token IS eos).
    promoting = promote_row >= 0
    ptok = nxt[n + jnp.clip(promote_row, 0, W - 1)]
    p_emitted = promote_ngen + 1
    p_alive = ~(((eos >= 0) & (ptok == eos)) | (p_emitted >= limits))
    new_tok = jnp.where(promoting, ptok, new_tok)
    new_pos = jnp.where(promoting, promote_pos, new_pos)
    new_emitted = jnp.where(promoting, p_emitted, new_emitted)
    new_alive = jnp.where(promoting, p_alive, new_alive)
    carry = (new_tok, new_pos, new_alive, new_emitted)
    if quantized:
        return nxt, carry, out[1], out[2]
    return nxt, carry, out[1]


def chunk_carry_greedy(params: Params, cfg: TransformerConfig, tok, pos,
                       alive, emitted, ctoks, cpos, cvalid, block_tables,
                       limits, eos, promote_row, promote_pos, promote_ngen,
                       pools, qa=None, *, attn_impl: str = "xla",
                       mesh=None, measure_qerr: bool = False, moe_fn=None):
    """Greedy carry chunk step — argmax over every packed row."""
    def sampler(logits, emitted_):
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    return _chunk_carry(params, cfg, tok, pos, alive, emitted, ctoks,
                        cpos, cvalid, block_tables, limits, eos,
                        promote_row, promote_pos, promote_ngen, pools, qa,
                        sampler, attn_impl, mesh, measure_qerr,
                        moe_fn=moe_fn)


def chunk_carry_sample(params: Params, cfg: TransformerConfig, tok, pos,
                       alive, emitted, ctoks, cpos, cvalid, block_tables,
                       limits, eos, promote_row, promote_pos, promote_ngen,
                       temperature, top_p, row_keys, chunk_ngen, pools,
                       qa=None, *, attn_impl: str = "xla", mesh=None,
                       measure_qerr: bool = False, moe_fn=None):
    """Sampled carry chunk step: per-row (temperature, top_p, key) come
    from the host; each row's token index is the carry's emitted count
    (decode rows) or the admission-time generated count (chunk rows —
    constant through a prefill, so the completing row's draw is exactly
    ``fold_in(key, len(req.tokens))``, the first-token draw every other
    path makes)."""
    n = tok.shape[0]

    def sampler(logits, emitted_):
        ngen = jnp.zeros((logits.shape[0],), jnp.int32) \
            .at[:n].set(emitted_).at[n:].set(chunk_ngen)
        keys = jax.vmap(jax.random.fold_in)(row_keys, ngen)
        return sample_tokens(logits, temperature, top_p, keys)

    return _chunk_carry(params, cfg, tok, pos, alive, emitted, ctoks,
                        cpos, cvalid, block_tables, limits, eos,
                        promote_row, promote_pos, promote_ngen, pools, qa,
                        sampler, attn_impl, mesh, measure_qerr,
                        moe_fn=moe_fn)


def decode_and_sample(params: Params, cfg: TransformerConfig, tokens,
                      positions, block_tables, active, temperature, top_p,
                      slot_keys, n_generated, pools, qa=None, *,
                      attn_impl: str = "xla", mesh=None,
                      measure_qerr: bool = False, moe_fn=None):
    """Fused decode step + sampler: ONE program (one dispatch, one (slots,)
    readback) per engine iteration — the serving analogue of ``generate``
    folding its sampler into the scan body. Per-token sampling keys are
    derived in-program: ``fold_in(slot_keys[i], n_generated[i])``, so a
    request's stream still depends only on its own key and token index,
    never on co-scheduling. Returns ((slots,) int32 next tokens,
    pools[, max quant error])."""
    out = paged_decode_step(
        params, cfg, tokens, positions, block_tables, active, pools, qa,
        attn_impl=attn_impl, mesh=mesh, measure_qerr=measure_qerr,
        moe_fn=moe_fn)
    keys = jax.vmap(jax.random.fold_in)(slot_keys, n_generated)
    toks = sample_tokens(out[0], temperature, top_p, keys)
    return (toks,) + tuple(out[1:])


# -- multi-token step: chunked prefill + speculative scoring -----------------

def _multitoken_features(params: Params, cfg: TransformerConfig, tokens,
                         positions, valid, block_tables, pools, qa=None, *,
                         attn_impl: str = "xla", mesh=None,
                         measure_qerr: bool = False, moe_fn=None):
    """The width-``w`` generalization of ``paged_decode_step``: run
    ``tokens`` (slots, w) through the model with PER-TOKEN absolute
    ``positions`` (slots, w) and a ``valid`` mask (slots, w), scattering
    each valid token's k/v into its flat pool slot and attending the
    gathered logical view. Invalid tokens (ragged rows: a decode row uses
    1 column, a prefill chunk ``c <= w``, an exhausted spec row fewer than
    ``k+1``) write only scratch and their outputs are garbage the host
    discards — same masked-write discipline as inactive decode slots.
    Returns ((slots, w, d_model) final-norm features, updated pools).

    Width 1 with a full mask is exactly ``paged_decode_step``'s semantics;
    a chunk at positions [p, p+c) is causally identical to the same tokens
    inside a bucketed prefill (each query attends cache entries <= its own
    position, and every extra masked pool slot contributes an exact 0.0
    softmax weight at fp32) — which is why chunked-vs-bucketed greedy
    bit-identity is a checkable contract, not a hope (docs/parity.md)."""
    block_size = pools[0]["k"].shape[1]
    quantized = pool_is_quantized(pools)
    capacity = block_tables.shape[1] * block_size
    if quantized and qa is None:
        raise ValueError(
            "quantized (int8) pools need the host-computed `qa` write "
            "layout (touched, filled, wt, wo) — see "
            "cache.quantized_append; ServingEngine derives it per step "
            "(_quant_layout)")
    bounds_guard(jnp.all(jnp.where(valid, positions, 0) < capacity),
                 "multitoken overflow: a position reached the block-table "
                 "capacity {cap}", cap=jnp.asarray(capacity))
    slots, w = tokens.shape
    qpos = jnp.where(valid, positions, 0)
    block = qpos // block_size
    phys = jnp.take_along_axis(block_tables, block, axis=1)   # (slots, w)
    write_idx = jnp.where(
        valid, phys * block_size + qpos % block_size, 0).reshape(-1)
    x = embed_lookup(params["embed"].astype(cfg.dtype), tokens)
    lora = params.get("lora")
    new_pools: List[dict] = []
    qerrs: List[jax.Array] = []
    for layer_i, (layer, pool) in enumerate(zip(params["layers"], pools)):
        updated: dict = {}

        def attn_fn(q, k, v, pool=pool, updated=updated):
            # Scatter every valid token's k/v, THEN attend: a chunk token
            # must attend its in-chunk predecessors (written this call) as
            # well as the cached prefix — the position mask provides the
            # causal cut, exactly as in the bucketed program.
            kv_heads, d_head = k.shape[2], k.shape[3]
            if quantized:
                upd, err = quantized_append(
                    pool, k.reshape(-1, kv_heads, d_head),
                    v.reshape(-1, kv_heads, d_head), *qa,
                    measure_error=measure_qerr)
                updated.update(upd)
                qerrs.append(err)
                return paged_attention(
                    q, upd["k"], upd["v"], block_tables, qpos,
                    upd["k_scale"], upd["v_scale"], impl=attn_impl,
                    mesh=mesh)
            kf = flat_pool(pool["k"]).at[write_idx].set(
                k.reshape(-1, kv_heads, d_head))
            vf = flat_pool(pool["v"]).at[write_idx].set(
                v.reshape(-1, kv_heads, d_head))
            updated["k"] = kf.reshape(pool["k"].shape)
            updated["v"] = vf.reshape(pool["v"].shape)
            if attn_impl != "xla":
                return paged_attention(
                    q, updated["k"], updated["v"], block_tables, qpos,
                    impl=attn_impl, mesh=mesh)
            k_view = gather_kv(kf, block_tables, block_size)
            v_view = gather_kv(vf, block_tables, block_size)
            return gqa_cached_attention(q, k_view, v_view, qpos)

        x_in = x
        x, _aux = _block(x, layer, cfg, attn_fn, positions=qpos,
                         moe_fn=moe_fn)
        if lora is not None:
            lpool, lblocks, lscales = lora
            x = x + apply_lora(x_in, lpool, lblocks[:, layer_i], lscales)
        new_pools.append(updated)
    feats = _rmsnorm(x, params["final_norm"])
    if quantized:
        return feats, new_pools, _fold_qerr(qerrs)
    return feats, new_pools


def paged_multitoken_logits(params: Params, cfg: TransformerConfig, tokens,
                            positions, valid, block_tables, pools, qa=None,
                            *, attn_impl: str = "xla", mesh=None,
                            measure_qerr: bool = False, moe_fn=None):
    """Full-width logits (slots, w, vocab) float32 — the speculative
    scoring step: ONE fused target pass scores all k+1 positions of every
    slot's [last_token, draft_1..draft_k] row against the paged cache."""
    out = _multitoken_features(
        params, cfg, tokens, positions, valid, block_tables, pools, qa,
        attn_impl=attn_impl, mesh=mesh, measure_qerr=measure_qerr,
        moe_fn=moe_fn)
    logits = out[0] @ params["unembed"].astype(cfg.dtype)
    return (logits.astype(jnp.float32),) + tuple(out[1:])


def spec_score_greedy(params: Params, cfg: TransformerConfig, tokens,
                      positions, valid, block_tables, pools, qa=None, *,
                      attn_impl: str = "xla", mesh=None,
                      measure_qerr: bool = False, moe_fn=None):
    """Fused speculative scoring + argmax: (slots, w) int32 target tokens
    — the greedy accept rule (longest agreeing prefix + bonus token) runs
    on these host-side and is bit-identical to non-speculative decoding."""
    out = paged_multitoken_logits(
        params, cfg, tokens, positions, valid, block_tables, pools, qa,
        attn_impl=attn_impl, mesh=mesh, measure_qerr=measure_qerr,
        moe_fn=moe_fn)
    return (jnp.argmax(out[0], axis=-1).astype(jnp.int32),) + tuple(out[1:])


def spec_score_probs(params: Params, cfg: TransformerConfig, tokens,
                     positions, valid, block_tables, temperature, top_p,
                     pools, qa=None, *, attn_impl: str = "xla", mesh=None,
                     measure_qerr: bool = False, moe_fn=None):
    """Fused speculative scoring for SAMPLED requests: per-position target
    probabilities (slots, w, vocab) float32 after the SAME temper-then-
    top_p filter ``sample_tokens`` applies — so host-side rejection
    sampling targets exactly the distribution non-speculative decoding
    samples from (the distribution-exactness contract). Greedy rows
    (temperature 0) run at temp 1 and the host takes argmax(probs), which
    equals argmax(logits) — softmax is monotonic."""
    out = paged_multitoken_logits(
        params, cfg, tokens, positions, valid, block_tables, pools, qa,
        attn_impl=attn_impl, mesh=mesh, measure_qerr=measure_qerr,
        moe_fn=moe_fn)
    logits = out[0]
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    filtered = _top_p_filter(
        (logits / safe_t[:, None, None]).reshape(-1, logits.shape[-1]),
        jnp.repeat(top_p, logits.shape[1]))
    probs = jax.nn.softmax(filtered, axis=-1).reshape(logits.shape)
    return (probs,) + tuple(out[1:])


def chunked_step_greedy(params: Params, cfg: TransformerConfig, tokens,
                        positions, valid, last_idx, block_tables, pools,
                        qa=None, *, attn_impl: str = "xla", mesh=None,
                        measure_qerr: bool = False, moe_fn=None):
    """Fused multi-row chunk ingestion: every row advances by its own
    ``valid`` span and emits the argmax at its LAST valid position
    (``last_idx``: (slots,)); mid-prompt rows' outputs are discarded by
    the host. The TARGET engine ingests through the token-packed decode
    step instead (engine._chunk_step — slots + chunk rows of width 1);
    this (slots, w) layout remains for the DRAFT cache catch-up, where
    several slots may need multi-token ingestion in one call. Returns
    ((slots,) int32, pools[, max quant error])."""
    out = _multitoken_features(
        params, cfg, tokens, positions, valid, block_tables, pools, qa,
        attn_impl=attn_impl, mesh=mesh, measure_qerr=measure_qerr,
        moe_fn=moe_fn)
    slots = tokens.shape[0]
    last = out[0][jnp.arange(slots), last_idx]      # (slots, d_model)
    logits = (last @ params["unembed"].astype(cfg.dtype)).astype(jnp.float32)
    return (jnp.argmax(logits, axis=-1).astype(jnp.int32),) + tuple(out[1:])


def sample_tokens(logits, temperature, top_p, keys):
    """Per-row sampling with per-row params in one program: row i is greedy
    when ``temperature[i] == 0``, else softmax-samples at its temperature
    through its nucleus (``top_p[i]``; 1.0 disables). ``keys``: (n, 2)
    uint32 — one PRNG key per row, so a request's token stream depends only
    on its own key, never on which slots it happens to share a step with
    (per-request determinism under any schedule). Same temper-then-filter
    order and the same ``_top_p_filter`` as ``generate``."""
    greedy = jnp.argmax(logits, axis=-1)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    filtered = _top_p_filter(logits / safe_t[:, None], top_p)
    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row, axis=-1)
    )(keys, filtered)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
