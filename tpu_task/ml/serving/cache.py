"""Paged KV cache: one shared physical block pool + per-slot block tables.

The dense decode cache (``ml.models.decoding.init_cache``) reserves
``slots × max_len`` token slots per layer up front — O(slots × max_len)
bytes whether or not anything lives there, and the worst-case ``max_len``
must cover the LONGEST request the server will ever admit. Serving traffic
is mixed-length, so almost all of that reservation is dead weight. The
paged layout (vLLM/PagedAttention, Kwon et al., SOSP 2023) carves KV memory
into fixed ``block_size``-token physical blocks shared by every slot:
sequences allocate blocks lazily as they cross block boundaries and free
them the step they finish, so live KV bytes are O(live tokens).

Device side, each layer holds ``k``/``v`` pools of static shape
``(n_blocks, block_size, kv_heads, d_head)``; a slot's logical token
``p`` lives at flat pool slot ``block_table[p // block_size] * block_size
+ p % block_size``. Host side, :class:`BlockAllocator` is a plain free
list — allocation is a scheduler decision, never traced.

Physical block 0 is reserved as the SCRATCH block: it is never allocated,
``0`` in a block table means "unallocated", and every masked write
(inactive slots, prompt padding) is redirected into it. Gathers through
unallocated table entries therefore read scratch garbage — which the
positional mask pins to a score of NEG_INF, an exact softmax weight of
0.0 at fp32, so the garbage never reaches an output bit (the paged/dense
parity contract in docs/parity.md).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax.numpy as jnp

from tpu_task.ml.models.transformer import TransformerConfig

#: Physical block index reserved for masked writes / the "unallocated"
#: block-table sentinel. Never handed out by the allocator.
SCRATCH_BLOCK = 0


@dataclass(frozen=True)
class ServingConfig:
    """Admission knobs for the continuous-batching engine.

    ``slots``: width of the fixed decode batch — how many sequences decode
    per step (the one compiled decode program). ``block_size``/``n_blocks``:
    paged-pool geometry (``n_blocks`` INCLUDES the reserved scratch block).
    ``max_len``: per-slot logical capacity (prompt + generated); it bounds
    the block table width, not any allocation. ``prefill_buckets``: padded
    prompt lengths — prefill compiles one program per bucket instead of one
    per prompt length.
    """

    slots: int = 8
    block_size: int = 16
    n_blocks: int = 128
    max_len: int = 256
    prefill_buckets: Tuple[int, ...] = (16, 32, 64, 128)

    def __post_init__(self):
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        if self.block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {self.block_size}")
        if self.n_blocks < 2:
            raise ValueError(
                f"n_blocks must be >= 2 (block 0 is scratch), got "
                f"{self.n_blocks}")
        if not self.prefill_buckets or list(self.prefill_buckets) != sorted(
                set(self.prefill_buckets)):
            raise ValueError(
                f"prefill_buckets must be non-empty strictly ascending, got "
                f"{self.prefill_buckets}")
        if self.prefill_buckets[-1] > self.max_len:
            raise ValueError(
                f"largest prefill bucket {self.prefill_buckets[-1]} exceeds "
                f"max_len {self.max_len}")

    @property
    def max_blocks_per_slot(self) -> int:
        return -(-self.max_len // self.block_size)

    def bucket_for(self, prompt_len: int) -> int:
        """Smallest prefill bucket holding ``prompt_len`` tokens."""
        for b in self.prefill_buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt of {prompt_len} tokens exceeds the largest prefill "
            f"bucket {self.prefill_buckets[-1]}")

    def blocks_for(self, n_tokens: int) -> int:
        """Physical blocks covering ``n_tokens`` logical tokens."""
        return -(-n_tokens // self.block_size)


def kv_token_bytes(cfg: TransformerConfig) -> int:
    """KV bytes one token occupies across all layers (k + v)."""
    return (2 * cfg.n_layers * cfg.kv_heads * cfg.d_head
            * jnp.dtype(cfg.dtype).itemsize)


def dense_cache_bytes(cfg: TransformerConfig, slots: int,
                      max_len: int) -> int:
    """Worst-case bytes of the dense layout: every slot reserves max_len."""
    return slots * max_len * kv_token_bytes(cfg)


def paged_cache_bytes(cfg: TransformerConfig, scfg: ServingConfig,
                      n_blocks: int) -> int:
    """Bytes of ``n_blocks`` physical blocks (e.g. the allocator's
    high-water mark — what a right-sized pool would have needed)."""
    return n_blocks * scfg.block_size * kv_token_bytes(cfg)


def init_pools(cfg: TransformerConfig, scfg: ServingConfig) -> List[dict]:
    """Per-layer k/v physical pools, same narrow KV-head layout (and the
    same per-layer list-of-dicts pytree) as the dense cache."""
    shape = (scfg.n_blocks, scfg.block_size, cfg.kv_heads, cfg.d_head)
    return [{"k": jnp.zeros(shape, cfg.dtype),
             "v": jnp.zeros(shape, cfg.dtype)}
            for _ in range(cfg.n_layers)]


#: Regex partition rules for the paged pools — the pool pytree carries no
#: logical-axis annotations (it is built here, not by the model), so the
#: registry's regex-over-path half covers it: every ``<layer>/k`` and
#: ``<layer>/v`` leaf is ``(n_blocks, block_size, kv_heads, d_head)`` and
#: shards its KV-HEAD axis wherever the "heads" logical axis goes (tp).
#: Paging stays along the token axis, so block accounting — tables,
#: allocator, scratch block — is identical at every tp width.
SERVING_POOL_RULES = (
    (r"(^|/)[kv]$", (None, None, "heads", None)),
)


def pool_pspecs(pools, mesh) -> List[dict]:
    """PartitionSpecs for the pool pytree via the shared partition registry
    (kv-heads over tp; block grid, block offset, and head_dim replicated)."""
    from tpu_task.ml.parallel.sharding import match_partition_rules

    return match_partition_rules(SERVING_POOL_RULES, pools, mesh=mesh)


def kv_shard_bytes(cfg: TransformerConfig, scfg: ServingConfig,
                   n_blocks: int, tp: int) -> int:
    """Per-device bytes of ``n_blocks`` physical blocks under a ``tp``-way
    kv-head shard: each device holds ``kv_heads / tp`` heads of every
    block, so the pool cost divides by tp exactly (kv_heads % tp == 0 is
    validated at engine construction)."""
    return paged_cache_bytes(cfg, scfg, n_blocks) // max(1, tp)


# -- traced indexing helpers (used inside the jitted serving steps) ----------

def flat_pool(pool):
    """(n_blocks, block_size, kv, d) → (n_blocks·block_size, kv, d): all
    reads/writes address the pool as flat token slots."""
    n, bs = pool.shape[:2]
    return pool.reshape(n * bs, *pool.shape[2:])


def token_slots(block_table, positions, block_size: int):
    """Flat pool slot of each logical ``positions`` entry through
    ``block_table``. block_table: (max_blocks,) or (slots, max_blocks);
    positions broadcasts accordingly ((s,) resp. (slots,))."""
    block = positions // block_size
    if block_table.ndim == 1:
        phys = block_table[block]
    else:
        phys = jnp.take_along_axis(block_table, block[:, None], axis=1)[:, 0]
    return phys * block_size + positions % block_size


def gather_kv(pool_flat, block_table, block_size: int):
    """Gather a (slots, max_blocks·block_size, kv, d) logical-order view of
    the pool through the block tables — the dense (b, L, kv, d) cache layout
    the shared attention core consumes. Unallocated table entries read the
    scratch block; the core's positional mask zeroes them exactly."""
    idx = (block_table[:, :, None] * block_size
           + jnp.arange(block_size)[None, None, :])
    return pool_flat[idx.reshape(block_table.shape[0], -1)]


class BlockAllocator:
    """Host-side free list over the physical blocks (block 0 excluded —
    it is the scratch block). Tracks the high-water mark of live blocks so
    the bench can report what a right-sized pool would have needed."""

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError(f"n_blocks must be >= 2, got {n_blocks}")
        self.n_blocks = n_blocks
        # Pop from the tail → lowest block numbers first (determinism aid).
        self._free = list(range(n_blocks - 1, SCRATCH_BLOCK, -1))
        self.high_water = 0

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return (self.n_blocks - 1) - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` blocks, or None (nothing allocated) if the pool can't."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n)]
        self.high_water = max(self.high_water, self.in_use)
        return got

    def free(self, blocks) -> None:
        for b in blocks:
            if not SCRATCH_BLOCK < b < self.n_blocks:
                raise ValueError(f"free of invalid block {b}")
            if b in self._free:
                raise ValueError(f"double free of block {b}")
            self._free.append(b)
