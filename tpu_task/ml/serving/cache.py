"""Paged KV cache: one shared physical block pool + per-slot block tables.

The dense decode cache (``ml.models.decoding.init_cache``) reserves
``slots × max_len`` token slots per layer up front — O(slots × max_len)
bytes whether or not anything lives there, and the worst-case ``max_len``
must cover the LONGEST request the server will ever admit. Serving traffic
is mixed-length, so almost all of that reservation is dead weight. The
paged layout (vLLM/PagedAttention, Kwon et al., SOSP 2023) carves KV memory
into fixed ``block_size``-token physical blocks shared by every slot:
sequences allocate blocks lazily as they cross block boundaries and free
them the step they finish, so live KV bytes are O(live tokens).

Device side, each layer holds ``k``/``v`` pools of static shape
``(n_blocks, block_size, kv_heads, d_head)``; a slot's logical token
``p`` lives at flat pool slot ``block_table[p // block_size] * block_size
+ p % block_size``. Host side, :class:`BlockAllocator` is a plain free
list — allocation is a scheduler decision, never traced.

Physical block 0 is reserved as the SCRATCH block: it is never allocated,
``0`` in a block table means "unallocated", and every masked write
(inactive slots, prompt padding) is redirected into it. Gathers through
unallocated table entries therefore read scratch garbage — which the
positional mask pins to a score of NEG_INF, an exact softmax weight of
0.0 at fp32, so the garbage never reaches an output bit (the paged/dense
parity contract in docs/parity.md).

``ServingConfig(kv_dtype="int8")`` stores the pools as int8 codes with a
per-(block, kv-head) float32 scale sidecar: the same HBM budget holds
~2× the blocks (``blocks_in_budget``), writes quantize at append/COW
time (:func:`quantized_append` — a vectorized dequantize→modify→
requantize over the step's touched blocks), and the attention paths
dequantize on read (in-register inside the Pallas paged kernel). The
fp32 bit-exactness contract demotes to a documented tolerance contract
for quantized pools only (docs/parity.md "Decode kernel + quantized KV").

``kv_dtype="fp8"`` generalizes the same sidecar machinery to float8
e4m3 codes: the scale normalizes a block's amax to :data:`FP8_MAX`, the
element then keeps a 3-bit mantissa of ITS OWN magnitude — error is
relative (≤ ``|x|·2⁻⁴`` per element) where int8's is uniform
(≤ ``scale/2``), so small entries of an outlier-heavy block survive
where int8 flattens them. Bytes per element are identical to int8 (1 +
the amortized sidecar); the knob trades accuracy shape, not density.
Gated on backend dtype support (:func:`fp8_supported`) with the same
interpret-mode CPU parity story as the int8 pools.

``kv_dtype="int4"`` is the sub-byte density rung: two 4-bit codes pack
into each uint8 pool element (adjacent channel pairs, even channel in
the low nibble), so the same HBM holds ~2× the blocks of int8 again.
The uint8 pool dtype IS the int4 marker — int8 pools are ``jnp.int8``,
fp8 pools ``float8_e4m3fn``, so every generic caller (``copy_block``,
``write_block``, export/import, COW) flows unchanged, and
:func:`quantize_blocks`/:func:`dequantize_blocks` pack/unpack at the
boundary. ``scale = amax / 7`` (floored at :data:`INT8_SCALE_EPS`),
round-to-nearest codes clipped to ±7 — round-trip error ≤ ``scale / 2``
per element, exactly int8's uniform bound at a coarser grid. A fresh
all-zero uint8 pool unpacks to code 0 in both nibbles and dequantizes
to exact zeros at the epsilon scale, preserving the fresh-pool
invariant. Requires an even ``d_head`` (pairs pack along the head
dim; validated at ``init_pools``).

**Tiered residency** (ROADMAP item 3): :class:`BlockAllocator` grows a
``demoted`` mark — a retained refcount-0 cached block whose bytes have
been replicated to the host offload tier (``ml/serving/offload.py``).
Demotion never invalidates the HBM copy; it makes the block the
PREFERRED eviction victim (:meth:`PrefixCache.evict` reclaims demoted
blocks first), so HBM frees under pressure without losing the bytes —
a later admission re-imports them host→HBM by content hash. Touching a
demoted block (``incref``) simply cancels the mark: the HBM bytes were
valid all along, so resurrection is free. Invariant: demoted ⊆
retained ∧ refcount-0 — a referenced or unretained block is never
marked, so a slot can only ever reference a demoted block while idle.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from tpu_task.ml.models.transformer import TransformerConfig

#: Physical block index reserved for masked writes / the "unallocated"
#: block-table sentinel. Never handed out by the allocator.
SCRATCH_BLOCK = 0

#: Floor for the per-(block, kv-head) quantization scale: an all-zero
#: block quantizes to zero codes at this scale and dequantizes back to
#: exact zeros, so fresh pools read the same values int8 as fp32.
INT8_SCALE_EPS = 1e-8

#: Largest finite float8 e4m3 value — the fp8 analogue of int8's 127:
#: the per-(block, kv-head) scale maps the block's amax to exactly this,
#: so nothing overflows to inf/nan and the 3-bit mantissa spends its
#: precision inside the block's real range.
FP8_MAX = 448.0

#: The quantized pool dtypes (``ServingConfig.kv_dtype`` values that
#: carry scale sidecars and route writes through
#: :func:`quantized_append`).
QUANT_DTYPES = ("int8", "fp8", "int4")

#: Largest int4 code magnitude: packed nibbles hold [-8, 7] but the
#: symmetric grid uses ±7 so the amax element maps to exactly ±7 and
#: nothing clips (the int8 127 analogue).
INT4_MAX = 7


def kv_code_dtype(kv_dtype: str):
    """Storage dtype of a quantized pool's code arrays. ``jnp.uint8`` IS
    the int4 marker (int8 pools are ``jnp.int8``, fp8 pools
    ``float8_e4m3fn`` — uint8 is unambiguous), so code paths that only
    see a pool can tell a packed layer from an int8 one by dtype alone."""
    if kv_dtype == "int8":
        return jnp.int8
    if kv_dtype == "fp8":
        return jnp.float8_e4m3fn
    if kv_dtype == "int4":
        return jnp.uint8
    raise ValueError(f"not a quantized kv_dtype: {kv_dtype!r}")


def fp8_supported() -> bool:
    """Whether this jax build + backend can store and convert float8
    e4m3 arrays — the construction-time gate for ``kv_dtype="fp8"``
    (an unsupported backend gets an actionable error, never a lowering
    failure mid-decode)."""
    if not hasattr(jnp, "float8_e4m3fn"):
        return False
    try:
        x = jnp.asarray([1.5], jnp.float8_e4m3fn).astype(jnp.float32)
        return float(x[0]) == 1.5
    except Exception:
        return False


@dataclass(frozen=True)
class ServingConfig:
    """Admission knobs for the continuous-batching engine.

    ``slots``: width of the fixed decode batch — how many sequences decode
    per step (the one compiled decode program). ``block_size``/``n_blocks``:
    paged-pool geometry (``n_blocks`` INCLUDES the reserved scratch block).
    ``max_len``: per-slot logical capacity (prompt + generated); it bounds
    the block table width, not any allocation. ``prefill_buckets``: padded
    prompt lengths — legacy ``prefill="bucketed"`` compiles one program per
    bucket instead of one per prompt length.

    Production-traffic knobs:

    - ``prefill``: ``"chunked"`` (default) folds prompt ingestion into the
      fused decode step — each step ingests at most ``chunk_tokens`` prompt
      positions of ONE admitting slot while every running slot still
      decodes its token, so a long admission never stalls the others'
      inter-token latency. ``"bucketed"`` is the legacy PR 5 path (whole
      prompt in one padded program at admission) kept as the baseline.
    - ``prefix_cache``: content-hash full KV blocks and share them across
      requests (refcounts + copy-on-write); admission prefills only the
      O(new tokens) tail. Requires ``prefill="chunked"`` (the tail is
      ingested through the chunk program).
    - ``spec_k``: speculative decoding — a draft model (passed to the
      engine) proposes ``spec_k`` tokens per slot per step and ONE fused
      target step scores all ``spec_k + 1`` positions. 0 disables.

    Raw-decode-speed knobs (ROADMAP item 3):

    - ``decode_impl``: which paged attention the fused steps run.
      ``"auto"`` (default) selects the Pallas paged-decode kernel on a
      TPU backend when the pool geometry satisfies its tile constraints
      (falling back to XLA with a one-time warning when it doesn't) and
      the XLA gather+dense path everywhere else; ``"xla"`` forces the
      gather path (the bit-exact fp32 reference); ``"pallas"`` demands
      the compiled kernel (raises an actionable error off-TPU or on bad
      geometry); ``"interpret"`` runs the same kernel through the Pallas
      interpreter on any backend (parity tests, CPU smokes — slow).
    - ``kv_dtype``: ``None`` stores KV in the model dtype (the bit-exact
      paged≡dense contract); ``"int8"`` stores int8 codes plus a
      per-(block, kv-head) fp32 scale sidecar — ~2× the blocks in the
      same bytes, under a documented tolerance contract
      (docs/parity.md "Decode kernel + quantized KV"); ``"fp8"`` stores
      float8 e4m3 codes through the same sidecar machinery (equal bytes
      to int8, relative-not-uniform rounding error); ``"int4"`` packs
      two codes per uint8 byte — ~2× the blocks of int8 in the same
      bytes, same uniform ≤ scale/2 error bound at a coarser grid
      (needs an even ``d_head``).
    - ``micro_k``: dispatch amortization — steady-state decode runs
      ``micro_k`` sequential iterations inside ONE jitted program
      (in-program eos/length retirement masks; a retired slot's
      remaining iterations write scratch), so the engine re-enters
      Python once per K tokens instead of per token. 1 (default) keeps
      the per-token step loop and its byte-identical programs; greedy
      streams at any K are bit-identical to K=1 and sampled streams
      key-identical (docs/parity.md "Dispatch amortization").
    - ``overlap``: the fully asynchronous engine loop (docs/parity.md
      "Async overlap"): each scheduler step DISPATCHES the next fused
      program (its inputs are a device-resident carry threaded program
      to program, never read back) before it blocks on — and host-sweeps
      — the previous one, so retire/admit/publish/obs bookkeeping runs
      while the device executes the next micro-step. Admissions are
      staged into the NEXT program's chunk rows (the in-flight program
      is never restarted or recompiled). Greedy streams stay
      bit-identical to the synchronous loop at every ``micro_k``;
      requires ``prefill="chunked"``, ``spec_k == 0``, and a mesh-less
      engine. False (default) keeps the synchronous step loop.
    - ``prefill_slots``: how many admitting slots may prefill
      CONCURRENTLY — the per-step ``chunk_tokens`` budget packs the
      oldest ``prefill_slots`` admissions' chunks into ONE program
      (each chunk row carries its own slot's block table). 1 (default)
      keeps the one-admission-at-a-time schedule; raising it drains an
      admission burst in ~burst/``prefill_slots`` fewer steps whenever
      prompts are shorter than the chunk budget (the admission-p99
      lever — ``bench.py goodput`` measures it).
    - ``host_offload_blocks``: capacity of the host-RAM KV offload tier
      in blocks (docs/parity.md "Tiered KV"). 0 (default) disables
      tiering. With a budget, cold retained refcount-0 cached blocks
      demote to pinned host arrays asynchronously on the overlap seam,
      eviction under pool pressure reclaims demoted blocks first (the
      bytes survive on the host), later admissions promote host-resident
      chains back into the pool ahead of prefill, and blocks evicted
      from a full host tier spill to the fleet KV bucket when one is
      attached (otherwise they drop — recompute-from-prefix covers the
      miss, never a wrong stream). Requires ``prefix_cache`` (the tier
      is content-addressed by the cache's chained block hashes).
    - ``lora_rank``: rank of the paged LoRA adapter pool (docs/parity.md
      "Multi-model tenancy"). 0 (default) disables multi-tenant
      adapters. With a rank, every fused program gathers per-slot
      adapter blocks and applies batched shrink/expand; adapter-less
      slots ride the all-zero scratch block (exact no-op). Adapters
      registered at a smaller rank zero-pad to this pool rank.
    - ``n_adapter_blocks``: capacity of the adapter block pool. One
      block holds one layer of one adapter, so a resident adapter costs
      ``n_layers`` blocks and block 0 is the zero scratch block (same
      convention as the KV pool). Required >= 2 when ``lora_rank`` > 0.
    """

    slots: int = 8
    block_size: int = 16
    n_blocks: int = 128
    max_len: int = 256
    prefill_buckets: Tuple[int, ...] = (16, 32, 64, 128)
    prefill: str = "chunked"
    chunk_tokens: int = 16
    prefix_cache: bool = True
    spec_k: int = 0
    decode_impl: str = "auto"
    kv_dtype: Optional[str] = None
    micro_k: int = 1
    overlap: bool = False
    prefill_slots: int = 1
    host_offload_blocks: int = 0
    lora_rank: int = 0
    n_adapter_blocks: int = 0

    def __post_init__(self):
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        if self.block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {self.block_size}")
        if self.n_blocks < 2:
            raise ValueError(
                f"n_blocks must be >= 2 (block 0 is scratch), got "
                f"{self.n_blocks}")
        if not self.prefill_buckets or list(self.prefill_buckets) != sorted(
                set(self.prefill_buckets)):
            raise ValueError(
                f"prefill_buckets must be non-empty strictly ascending, got "
                f"{self.prefill_buckets}")
        if self.prefill == "bucketed" and self.prefill_buckets[-1] > self.max_len:
            # Chunked prefill never pads to a bucket, so the default bucket
            # table may exceed a small max_len there without harm.
            raise ValueError(
                f"largest prefill bucket {self.prefill_buckets[-1]} exceeds "
                f"max_len {self.max_len}")
        if self.prefill not in ("chunked", "bucketed"):
            raise ValueError(
                f"prefill must be 'chunked' or 'bucketed', got "
                f"{self.prefill!r}")
        if self.chunk_tokens < 1:
            raise ValueError(
                f"chunk_tokens must be >= 1, got {self.chunk_tokens}")
        if self.prefix_cache and self.prefill != "chunked":
            raise ValueError(
                "prefix_cache needs prefill='chunked': a cache-hit "
                "admission prefills only the tail, which is a chunk step")
        if self.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {self.spec_k}")
        if self.decode_impl not in ("auto", "xla", "pallas", "interpret",
                                    "pipelined", "interpret_pipelined"):
            raise ValueError(
                f"decode_impl must be one of 'auto', 'xla', 'pallas', "
                f"'interpret', 'pipelined', 'interpret_pipelined', got "
                f"{self.decode_impl!r}")
        if self.kv_dtype not in (None,) + QUANT_DTYPES:
            raise ValueError(
                f"kv_dtype must be None (model dtype), 'int8', 'fp8', or "
                f"'int4', got {self.kv_dtype!r}")
        if self.micro_k < 1:
            raise ValueError(
                f"micro_k must be >= 1, got {self.micro_k}")
        if self.micro_k > self.max_len:
            raise ValueError(
                f"micro_k {self.micro_k} exceeds max_len {self.max_len}")
        if self.prefill_slots < 1:
            raise ValueError(
                f"prefill_slots must be >= 1, got {self.prefill_slots}")
        if self.prefill_slots > self.slots:
            raise ValueError(
                f"prefill_slots {self.prefill_slots} exceeds slots "
                f"{self.slots}")
        if self.overlap and self.prefill != "chunked":
            raise ValueError(
                "overlap=True needs prefill='chunked': admissions are "
                "staged into the next program's chunk rows")
        if self.overlap and self.spec_k > 0:
            raise ValueError(
                "overlap=True is incompatible with speculative decoding "
                "(spec_k > 0): the draft/score round-trip is a host "
                "sync point every round")
        if self.host_offload_blocks < 0:
            raise ValueError(
                f"host_offload_blocks must be >= 0, got "
                f"{self.host_offload_blocks}")
        if self.host_offload_blocks and not self.prefix_cache:
            raise ValueError(
                "host_offload_blocks needs prefix_cache=True: the host "
                "tier is content-addressed by the cache's chained block "
                "hashes")
        if self.lora_rank < 0:
            raise ValueError(
                f"lora_rank must be >= 0, got {self.lora_rank}")
        if self.n_adapter_blocks < 0:
            raise ValueError(
                f"n_adapter_blocks must be >= 0, got "
                f"{self.n_adapter_blocks}")
        if self.lora_rank > 0 and self.n_adapter_blocks < 2:
            raise ValueError(
                f"lora_rank > 0 needs n_adapter_blocks >= 2 (block 0 is "
                f"the zero scratch block), got {self.n_adapter_blocks}")

    @property
    def max_blocks_per_slot(self) -> int:
        return -(-self.max_len // self.block_size)

    def bucket_for(self, prompt_len: int) -> int:
        """Smallest prefill bucket holding ``prompt_len`` tokens."""
        for b in self.prefill_buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt of {prompt_len} tokens exceeds the largest prefill "
            f"bucket {self.prefill_buckets[-1]}")

    def blocks_for(self, n_tokens: int) -> int:
        """Physical blocks covering ``n_tokens`` logical tokens."""
        return -(-n_tokens // self.block_size)


def kv_token_bytes(cfg: TransformerConfig,
                   scfg: Optional[ServingConfig] = None) -> int:
    """KV bytes one token occupies across all layers (k + v) — DTYPE-AWARE:
    without ``scfg`` (or with ``kv_dtype=None``) the storage dtype is the
    model dtype; with a quantized dtype each element is one byte
    (``"int8"``/``"fp8"``) or half a byte (``"int4"`` — two codes per
    uint8) plus the amortized per-(block, kv-head) fp32 scale sidecar
    (``2 · n_layers · kv_heads · 4 / block_size`` bytes per token)."""
    per_channel = 2 * cfg.n_layers * cfg.kv_heads
    if scfg is None or scfg.kv_dtype is None:
        return per_channel * cfg.d_head * jnp.dtype(cfg.dtype).itemsize
    # Quantized codes + the scale sidecar amortized over the block's
    # tokens.
    d_bytes = cfg.d_head // 2 if scfg.kv_dtype == "int4" else cfg.d_head
    return (per_channel * d_bytes
            + -(-per_channel * 4 // scfg.block_size))


def kv_block_bytes(cfg: TransformerConfig, scfg: ServingConfig) -> int:
    """Exact bytes ONE physical block costs (codes + its scale sidecar) —
    the unit ``blocks_in_budget`` divides an HBM budget by."""
    if scfg.kv_dtype in QUANT_DTYPES:
        d_bytes = (cfg.d_head // 2 if scfg.kv_dtype == "int4"
                   else cfg.d_head)
        per_block = 2 * cfg.n_layers * cfg.kv_heads * (
            scfg.block_size * d_bytes)
        per_block += 2 * cfg.n_layers * cfg.kv_heads * 4
        return per_block
    return 2 * cfg.n_layers * cfg.kv_heads * (
        scfg.block_size * cfg.d_head * jnp.dtype(cfg.dtype).itemsize)


def blocks_in_budget(cfg: TransformerConfig, scfg: ServingConfig,
                     budget_bytes: int) -> int:
    """How many physical blocks (scratch included) fit ``budget_bytes``
    under this config's KV dtype — the int8 density claim in one number:
    the same budget admits ~2× the fp32 ``n_blocks`` (minus the scale
    sidecar overhead), tracked by ``bench.py serving``."""
    return budget_bytes // kv_block_bytes(cfg, scfg)


def dense_cache_bytes(cfg: TransformerConfig, slots: int,
                      max_len: int) -> int:
    """Worst-case bytes of the dense layout: every slot reserves max_len."""
    return slots * max_len * kv_token_bytes(cfg)


def paged_cache_bytes(cfg: TransformerConfig, scfg: ServingConfig,
                      n_blocks: int) -> int:
    """Bytes of ``n_blocks`` physical blocks (e.g. the allocator's
    high-water mark — what a right-sized pool would have needed),
    scale sidecars included when the pool is quantized."""
    return n_blocks * kv_block_bytes(cfg, scfg)


def init_pools(cfg: TransformerConfig, scfg: ServingConfig) -> List[dict]:
    """Per-layer k/v physical pools, same narrow KV-head layout (and the
    same per-layer list-of-dicts pytree) as the dense cache. With a
    quantized ``kv_dtype`` (``"int8"``/``"fp8"``) each layer additionally
    carries ``k_scale``/``v_scale`` sidecars of shape
    (n_blocks, kv_heads) float32; zero codes at the epsilon scale
    dequantize to exact zeros, so a fresh quantized pool reads
    identically to a fresh fp32 one (an all-zero uint8 int4 pool unpacks
    to code 0 in both nibbles — the invariant survives packing)."""
    shape = (scfg.n_blocks, scfg.block_size, cfg.kv_heads, cfg.d_head)
    if scfg.kv_dtype in QUANT_DTYPES:
        code_dtype = kv_code_dtype(scfg.kv_dtype)
        if scfg.kv_dtype == "int4":
            if cfg.d_head % 2:
                raise ValueError(
                    f"kv_dtype='int4' packs adjacent d_head pairs and "
                    f"needs an even d_head, got {cfg.d_head}")
            shape = shape[:-1] + (cfg.d_head // 2,)

        # Distinct arrays per leaf: the engine DONATES the pool pytree,
        # and XLA rejects the same buffer donated twice.
        def scale():
            return jnp.full((scfg.n_blocks, cfg.kv_heads), INT8_SCALE_EPS,
                            jnp.float32)

        return [{"k": jnp.zeros(shape, code_dtype),
                 "v": jnp.zeros(shape, code_dtype),
                 "k_scale": scale(), "v_scale": scale()}
                for _ in range(cfg.n_layers)]
    return [{"k": jnp.zeros(shape, cfg.dtype),
             "v": jnp.zeros(shape, cfg.dtype)}
            for _ in range(cfg.n_layers)]


#: Regex partition rules for the paged pools — the pool pytree carries no
#: logical-axis annotations (it is built here, not by the model), so the
#: registry's regex-over-path half covers it: every ``<layer>/k`` and
#: ``<layer>/v`` leaf is ``(n_blocks, block_size, kv_heads, d_head)`` and
#: shards its KV-HEAD axis wherever the "heads" logical axis goes (tp).
#: Paging stays along the token axis, so block accounting — tables,
#: allocator, scratch block — is identical at every tp width.
#: Scale sidecars are (n_blocks, kv_heads): the kv-head axis shards with
#: the pool it scales. Listed first only for clarity — ``[kv]$`` cannot
#: match a ``*_scale`` path anyway.
SERVING_POOL_RULES = (
    (r"(^|/)[kv]_scale$", (None, "heads")),
    (r"(^|/)[kv]$", (None, None, "heads", None)),
)


def pool_pspecs(pools, mesh) -> List[dict]:
    """PartitionSpecs for the pool pytree via the shared partition registry
    (kv-heads over tp; block grid, block offset, and head_dim replicated)."""
    from tpu_task.ml.parallel.sharding import match_partition_rules

    return match_partition_rules(SERVING_POOL_RULES, pools, mesh=mesh)


def kv_shard_bytes(cfg: TransformerConfig, scfg: ServingConfig,
                   n_blocks: int, tp: int) -> int:
    """Per-device bytes of ``n_blocks`` physical blocks under a ``tp``-way
    kv-head shard: each device holds ``kv_heads / tp`` heads of every
    block, so the pool cost divides by tp exactly (kv_heads % tp == 0 is
    validated at engine construction)."""
    return paged_cache_bytes(cfg, scfg, n_blocks) // max(1, tp)


# -- traced indexing helpers (used inside the jitted serving steps) ----------

def flat_pool(pool):
    """(n_blocks, block_size, kv, d) → (n_blocks·block_size, kv, d): all
    reads/writes address the pool as flat token slots."""
    n, bs = pool.shape[:2]
    return pool.reshape(n * bs, *pool.shape[2:])


def token_slots(block_table, positions, block_size: int):
    """Flat pool slot of each logical ``positions`` entry through
    ``block_table``. block_table: (max_blocks,) or (slots, max_blocks);
    positions broadcasts accordingly ((s,) resp. (slots,))."""
    block = positions // block_size
    if block_table.ndim == 1:
        phys = block_table[block]
    else:
        phys = jnp.take_along_axis(block_table, block[:, None], axis=1)[:, 0]
    return phys * block_size + positions % block_size


def copy_block(pools: List[dict], src, dst) -> List[dict]:
    """Copy physical block ``src`` to ``dst`` in every layer's k/v pool —
    the device half of copy-on-write: a slot about to write into a block it
    shares with the prefix cache gets a private copy first, so the donor
    block's bytes (and every other reader's view) stay untouched. ``src``/
    ``dst`` may be traced scalars: one compiled program covers every COW.
    Generic over the pool layout: a quantized layer's scale sidecars copy
    with its codes (COW-time "quantization" is a byte copy — the donor's
    codes are already exact for the shared prefix)."""
    return [{name: arr.at[dst].set(arr[src])
             for name, arr in pool.items()}
            for pool in pools]


def gather_kv(pool_flat, block_table, block_size: int):
    """Gather a (slots, max_blocks·block_size, kv, d) logical-order view of
    the pool through the block tables — the dense (b, L, kv, d) cache layout
    the shared attention core consumes. Unallocated table entries read the
    scratch block; the core's positional mask zeroes them exactly."""
    idx = (block_table[:, :, None] * block_size
           + jnp.arange(block_size)[None, None, :])
    return pool_flat[idx.reshape(block_table.shape[0], -1)]


# -- int8 / fp8 / int4 KV block quantization ---------------------------------

def pack_int4(codes):
    """(..., d) int8 codes in [-7, 7] → (..., d/2) uint8: adjacent
    channel pairs share a byte, even channel in the low nibble. Bitwise
    ops on the int8 codes see two's-complement nibbles (-7 & 15 == 9),
    so packing needs no bias term."""
    pairs = codes.reshape(codes.shape[:-1] + (codes.shape[-1] // 2, 2))
    lo = pairs[..., 0].astype(jnp.uint8) & 15
    hi = pairs[..., 1].astype(jnp.uint8) & 15
    return lo | (hi << 4)


def unpack_int4(packed):
    """Inverse of :func:`pack_int4`: (..., d/2) uint8 → (..., d) int8.
    Branch-free nibble sign extension ``(n ^ 8) - 8`` maps 0..15 back to
    two's complement (9 → -7, 15 → -1, 0 → 0 — a fresh all-zero pool
    stays exact zeros)."""
    nibbles = jnp.stack([packed & 15, (packed >> 4) & 15], axis=-1)
    signed = (nibbles.astype(jnp.int8) ^ 8) - 8
    return signed.reshape(packed.shape[:-1] + (packed.shape[-1] * 2,))


def quantize_blocks(x, code_dtype=jnp.int8):
    """(n, block_size, kv, d) float values → (codes, (n, kv) float32
    scales): symmetric per-(block, kv-head) quantization.

    ``code_dtype=jnp.int8`` (default): ``scale = amax / 127`` (floored at
    :data:`INT8_SCALE_EPS`), round-to-nearest integer codes — round-trip
    error ≤ ``scale / 2`` per element, UNIFORM across the block (the amax
    element maps to exactly ±127, nothing clips).

    ``code_dtype=jnp.float8_e4m3fn``: ``scale = amax / FP8_MAX`` and the
    scaled value keeps fp8's own 3-bit mantissa — round-trip error is
    RELATIVE, ≤ ``max(|x| · 2⁻⁴, scale · 2⁻⁹)`` per element (half-ulp of
    a normal, resp. the subnormal step at the bottom), so small entries
    of an outlier-heavy block keep precision int8's uniform grid loses.

    ``code_dtype=jnp.uint8`` (the int4 marker): ``scale = amax /``
    :data:`INT4_MAX`, codes clipped to ±7 and PACKED two per byte
    (:func:`pack_int4`) — the returned codes' trailing dim is ``d/2``.
    Round-trip error ≤ ``scale / 2`` per element of the PAIR, int8's
    bound at a 16× coarser grid.
    All bounds are property-pinned in tests/test_paged_attention.py
    and tests/test_kv_tiering.py."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=(1, 3))
    if jnp.dtype(code_dtype) == jnp.dtype(jnp.int8):
        scale = jnp.maximum(amax / 127.0, INT8_SCALE_EPS)
        codes = jnp.clip(
            jnp.round(x.astype(jnp.float32) / scale[:, None, :, None]),
            -127, 127).astype(jnp.int8)
        return codes, scale
    if jnp.dtype(code_dtype) == jnp.dtype(jnp.uint8):
        scale = jnp.maximum(amax / float(INT4_MAX), INT8_SCALE_EPS)
        codes = jnp.clip(
            jnp.round(x.astype(jnp.float32) / scale[:, None, :, None]),
            -INT4_MAX, INT4_MAX).astype(jnp.int8)
        return pack_int4(codes), scale
    scale = jnp.maximum(amax / FP8_MAX, INT8_SCALE_EPS)
    codes = (x.astype(jnp.float32)
             / scale[:, None, :, None]).astype(code_dtype)
    return codes, scale


def dequantize_blocks(codes, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_blocks` (up to the ≤ scale/2 rounding).
    uint8 codes are packed int4 pairs and unpack to the full head dim
    first — callers always see full-width values."""
    if codes.dtype == jnp.uint8:
        codes = unpack_int4(codes)
    return (codes.astype(jnp.float32)
            * scale[:, None, :, None]).astype(dtype)


def quantized_append(pool: dict, new_k, new_v, touched, filled, wt, wo,
                     measure_error: bool = False):
    """Append this step's tokens into a quantized (int8/fp8) pool layer,
    requantizing the written blocks — the device half of "writes quantize
    at append time". The code dtype is read off the pool, so int8 and
    fp8 pools share every caller.

    A per-(block, kv-head) scale cannot absorb a new token in place (the
    block's amax may grow), so the write is a dequantize→modify→requantize
    at BLOCK granularity over the step's touched blocks, fully vectorized:

    - ``touched``: (T,) physical block ids this step writes (host-deduped
      — packed chunk rows share a block; padded with the scratch sentinel,
      whose rewrite-to-zeros is harmless by definition);
    - ``filled``: (T,) valid tokens in each touched block AFTER the step —
      rows at or past it are garbage (stale frees, rejected speculative
      writes) and are zeroed rather than letting them inflate the scale;
    - ``wt``/``wo``: per new token, the touched-index and in-block offset
      (invalid tokens point at the pad entry, whose ``filled`` is 0).

    Only EXCLUSIVELY-OWNED blocks are ever written (copy-on-write gives a
    slot a private copy before it touches a shared block), so
    requantization never perturbs bytes another slot or the prefix cache
    can read. Per-token drift from repeated requantization of a hot block
    is bounded by the documented tolerance contract (docs/parity.md).

    Returns the updated layer dict plus the max absolute quantization
    error over this step's live rows — computed only when
    ``measure_error`` (the engine's debug mode; it is an extra dequantize
    + abs + max over every touched block, and as a program OUTPUT it
    could never be dead-code-eliminated, so the hot path must not carry
    it), else an exact 0.0 scalar."""
    bs = pool["k"].shape[1]
    T = touched.shape[0]
    rows_live = (jnp.arange(bs)[None, :] < filled[:, None])[..., None, None]
    out = {}
    qerr = jnp.float32(0.0)
    for name, new in (("k", new_k), ("v", new_v)):
        codes, scale = pool[name], pool[name + "_scale"]
        staged = dequantize_blocks(codes[touched], scale[touched])
        flat = staged.reshape(T * bs, *staged.shape[2:])
        flat = flat.at[wt * bs + wo].set(new.astype(jnp.float32))
        staged = jnp.where(rows_live, flat.reshape(staged.shape), 0.0)
        q_codes, q_scale = quantize_blocks(staged, codes.dtype)
        if measure_error:
            qerr = jnp.maximum(qerr, jnp.max(jnp.where(
                rows_live,
                jnp.abs(staged - dequantize_blocks(q_codes, q_scale)),
                0.0)))
        out[name] = codes.at[touched].set(q_codes)
        out[name + "_scale"] = scale.at[touched].set(q_scale)
    return out, qerr


# -- fleet block shipping (export/import of physical blocks) -----------------

def kv_fingerprint(cfg: TransformerConfig, scfg: ServingConfig) -> str:
    """Compatibility fingerprint of a pool's BLOCK PAYLOAD layout — the
    namespace key of the fleet KV plane's bucket layout. Two engines may
    exchange block bytes iff their fingerprints match: same per-block
    geometry (block_size, kv_heads, d_head, n_layers) and same storage
    representation (model dtype or quantized code dtype). It deliberately
    ignores everything that does NOT change a block's bytes (n_blocks,
    slots, chunking, spec_k), so differently-sized pools still share."""
    parts = (cfg.n_layers, cfg.kv_heads, cfg.d_head,
             str(jnp.dtype(cfg.dtype)), scfg.block_size,
             scfg.kv_dtype or "model")
    return hashlib.blake2b(repr(parts).encode(), digest_size=8).hexdigest()


def block_payload_nbytes(cfg: TransformerConfig, scfg: ServingConfig) -> int:
    """Exact byte length of one exported block payload — the importer's
    validation gate (a payload of any other length is treated as a miss,
    never written into the pool)."""
    if scfg.kv_dtype in QUANT_DTYPES:
        d_bytes = (cfg.d_head // 2 if scfg.kv_dtype == "int4"
                   else cfg.d_head)
        per_layer = 2 * scfg.block_size * cfg.kv_heads * d_bytes
        per_layer += 2 * cfg.kv_heads * 4          # k_scale + v_scale rows
    else:
        per_layer = (2 * scfg.block_size * cfg.kv_heads * cfg.d_head
                     * jnp.dtype(cfg.dtype).itemsize)
    return cfg.n_layers * per_layer


def export_block_bytes(pools: List[dict], block: int) -> bytes:
    """ONE physical block's bytes across every layer, in the deterministic
    (layer, sorted leaf name) order — codes AND scale sidecars for
    quantized pools, raw model-dtype values otherwise. The unit the fleet
    KV plane ships: for int8/fp8 pools this is exactly the 1-byte codes
    plus the per-(block, kv-head) fp32 scales, ~4× cheaper than fp32.
    Round-trips bit-faithfully through :func:`split_block_bytes` +
    :func:`write_block` (every leaf's leading axis is n_blocks, so
    ``leaf[block]`` is the complete per-block slice)."""
    return b"".join(
        np.asarray(layer[name][block]).tobytes()
        for layer in pools for name in sorted(layer))


def stage_block_arrays(pools: List[dict], block: int) -> List:
    """The NON-BLOCKING half of :func:`export_block_bytes`: slice one
    physical block out of every layer (deterministic layer, sorted-leaf
    order) WITHOUT forcing the values to the host. Each slice is its own
    device array (enqueued after every already-dispatched pool program,
    so the bytes read later are exactly the pool state at staging time —
    and independent of the pool buffers, so later donations of the pool
    cannot invalidate it). The overlapped engine stages publishes on its
    critical path and lets :func:`staged_block_to_bytes` pay the
    blocking readback off it (a publisher thread, or simply after the
    next dispatch)."""
    return [layer[name][block] for layer in pools for name in sorted(layer)]


def staged_block_to_bytes(staged: List) -> bytes:
    """Force a :func:`stage_block_arrays` staging to host bytes — the
    blocking half; byte-identical to :func:`export_block_bytes` over the
    pool state the staging captured."""
    return b"".join(np.asarray(leaf).tobytes() for leaf in staged)


def split_block_bytes(data: bytes, cfg: TransformerConfig,
                      scfg: ServingConfig) -> Optional[List[dict]]:
    """Inverse of :func:`export_block_bytes`: parse one block payload into
    the per-layer {leaf name: array} pytree :func:`write_block` consumes
    (shapes without the leading n_blocks axis). Returns None — a miss,
    never an exception — when the payload length does not match this
    config's layout (a foreign or torn object in the bucket)."""
    if len(data) != block_payload_nbytes(cfg, scfg):
        return None
    if scfg.kv_dtype in QUANT_DTYPES:
        code_dtype = kv_code_dtype(scfg.kv_dtype)
        leaves = (("k", code_dtype), ("k_scale", jnp.float32),
                  ("v", code_dtype), ("v_scale", jnp.float32))
    else:
        leaves = (("k", cfg.dtype), ("v", cfg.dtype))
    d_store = cfg.d_head // 2 if scfg.kv_dtype == "int4" else cfg.d_head
    shape = (scfg.block_size, cfg.kv_heads, d_store)
    out: List[dict] = []
    offset = 0
    for _ in range(cfg.n_layers):
        layer = {}
        for name, dtype in leaves:
            leaf_shape = (cfg.kv_heads,) if name.endswith("_scale") \
                else shape
            n = int(np.prod(leaf_shape)) * jnp.dtype(dtype).itemsize
            layer[name] = np.frombuffer(
                data, dtype=np.dtype(dtype), count=int(np.prod(leaf_shape)),
                offset=offset).reshape(leaf_shape)
            offset += n
        out.append(layer)
    return out


def write_block(pools: List[dict], dst, values: List[dict]) -> List[dict]:
    """Write one imported block's values (the :func:`split_block_bytes`
    pytree) into physical block ``dst`` of every layer — the import half
    of fleet block shipping, shaped exactly like :func:`copy_block` so
    the engine compiles it once with donated pools. Quantized layers'
    scale sidecars land with their codes; the write is a byte copy, so an
    imported block dequantizes to exactly the publisher's values."""
    return [{name: arr.at[dst].set(vals[name])
             for name, arr in pool.items()}
            for pool, vals in zip(pools, values)]


def write_blocks(pools: List[dict], dsts, values: List[dict]) -> List[dict]:
    """Batched :func:`write_block`: ``dsts`` is (N,) physical block ids
    and every ``values`` leaf carries a leading N axis — ONE device
    dispatch imports a whole shipped prefix chain instead of one
    dispatch per block (the import sits on the admission path, where a
    running batch is waiting on it). Rows may be padded with the scratch
    sentinel as ``dst`` (scratch rewrites are harmless by definition);
    duplicate scratch rows scatter in unspecified order onto bytes
    nothing ever reads."""
    return [{name: arr.at[dsts].set(vals[name])
             for name, arr in pool.items()}
            for pool, vals in zip(pools, values)]


class BlockAllocator:
    """Host-side refcounted free list over the physical blocks (block 0
    excluded — it is the scratch block). Every allocated block carries a
    refcount: ``alloc`` hands out blocks at refcount 1, shared-prefix
    mappings ``incref``, releases ``decref``. A block whose refcount hits 0
    returns to the free list UNLESS the prefix cache has ``retain``-ed it —
    retained refcount-0 blocks sit off both the free list and the live set
    until the cache either resurrects them (``incref``) or evicts them
    (``release``). Tracks the high-water mark of REFERENCED blocks (the
    real working set) so the bench can report what a right-sized pool
    would have needed — cache-retained refcount-0 blocks are excluded:
    they are instantly reclaimable, so counting them would inflate the
    metric toward the full pool size on any cache-on engine.

    Tier-aware residency (docs/parity.md "Tiered KV"): a retained
    refcount-0 block may additionally carry a ``demoted`` mark — its
    bytes have been replicated to the host offload tier, making it the
    preferred eviction victim. The mark never invalidates the HBM copy;
    ``incref`` (a slot touching the block again) simply cancels it, so
    a slot's table can only reference a demoted block while the slot is
    idle, and touching one costs nothing.

    Invariants (property-tested in tests/test_serving_production.py):
    refcounts are never negative; a block is never simultaneously free and
    referenced (or free and retained); only refcount-0 blocks are ever
    evicted back to the free list; demoted ⊆ retained ∧ refcount-0."""

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError(f"n_blocks must be >= 2, got {n_blocks}")
        self.n_blocks = n_blocks
        # Pop from the tail → lowest block numbers first (determinism aid).
        self._free = list(range(n_blocks - 1, SCRATCH_BLOCK, -1))
        self._ref: Dict[int, int] = {}     # block -> refcount (>= 1)
        self._retained: set = set()        # refcount-0 blocks the cache holds
        self._demoted: set = set()         # retained blocks with a host copy
        self.high_water = 0

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        """Blocks off the free list — referenced or cache-retained."""
        return (self.n_blocks - 1) - len(self._free)

    @property
    def referenced(self) -> int:
        """Blocks some slot still holds a reference to — the leak check:
        after a full drain this must be 0 (cache-retained blocks are not
        leaks; they are reclaimable the moment the free list runs dry)."""
        return len(self._ref)

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def is_free(self, block: int) -> bool:
        return block in self._free

    def is_retained(self, block: int) -> bool:
        return block in self._retained

    def is_demoted(self, block: int) -> bool:
        return block in self._demoted

    @property
    def demoted(self) -> int:
        """Retained refcount-0 blocks whose bytes also live on the host
        tier — the instantly-evictable set."""
        return len(self._demoted)

    def mark_demoted(self, block: int) -> None:
        """Record that ``block``'s bytes now live on the host tier. Only
        a retained refcount-0 block qualifies (a referenced block's bytes
        are still being appended to; an unretained one is already free) —
        the caller checks liveness at finalize time and skips blocks that
        were resurrected or evicted while the copy was in flight."""
        self._check(block)
        if block not in self._retained or block in self._ref:
            raise ValueError(
                f"mark_demoted of block {block}: only retained "
                f"refcount-0 blocks demote")
        self._demoted.add(block)

    def _check(self, block: int) -> None:
        if not SCRATCH_BLOCK < block < self.n_blocks:
            raise ValueError(f"invalid block {block}")

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh blocks at refcount 1, or None (nothing allocated) if
        the free list can't cover it — the engine evicts cache-retained
        blocks and retries before resorting to preemption."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n)]
        for b in got:
            self._ref[b] = 1
        self.high_water = max(self.high_water, len(self._ref))
        return got

    def incref(self, block: int) -> int:
        """Add a reference — a new slot mapping a shared (possibly
        retained refcount-0) block. The block must be off the free list."""
        self._check(block)
        if block in self._free:
            raise ValueError(f"incref of free block {block}")
        # Touching a demoted block cancels the mark: its HBM bytes were
        # never invalidated, so resurrection is free (promotion proper —
        # host→HBM — only happens for blocks eviction already reclaimed).
        self._demoted.discard(block)
        self._ref[block] = self._ref.get(block, 0) + 1
        self.high_water = max(self.high_water, len(self._ref))
        return self._ref[block]

    def decref(self, block: int) -> int:
        """Drop a reference; at 0 the block frees unless retained."""
        self._check(block)
        count = self._ref.get(block, 0)
        if count < 1:
            raise ValueError(f"decref of unreferenced block {block}")
        count -= 1
        if count:
            self._ref[block] = count
        else:
            del self._ref[block]
            if block not in self._retained:
                self._free.append(block)
        return count

    def retain(self, block: int) -> None:
        """Prefix-cache hold: keep the block off the free list at ref 0."""
        self._check(block)
        if block in self._free:
            raise ValueError(f"retain of free block {block}")
        self._retained.add(block)

    def release(self, block: int) -> None:
        """Drop the cache hold (eviction); frees the block iff ref 0."""
        self._check(block)
        if block not in self._retained:
            raise ValueError(f"release of unretained block {block}")
        self._retained.discard(block)
        self._demoted.discard(block)
        if block not in self._ref:
            self._free.append(block)

    def free(self, blocks) -> None:
        """Legacy exclusive-owner release: decref blocks that must be at
        refcount 1 (kept for the bucketed path and the PR 5 tests)."""
        for b in blocks:
            self._check(b)
            if b in self._free:
                raise ValueError(f"double free of block {b}")
            self.decref(b)


def chain_block_hashes(token_ids, block_size: int) -> List[bytes]:
    """Content hash of each FULL block of ``token_ids``: the hash of the
    token ids the block covers, chained on the previous block's hash — so a
    block's hash identifies the whole prefix through it, and equal hashes
    mean equal KV contents (same tokens, same positions, same weights)."""
    ids = np.asarray(token_ids, np.int32)
    out: List[bytes] = []
    h = b""
    for i in range(len(ids) // block_size):
        h = hashlib.blake2b(
            h + ids[i * block_size:(i + 1) * block_size].tobytes(),
            digest_size=16).digest()
        out.append(h)
    return out


class PrefixCache:
    """Content-addressed registry of full KV blocks (vLLM-style shared
    prefixes): hash → physical block. Retiring slots ``register`` their
    full blocks; ``lookup`` maps a new prompt's longest cached prefix to
    existing block ids (incref — zero prefill for those tokens). Blocks
    whose refcount is 0 stay retained off the free list and are evicted in
    LRU order ONLY when the free list runs dry, so caching never causes a
    recompute preemption that an uncached engine would not have had."""

    def __init__(self, allocator: BlockAllocator, block_size: int):
        self._alloc = allocator
        self.block_size = block_size
        self._by_hash: Dict[bytes, int] = {}
        self._hash_of: Dict[int, bytes] = {}
        self._lru: Dict[int, int] = {}     # block -> last-touch tick
        self._tick = 0
        self.evictions = 0     # hit/miss/saved counters live on the engine
                               # (admission-level, not per-lookup)

    def __len__(self) -> int:
        return len(self._by_hash)

    def has(self, h: bytes) -> bool:
        """Whether ``h`` is cached — refcount-free membership (the
        prefetch path's skip test; ``lookup`` increfs, this must not)."""
        return h in self._by_hash

    def cached_block(self, h: bytes) -> Optional[int]:
        """Physical block currently registered under ``h``, or None — no
        incref, no LRU touch (demotion-finalize's still-the-same-block
        check: between staging a host copy and forcing its bytes the
        block may be evicted and even recycled under another hash)."""
        return self._by_hash.get(h)

    def _touch(self, block: int) -> None:
        self._tick += 1
        self._lru[block] = self._tick

    def lookup(self, token_ids) -> List[int]:
        """Longest cached FULL-block prefix of ``token_ids``; each matched
        block is incref'd (resurrecting retained refcount-0 blocks) and
        LRU-touched, so a subsequent eviction pass cannot reclaim it out
        from under the admission. Returns the physical block ids (possibly
        empty); the caller decrefs them if the admission falls through."""
        blocks: List[int] = []
        for h in chain_block_hashes(token_ids, self.block_size):
            b = self._by_hash.get(h)
            if b is None:
                break
            blocks.append(b)
        for b in blocks:
            self._alloc.incref(b)
            self._touch(b)
        return blocks

    def register(self, token_ids, table_blocks: Sequence[int]) -> int:
        """Offer a releasing slot's blocks to the cache: every FULL block
        of ``token_ids`` (``table_blocks[i]`` covers tokens [i·bs, (i+1)·bs))
        is registered under its chained hash, or deduped onto an existing
        entry holding the same content. Must be called BEFORE the caller
        decrefs the blocks (registration retains them, so the decref leaves
        them cached instead of free). Returns newly registered count."""
        hashes = chain_block_hashes(token_ids, self.block_size)
        if len(hashes) != len(table_blocks):
            raise ValueError(
                f"register: {len(table_blocks)} blocks but the token ids "
                f"cover {len(hashes)} full blocks — the ids must be exactly "
                "the context that produced the blocks' KV")
        new = 0
        for h, b in zip(hashes, table_blocks):
            have = self._by_hash.get(h)
            if have is not None:
                self._touch(have)   # dedup: caller's decref frees b if sole
                continue
            self._by_hash[h] = b
            self._hash_of[b] = h
            self._alloc.retain(b)
            self._touch(b)
            new += 1
        return new

    def adopt(self, h: bytes, block: int) -> bool:
        """Register an ALLOCATED block imported from the fleet KV plane
        under its content hash ``h`` (the publisher's chained block hash —
        content-addressing is what makes adoption safe: equal hashes mean
        equal token prefixes, so the imported bytes are exactly the KV a
        local prefill of those ids would have produced, up to the
        quantization contract). The block is retained like any registered
        block; the importing slot's reference comes from its allocation.
        Returns False (nothing adopted) when the hash is already cached —
        the caller should have used :meth:`lookup` instead."""
        if h in self._by_hash:
            self._touch(self._by_hash[h])
            return False
        self._by_hash[h] = block
        self._hash_of[block] = h
        self._alloc.retain(block)
        self._touch(block)
        return True

    def hot_entries(self, limit: Optional[int] = None) -> List[Tuple[bytes, int]]:
        """The publishable working set: (hash, block) of every RETAINED
        refcount-0 cached block, most recently touched first — "hot ref-0"
        is exactly the set a replica may read without racing a slot's
        writes (referenced blocks are still being appended to; retained
        ones are frozen until eviction or resurrection)."""
        entries = sorted(
            ((t, b) for b, t in self._lru.items()
             if self._alloc.refcount(b) == 0
             and self._alloc.is_retained(b)),
            reverse=True)
        if limit is not None:
            entries = entries[:limit]
        return [(self._hash_of[b], b) for _, b in entries]

    def cold_entries(self, limit: int) -> List[Tuple[bytes, int]]:
        """Demotion candidates: (hash, block) of up to ``limit`` retained
        refcount-0 cached blocks not yet demoted, COLDEST first — the
        mirror of :meth:`hot_entries` (publish wants the hot end, the
        host tier wants the LRU tail: the blocks eviction would reclaim
        next are exactly the ones worth a host copy first)."""
        entries = sorted(
            (t, b) for b, t in self._lru.items()
            if self._alloc.refcount(b) == 0
            and self._alloc.is_retained(b)
            and not self._alloc.is_demoted(b))
        return [(self._hash_of[b], b) for _, b in entries[:limit]]

    def evict(self, n: int) -> int:
        """Evict up to ``n`` refcount-0 cached blocks back to the free
        list — DEMOTED blocks first (their bytes survive on the host
        tier, so reclaiming them loses nothing), then LRU order.
        Referenced blocks are never touched. Returns how many blocks
        were actually reclaimed."""
        victims = sorted(
            (not self._alloc.is_demoted(b), t, b)
            for b, t in self._lru.items()
            if self._alloc.refcount(b) == 0)
        freed = 0
        for _, _, b in victims:
            if freed >= n:
                break
            del self._by_hash[self._hash_of.pop(b)]
            del self._lru[b]
            self._alloc.release(b)
            self.evictions += 1
            freed += 1
        return freed

    def shared_blocks(self) -> int:
        """Registered blocks currently referenced by at least one slot."""
        return sum(1 for b in self._hash_of
                   if self._alloc.refcount(b) > 0)
