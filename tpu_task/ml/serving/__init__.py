"""Serving subsystem: continuous batching over a paged KV cache.

Two halves (see docs/parity.md "Serving cost model" for the contract):

- ``cache``: the paged KV memory — a shared physical block pool per layer
  plus per-slot block tables, host-side :class:`BlockAllocator`. KV bytes
  are O(live tokens) instead of the dense cache's O(slots × max_len).
- ``model`` + ``engine``: bucketed-length prefill and a single jitted
  decode step over a fixed slot array, driven by an iteration-level
  scheduler (:class:`ServingEngine`) that admits queued requests into free
  slots every step and retires finished ones immediately.

Both halves decode through the SAME attention core as the offline
``generate`` path (``ml.ops.attention.gqa_cached_attention``), so paged
and dense caches are bit-exact at fp32 — greedy tokens from the engine
are pinned identical to ``generate``'s in the tier-1 suite.
"""

from tpu_task.ml.serving.cache import (
    SCRATCH_BLOCK,
    SERVING_POOL_RULES,
    BlockAllocator,
    ServingConfig,
    dense_cache_bytes,
    init_pools,
    kv_shard_bytes,
    kv_token_bytes,
    paged_cache_bytes,
    pool_pspecs,
)
from tpu_task.ml.serving.engine import Request, ServingEngine
from tpu_task.ml.serving.model import (
    greedy_decode_step,
    paged_decode_step,
    paged_prefill,
    sample_tokens,
)

__all__ = [
    "SCRATCH_BLOCK",
    "SERVING_POOL_RULES",
    "BlockAllocator",
    "Request",
    "ServingConfig",
    "ServingEngine",
    "dense_cache_bytes",
    "greedy_decode_step",
    "init_pools",
    "kv_shard_bytes",
    "kv_token_bytes",
    "paged_cache_bytes",
    "paged_decode_step",
    "paged_prefill",
    "pool_pspecs",
    "sample_tokens",
]
