"""Serving subsystem: continuous batching over a paged KV cache.

Two halves (see docs/parity.md "Serving cost model" for the contract):

- ``cache``: the paged KV memory — a shared physical block pool per layer
  plus per-slot block tables, host-side :class:`BlockAllocator`. KV bytes
  are O(live tokens) instead of the dense cache's O(slots × max_len).
- ``model`` + ``engine``: chunked (or legacy bucketed) prefill and a
  single jitted decode step over a fixed slot array, driven by an
  iteration-level scheduler (:class:`ServingEngine`) that admits queued
  requests into free slots every step and retires finished ones
  immediately.

Production-traffic pieces ride the same substrate (ROADMAP item 2): a
refcounted content-hash :class:`PrefixCache` (shared-prefix admissions
prefill only the O(new tokens) tail, copy-on-write on shared partial
blocks, LRU eviction only when the free list runs dry), Sarathi-style
chunked prefill folded into the fused step, and speculative decoding
(draft proposals scored by one fused multi-token target step, rejection
sampling keeps the output distribution exact).

Both halves decode through the SAME attention core as the offline
``generate`` path (``ml.ops.attention.gqa_cached_attention``), so paged
and dense caches are bit-exact at fp32 — greedy tokens from the engine
are pinned identical to ``generate``'s in the tier-1 suite, with the
cache on or off, chunked or bucketed, speculative or not.
"""

from tpu_task.ml.serving.cache import (
    SCRATCH_BLOCK,
    SERVING_POOL_RULES,
    BlockAllocator,
    PrefixCache,
    ServingConfig,
    blocks_in_budget,
    chain_block_hashes,
    dense_cache_bytes,
    dequantize_blocks,
    init_pools,
    kv_block_bytes,
    kv_shard_bytes,
    kv_token_bytes,
    paged_cache_bytes,
    pool_pspecs,
    quantize_blocks,
    quantized_append,
)
from tpu_task.ml.serving.engine import (
    DrainTimeout,
    Request,
    ServingEngine,
    resolve_decode_impl,
)
from tpu_task.ml.serving.model import (
    greedy_decode_step,
    paged_decode_step,
    paged_multitoken_logits,
    paged_prefill,
    sample_tokens,
)

__all__ = [
    "SCRATCH_BLOCK",
    "SERVING_POOL_RULES",
    "BlockAllocator",
    "DrainTimeout",
    "PrefixCache",
    "Request",
    "ServingConfig",
    "ServingEngine",
    "blocks_in_budget",
    "chain_block_hashes",
    "dense_cache_bytes",
    "dequantize_blocks",
    "greedy_decode_step",
    "init_pools",
    "kv_block_bytes",
    "kv_shard_bytes",
    "kv_token_bytes",
    "paged_cache_bytes",
    "paged_decode_step",
    "paged_prefill",
    "pool_pspecs",
    "quantize_blocks",
    "quantized_append",
    "resolve_decode_impl",
    "sample_tokens",
]
