"""Host-RAM KV offload tier: the middle rung of the block hierarchy.

The block export/import seam (``cache.export_block_bytes`` /
``split_block_bytes`` / ``write_block``) serializes any physical block
byte-faithfully; PR 14 used it as a SHARING plane (the kvfleet bucket).
This module uses the same payloads as a MEMORY tier: a budgeted,
content-addressed store of block bytes in host RAM, sitting between the
paged HBM pools and the bucket.

    HBM pool  ──demote──▶  HostKvTier  ──spill──▶  kvfleet bucket
       ▲                       │                        │
       └──────promote──────────┴───────fetch────────────┘

* **Demote** — the engine replicates cold retained refcount-0 cached
  blocks (the prefix cache's LRU tail — exactly the blocks eviction
  would reclaim next) into the tier on the overlap seam: the device
  slice is staged non-blocking (``stage_block_arrays``) while a program
  is in flight, and the bytes are forced at the consume edge, where the
  host is already blocked on the device — migration hides under the
  in-flight program (``goodput.host_gap_frac`` stays ~0).
* **Promote** — admission's hash-chain import consults this tier BEFORE
  the fleet bucket (RAM beats a network object store by orders of
  magnitude): a hit hands back the exact exported payload, which the
  engine writes into a fresh HBM block and re-registers in its prefix
  cache. ``prefetch_chain`` rides the same lookup, so the router's
  session-affinity prefetch hints warm HBM from host RAM ahead of the
  next turn.
* **Spill** — entries past the block budget evict LRU-first into a
  caller-provided sink (the engine wires ``FleetKvClient.ship_bytes``
  when a fleet plane is attached; with no sink they drop, and the miss
  degrades to recompute-from-prefix — the PR 14 staleness contract's
  arm, never a wrong stream).

The tier is deliberately dumb: a dict of immutable ``bytes`` payloads
keyed by the chained content hash, LRU-ordered by dict insertion order.
Content addressing is the whole correctness story — a payload is only
ever adopted under the hash naming its exact token prefix, so a stale
or dropped entry can never corrupt a stream, only cost a recompute.
Host "pinning" here is simply keeping the bytes referenced from Python;
the arrays ``split_block_bytes`` later views are zero-copy over them.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["HostKvTier"]


class HostKvTier:
    """Budgeted LRU store: chained block hash → exported block payload.

    ``budget_blocks`` bounds resident entries (one entry is one physical
    block's export payload — ``cache.block_payload_nbytes`` bytes).
    ``spill`` is called with the evicted ``[(hash, payload), ...]``
    batch whenever an insert pushes the tier over budget; exceptions
    from the sink are swallowed (a failed spill loses only cache, the
    recompute fallback covers it).
    """

    def __init__(self, budget_blocks: int,
                 spill: Optional[Callable[[List[Tuple[bytes, bytes]]],
                                          None]] = None):
        if budget_blocks < 1:
            raise ValueError(
                f"budget_blocks must be >= 1, got {budget_blocks}")
        self.budget_blocks = budget_blocks
        self._spill = spill
        self._entries: Dict[bytes, bytes] = {}   # insertion order = LRU
        self.hits = 0
        self.misses = 0
        self.spilled_blocks = 0
        self.dropped_blocks = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, h: bytes) -> bool:
        return h in self._entries

    @property
    def resident_bytes(self) -> int:
        return sum(len(p) for p in self._entries.values())

    def put(self, h: bytes, payload: bytes) -> None:
        """Insert (or LRU-refresh) one block payload; evicts the LRU
        tail past the budget into the spill sink."""
        self._entries.pop(h, None)
        self._entries[h] = payload
        over = len(self._entries) - self.budget_blocks
        if over <= 0:
            return
        victims = []
        for old in list(self._entries):
            if len(victims) >= over:
                break
            victims.append((old, self._entries.pop(old)))
        if self._spill is not None:
            try:
                self._spill(victims)
                self.spilled_blocks += len(victims)
                return
            except OSError:
                pass                    # dropped below — cache, not truth
        self.dropped_blocks += len(victims)

    def get(self, h: bytes) -> Optional[bytes]:
        """One payload by hash (LRU-touching), or None. The entry STAYS
        resident — a promoted block may be evicted from HBM again before
        the tier's LRU would have dropped it, and the bytes are
        immutable, so keeping them costs nothing extra."""
        payload = self._entries.pop(h, None)
        if payload is None:
            self.misses += 1
            return None
        self._entries[h] = payload      # re-insert = LRU touch
        self.hits += 1
        return payload

    def chain_depth(self, hashes) -> int:
        """Consecutive-leading-hit depth of a hash chain (the
        ``FleetKvIndex.chain_depth`` contract: a chain with a hole stops
        at the hole — blocks past it would leave a KV gap no import can
        fill). Membership only; no LRU touch."""
        depth = 0
        for h in hashes:
            if h not in self._entries:
                break
            depth += 1
        return depth

    def stats(self) -> dict:
        return {
            "resident_blocks": len(self._entries),
            "budget_blocks": self.budget_blocks,
            "resident_bytes": self.resident_bytes,
            "hits": self.hits,
            "misses": self.misses,
            "spilled_blocks": self.spilled_blocks,
            "dropped_blocks": self.dropped_blocks,
        }
