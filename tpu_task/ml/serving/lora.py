"""Paged LoRA adapters: multi-tenant fine-tunes inside the ONE fused step.

The fleet serves one base model per service; "thousands of fine-tunes"
must not mean thousands of fleets. LoRA (Hu et al., 2021) makes a tenant
a pair of thin matrices per layer — ``h += ((x @ A) * scale) @ B`` with
``A: (d, r)``, ``B: (r, d)``, ``r << d`` — small enough that ONE replica
can hold many tenants resident and mix them in one batch (S-LoRA's
batched shrink/expand over Punica-style per-slot gathers).

Residency reuses the machinery that already pages KV: adapter weights
live in a device pool of fixed-shape blocks, a second
:class:`~tpu_task.ml.serving.cache.BlockAllocator` (the allocator is a
pool-size-agnostic refcount/free-list abstraction — nothing in it is
KV-specific) hands blocks out, and cold refcount-0 adapters evict LRU
and reload from the fleet bucket by content hash through the kvfleet
plane, exactly like a demoted KV block.

Pool layout: ``(n_adapter_blocks, 2, rank, d_model)`` in the model
dtype. ONE block holds ONE layer of ONE adapter — ``[b, 0]`` is Aᵀ
(rank, d) and ``[b, 1]`` is B (rank, d) — so an adapter occupies
``n_layers`` blocks and the engine's per-slot gather is a (slots,
n_layers) int32 table, the adapter analogue of a KV block table. Block
0 is the all-zero scratch block: an adapter-less slot's table rows
point at it, its gathered Aᵀ/B are exact zeros, and the delta it adds
is an exact 0.0 at fp32 — the rank-0 no-op that keeps adapter-less
streams bit-identical to a LoRA-free engine while paying only the one
gather plus two thin matmuls (the pinned ≤ 5% overhead). Adapters
trained at a smaller rank zero-pad to the pool rank; the padded rows
contribute the same exact 0.0.

The delta applies PER LAYER as a parallel branch around the transformer
block: the fused programs capture each layer's input ``x``, run the
unmodified ``_block``, then add ``apply_lora(x, ...)`` — a
row-independent contraction, so one slot's stream never depends on
which adapters its co-tenants run (the per-request exactness contract,
pinned in tests/test_lora.py against dedicated single-adapter engines).
"""

from __future__ import annotations

import hashlib
from typing import List, Tuple

import jax.numpy as jnp
import numpy as np

__all__ = [
    "adapter_fingerprint",
    "adapter_payload",
    "apply_lora",
    "init_adapter_pool",
    "pack_adapter",
]


def init_adapter_pool(n_adapter_blocks: int, rank: int, d_model: int,
                      dtype=jnp.float32):
    """The device adapter pool: ``(n_adapter_blocks, 2, rank, d_model)``
    zeros. Axis 1 is the (Aᵀ, B) pair; block 0 is the scratch block every
    adapter-less table row points at — all-zero, so its delta is an exact
    0.0 (never allocated, same contract as the KV scratch block)."""
    return jnp.zeros((n_adapter_blocks, 2, rank, d_model), dtype)


def apply_lora(x, pool, blocks, scales):
    """Batched shrink/expand over per-row gathered adapter blocks:
    ``x + apply_lora(x, ...)`` is ``h += ((x @ A) * scale) @ B`` per row.

    ``x``: (rows, w, d) layer-input activations; ``blocks``: (rows,)
    int32 — each row's adapter block for THIS layer (0 = scratch = exact
    no-op); ``scales``: (rows,) float32. The gather is one
    ``pool[blocks]`` (Punica-style per-slot lookup), the contraction two
    rank-thin einsums batched over rows (S-LoRA's shrink/expand). Each
    row's output depends only on its own block and scale — the
    row-independence that makes mixed-batch streams bit-identical to
    dedicated engines."""
    ab = pool[blocks]                       # (rows, 2, rank, d)
    a, b = ab[:, 0], ab[:, 1]
    shrink = jnp.einsum("rwd,rkd->rwk", x, a)
    return jnp.einsum("rwk,rkd->rwd",
                      shrink * scales.astype(x.dtype)[:, None, None], b)


def pack_adapter(layers, rank: int, d_model: int,
                 dtype=np.float32) -> np.ndarray:
    """Normalize one adapter's per-layer (A, B) pairs into the pool's
    block layout: (n_layers, 2, rank, d_model). ``layers`` is a sequence
    of ``{"a": (d, r), "b": (r, d)}`` dicts (or (A, B) tuples) with any
    ``r <= rank`` — smaller ranks zero-pad, and the padded rows multiply
    through as exact zeros, so a rank-2 adapter in a rank-8 pool emits
    the identical stream it would at rank 2."""
    blocks = np.zeros((len(layers), 2, rank, d_model), dtype)
    for i, layer in enumerate(layers):
        if isinstance(layer, dict):
            a, b = layer["a"], layer["b"]
        else:
            a, b = layer
        a = np.asarray(a, dtype)
        b = np.asarray(b, dtype)
        if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
            raise ValueError(
                f"layer {i}: A must be (d, r) and B (r, d) with matching "
                f"r, got {a.shape} and {b.shape}")
        r = a.shape[1]
        if r > rank:
            raise ValueError(
                f"layer {i}: adapter rank {r} exceeds the pool rank "
                f"{rank} (ServingConfig.lora_rank)")
        if a.shape[0] != d_model or b.shape[1] != d_model:
            raise ValueError(
                f"layer {i}: adapter width {a.shape[0]}x{b.shape[1]} "
                f"does not match d_model {d_model}")
        blocks[i, 0, :r] = a.T
        blocks[i, 1, :r] = b
    return blocks


def adapter_payload(blocks: np.ndarray, scale: float) -> bytes:
    """Serialize a packed adapter (plus its scale) to the bytes the fleet
    bucket stores — a fixed header (shape + dtype + scale) then the raw
    block bytes, so the importer can validate geometry before adopting."""
    header = repr((tuple(int(s) for s in blocks.shape),
                   str(blocks.dtype), float(scale))).encode()
    return (len(header).to_bytes(4, "little") + header
            + np.ascontiguousarray(blocks).tobytes())


def split_adapter_payload(data: bytes) -> Tuple[np.ndarray, float]:
    """Inverse of :func:`adapter_payload`. Raises ValueError on any
    malformed/foreign payload — a torn bucket object must read as a
    miss (reload fails loudly), never as wrong weights."""
    if len(data) < 4:
        raise ValueError("truncated adapter payload")
    hlen = int.from_bytes(data[:4], "little")
    header = data[4:4 + hlen].decode()
    shape, dtype, scale = eval(header, {"__builtins__": {}})  # noqa: S307
    blocks = np.frombuffer(data[4 + hlen:], np.dtype(dtype))
    if blocks.size != int(np.prod(shape)):
        raise ValueError(
            f"adapter payload size mismatch: header claims {shape}, "
            f"got {blocks.size} elements")
    return blocks.reshape(shape).copy(), float(scale)


def adapter_fingerprint(blocks: np.ndarray, scale: float) -> str:
    """Content hash of a packed adapter — the bucket key (and dedup
    identity) of the adapter plane, the ``kv_fingerprint``-style
    namespace for adapter payloads: same weights + scale → same hash on
    any replica, so a re-register ships nothing."""
    return hashlib.blake2b(
        adapter_payload(blocks, scale), digest_size=16).hexdigest()


def adapter_bytes(n_layers: int, rank: int, d_model: int,
                  itemsize: int = 4) -> int:
    """Device bytes one resident adapter occupies (its ``n_layers``
    blocks) — the density cost model's unit: adapters-per-replica =
    pool blocks // n_layers."""
    return n_layers * 2 * rank * d_model * itemsize


def validate_lora_tables(blocks: np.ndarray, n_blocks: int) -> None:
    """Host-side sanity check mirrored from the KV allocator's `_check`:
    every table entry is scratch (0) or a valid pool block."""
    arr = np.asarray(blocks)
    if arr.size and (arr.min() < 0 or arr.max() >= n_blocks):
        raise ValueError(
            f"adapter block table entry out of range [0, {n_blocks})")


def lora_pool_bytes(n_adapter_blocks: int, rank: int, d_model: int,
                    itemsize: int = 4) -> int:
    """Total device bytes of the adapter pool — what ``bench.py
    serving`` reports next to the KV pool's byte model."""
    return n_adapter_blocks * 2 * rank * d_model * itemsize


def gather_tables(slot_blocks: np.ndarray, rows: List[int]) -> np.ndarray:
    """Expand per-slot adapter tables (slots, n_layers) to per-row tables
    for a packed program: ``rows[i]`` is the slot owning packed row i
    (-1 = no owner → scratch)."""
    out = np.zeros((len(rows), slot_blocks.shape[1]), np.int32)
    for i, slot in enumerate(rows):
        if slot >= 0:
            out[i] = slot_blocks[slot]
    return out
