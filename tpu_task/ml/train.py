"""Sharded training step for the flagship transformer.

One jitted function: loss → grads → optax update, with parameter/optimizer
shardings derived from the model's logical axes and activations sharded over
the data axes. XLA inserts the psum/reduce-scatter collectives implied by the
shardings; buffers are donated so the update is in-place in HBM.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec

from tpu_task.ml.models import transformer
from tpu_task.ml.parallel.sharding import logical_to_mesh_axes


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.01):
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def init_state(rng, cfg: transformer.TransformerConfig, optimizer=None) -> TrainState:
    optimizer = optimizer or make_optimizer()
    params = transformer.init(rng, cfg)
    opt_state = optimizer.init(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)


def state_pspecs(state: TrainState, cfg: transformer.TransformerConfig, mesh) -> TrainState:
    """PartitionSpecs for a TrainState: optimizer moments follow the params."""
    p_specs = transformer.param_pspecs(cfg, mesh=mesh)

    # optax state embeds copies of the param pytree (ScaleByAdamState.mu/.nu,
    # trace terms, ...). Map each optimizer leaf to the param spec whose tree
    # path is a suffix of the leaf's path — structural, so two same-shaped
    # params with different layouts can't collide. Scalars (counts,
    # schedules) fall through to replicated.
    param_paths = {}
    for path, spec in jax.tree_util.tree_flatten_with_path(
        p_specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
    )[0]:
        param_paths[tuple(str(k) for k in path)] = spec

    def spec_for(path, leaf):
        keys = tuple(str(k) for k in path)
        for start in range(len(keys)):  # longest suffix first
            spec = param_paths.get(keys[start:])
            if spec is not None and jnp.ndim(leaf) == len(spec):
                return spec
        return PartitionSpec()

    opt_specs = jax.tree_util.tree_map_with_path(spec_for, state.opt_state)
    return TrainState(
        step=PartitionSpec(),
        params=p_specs,
        opt_state=opt_specs,
    )


def shard_state(state: TrainState, cfg, mesh) -> Tuple[TrainState, TrainState]:
    """Place a TrainState on the mesh; returns (sharded_state, pspecs)."""
    specs = state_pspecs(state, cfg, mesh)
    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state, specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )
    return sharded, specs


def make_train_step(cfg: transformer.TransformerConfig, optimizer=None, mesh=None,
                    attn_fn=None, donate: bool = True, activation_spec=None,
                    accum_steps: int = 1):
    """Build the jitted (state, batch) → (state, metrics) step.

    With a mesh, in/out shardings pin the state layout and shard the batch
    over the data axes; single-device otherwise. ``activation_spec`` is
    forwarded to the model so e.g. sequence-parallel steps can pin the
    residual stream's seq axis onto the mesh (see make_sp_train_step).

    ``accum_steps > 1`` splits the batch dim into that many equal
    microbatches and accumulates gradients over a ``lax.scan`` before ONE
    optimizer update — activation memory drops to one microbatch's worth
    while the update equals the full-batch step exactly (the loss is a
    token mean over equal-sized microbatches, so mean-of-grads =
    grad-of-mean). The global batch must divide by accum_steps.
    """
    optimizer = optimizer or make_optimizer()
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def loss_and_grads(params, tokens):
        if accum_steps == 1:
            return jax.value_and_grad(transformer.loss_fn)(
                params, cfg, tokens, attn_fn=attn_fn,
                activation_spec=activation_spec)
        batch = tokens.shape[0]
        if batch % accum_steps:
            raise ValueError(f"batch {batch} not divisible by "
                             f"accum_steps {accum_steps}")
        micro = tokens.reshape(accum_steps, batch // accum_steps,
                               *tokens.shape[1:])

        def body(carry, micro_tokens):
            loss_sum, grad_sum = carry
            loss, grads = jax.value_and_grad(transformer.loss_fn)(
                params, cfg, micro_tokens, attn_fn=attn_fn,
                activation_spec=activation_spec)
            return (loss_sum + loss,
                    jax.tree.map(jnp.add, grad_sum, grads)), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.result_type(p)), params)
        (loss_sum, grad_sum), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zeros), micro)
        scale = 1.0 / accum_steps
        return loss_sum * scale, jax.tree.map(
            lambda g: g * scale, grad_sum)

    def step(state: TrainState, tokens):
        loss, grads = loss_and_grads(state.params, tokens)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(step=state.step + 1, params=params, opt_state=opt_state)
        return new_state, {"loss": loss, "grad_norm": gnorm}

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    batch_spec = logical_to_mesh_axes(("batch", "seq"), mesh=mesh)

    def jit_with_state(state: TrainState):
        specs = state_pspecs(state, cfg, mesh)
        state_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )
        return jax.jit(
            step,
            in_shardings=(state_shardings, NamedSharding(mesh, batch_spec)),
            out_shardings=(state_shardings, NamedSharding(mesh, PartitionSpec())),
            donate_argnums=(0,) if donate else (),
        )

    return jit_with_state


def make_sp_train_step(cfg: transformer.TransformerConfig, mesh,
                       optimizer=None, donate: bool = True,
                       axis_name: str = "sp", context_parallel: str = "zigzag"):
    """Sequence-parallel (long-context) training step.

    One document's activations shard over the ``sp`` mesh axis; the fused
    loss reduces globally, and parameters/optimizer state replicate over
    sp (they carry no seq axis) while following the usual logical rules on
    any other mesh axes. Combine with dp in the same mesh for batch
    parallelism: ``make_mesh(n, axis_names=("dp", "sp"), axis_sizes=(a, b))``.

    ``context_parallel`` picks how attention crosses the shards:

    - ``"zigzag"`` (default): balanced causal ring — k/v circulate, ~half
      the uniform ring's attention FLOPs, parallel degree unbounded by the
      head count. 2 × sp (the stripe count) must divide the MODEL sequence
      length, i.e. feed token arrays of length (2·sp·k) + 1.
    - ``"ulysses"``: two all_to_all reshards (seq↔heads) around one
      full-length fused attention call (the flash kernel on TPU). Needs
      ``heads % sp == 0``; sp must divide the model sequence length.
    """
    from tpu_task.ml.parallel.ring_attention import zigzag_ring_attention
    from tpu_task.ml.parallel.ulysses import ulysses_attention

    # Resolve the batch placement from the logical rules (dp and/or fsdp,
    # filtered to this mesh) so the activation constraint, the attention
    # shard_map batch spec, and make_train_step's token sharding all agree
    # — a mismatch would all-gather the batch dim every layer and compute
    # attention redundantly on every replica.
    batch_axes = logical_to_mesh_axes(("batch",), mesh=mesh)[0]

    # GQA note: k/v widen to the query head count BEFORE crossing shards,
    # so the ring/all_to_all traffic does not see GQA's narrow-kv saving;
    # keeping the wire format narrow would need grouped-attention support
    # inside the ring block primitives — a future optimization, traded
    # here for exactness through the existing well-tested paths.
    if context_parallel == "zigzag":
        def attn(q, k, v):
            k = transformer.expand_kv(k, cfg.n_heads)
            v = transformer.expand_kv(v, cfg.n_heads)
            return zigzag_ring_attention(q, k, v, mesh, axis_name=axis_name,
                                         batch_axes=batch_axes)
    elif context_parallel == "ulysses":
        def attn(q, k, v):
            k = transformer.expand_kv(k, cfg.n_heads)
            v = transformer.expand_kv(v, cfg.n_heads)
            return ulysses_attention(q, k, v, mesh, axis_name=axis_name,
                                     batch_axes=batch_axes)
    else:
        raise ValueError(f"unknown context_parallel {context_parallel!r} "
                         "(use 'zigzag' or 'ulysses')")

    activation_spec = NamedSharding(
        mesh, PartitionSpec(batch_axes, axis_name, None))
    return make_train_step(cfg, optimizer=optimizer, mesh=mesh,
                           attn_fn=attn, donate=donate,
                           activation_spec=activation_spec)
