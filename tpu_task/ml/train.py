"""Sharded training step for the flagship transformer.

One jitted function: loss → grads → optax update, with parameter/optimizer
shardings derived from the model's logical axes and activations sharded over
the data axes. XLA inserts the psum/reduce-scatter collectives implied by the
shardings; buffers are donated so the update is in-place in HBM.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec

from tpu_task.ml.models import transformer
from tpu_task.ml.parallel.sharding import (
    PartitionPlan,
    compile_step,
    device_put_tree,
    logical_to_mesh_axes,
    mesh_batch_axes,
    spec_leaves_with_paths,
)


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.01):
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def init_state(rng, cfg: transformer.TransformerConfig, optimizer=None) -> TrainState:
    optimizer = optimizer or make_optimizer()
    params = transformer.init(rng, cfg)
    opt_state = optimizer.init(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)


def _opt_specs_like(p_specs, opt_state):
    """Map optimizer-state leaves to the param spec whose tree path is a
    suffix of the leaf's path.

    optax state embeds copies of the param pytree (ScaleByAdamState.mu/.nu,
    trace terms, ...); suffix matching is structural, so two same-shaped
    params with different layouts can't collide. Scalars (counts,
    schedules) fall through to replicated."""
    param_paths = dict(spec_leaves_with_paths(p_specs))

    def spec_for(path, leaf):
        keys = tuple(str(k) for k in path)
        for start in range(len(keys)):  # longest suffix first
            spec = param_paths.get(keys[start:])
            if spec is not None and jnp.ndim(leaf) >= len(spec):
                return spec
        return PartitionSpec()

    return jax.tree_util.tree_map_with_path(spec_for, opt_state)


def state_pspecs(state: TrainState, cfg: transformer.TransformerConfig, mesh) -> TrainState:
    """PartitionSpecs for a TrainState: optimizer moments follow the params."""
    p_specs = transformer.param_pspecs(cfg, mesh=mesh)
    return TrainState(
        step=PartitionSpec(),
        params=p_specs,
        opt_state=_opt_specs_like(p_specs, state.opt_state),
    )


def shard_state(state: TrainState, cfg, mesh) -> Tuple[TrainState, TrainState]:
    """Place a TrainState on the mesh; returns (sharded_state, pspecs)."""
    specs = state_pspecs(state, cfg, mesh)
    return device_put_tree(state, specs, mesh), specs


def _token_shard_factor(mesh, activation_spec) -> int:
    """How many ways the (batch, seq) token grid shards on this mesh —
    trace-time shapes are global, so per-device tile sizing (the fused
    xent auto block) must divide by this. Derived from the activation
    sharding when pinned (it names the batch AND seq axes, e.g. sp), else
    from the logical batch rule."""
    if mesh is None:
        return 1
    if activation_spec is not None:
        spec = getattr(activation_spec, "spec", activation_spec)
    else:
        spec = logical_to_mesh_axes(("batch", "seq"), mesh=mesh)
    factor = 1
    for entry in tuple(spec)[:2]:
        if entry is None:
            continue
        for axis in (entry if isinstance(entry, tuple) else (entry,)):
            factor *= mesh.shape[axis]
    return factor


def make_train_step(cfg: transformer.TransformerConfig, optimizer=None, mesh=None,
                    attn_fn=None, donate: bool = True, activation_spec=None,
                    accum_steps: int = 1, moe_fn=None):
    """Build the jitted (state, batch) → (state, metrics) step.

    With a mesh, in/out shardings pin the state layout and shard the batch
    over the data axes; single-device otherwise. ``activation_spec`` is
    forwarded to the model so e.g. sequence-parallel steps can pin the
    residual stream's seq axis onto the mesh (see make_sp_train_step).

    ``accum_steps > 1`` splits the batch dim into that many equal
    microbatches and accumulates gradients over a ``lax.scan`` before ONE
    optimizer update — activation memory drops to one microbatch's worth
    while the update equals the full-batch step exactly (the loss is a
    token mean over equal-sized microbatches, so mean-of-grads =
    grad-of-mean). The global batch must divide by accum_steps.
    """
    optimizer = optimizer or make_optimizer()
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    token_shards = _token_shard_factor(mesh, activation_spec)

    def loss_and_grads(params, tokens):
        if accum_steps == 1:
            return jax.value_and_grad(transformer.loss_fn)(
                params, cfg, tokens, attn_fn=attn_fn,
                activation_spec=activation_spec, moe_fn=moe_fn,
                token_shards=token_shards)
        batch = tokens.shape[0]
        if batch % accum_steps:
            raise ValueError(f"batch {batch} not divisible by "
                             f"accum_steps {accum_steps}")
        micro = tokens.reshape(accum_steps, batch // accum_steps,
                               *tokens.shape[1:])

        def body(carry, micro_tokens):
            loss_sum, grad_sum = carry
            loss, grads = jax.value_and_grad(transformer.loss_fn)(
                params, cfg, micro_tokens, attn_fn=attn_fn,
                activation_spec=activation_spec, moe_fn=moe_fn,
                token_shards=token_shards)
            return (loss_sum + loss,
                    jax.tree.map(jnp.add, grad_sum, grads)), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.result_type(p)), params)
        (loss_sum, grad_sum), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zeros), micro)
        scale = 1.0 / accum_steps
        return loss_sum * scale, jax.tree.map(
            lambda g: g * scale, grad_sum)

    def step(state: TrainState, tokens):
        loss, grads = loss_and_grads(state.params, tokens)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(step=state.step + 1, params=params, opt_state=opt_state)
        return new_state, {"loss": loss, "grad_norm": gnorm}

    if mesh is None:
        return compile_step(step, PartitionPlan(
            donate=(0,) if donate else ()))

    batch_spec = logical_to_mesh_axes(("batch", "seq"), mesh=mesh)

    def jit_with_state(state: TrainState):
        specs = state_pspecs(state, cfg, mesh)
        return compile_step(step, PartitionPlan(
            mesh=mesh,
            in_specs=(specs, batch_spec),
            out_specs=(specs, PartitionSpec()),
            donate=(0,) if donate else (),
        ))

    return jit_with_state


def pp_stack_params(params, n_stages: int):
    """Regular flagship params → pipeline-parallel layout.

    ``{"embed", "final_norm", "unembed", "stages"}`` where ``stages`` leaves
    carry a leading (n_stages, layers_per_stage) prefix — stage-sharded over
    ``pp``, each stage owning a contiguous slice of layers."""
    n_layers = len(params["layers"])
    if n_layers % n_stages:
        raise ValueError(f"n_layers {n_layers} not divisible by "
                         f"{n_stages} pipeline stages")
    lps = n_layers // n_stages
    grouped = [
        jax.tree.map(lambda *leaves: jnp.stack(leaves),
                     *params["layers"][s * lps:(s + 1) * lps])
        for s in range(n_stages)
    ]
    return {
        "embed": params["embed"],
        "final_norm": params["final_norm"],
        "unembed": params["unembed"],
        "stages": jax.tree.map(lambda *leaves: jnp.stack(leaves), *grouped),
    }


def pp_unstack_params(pp_params):
    """Inverse of :func:`pp_stack_params` (for checkpoint interchange and
    the equivalence tests)."""
    stages = pp_params["stages"]
    leaves = jax.tree.leaves(stages)
    n_stages, lps = leaves[0].shape[0], leaves[0].shape[1]
    layers = [
        jax.tree.map(lambda p: p[s, j], stages)
        for s in range(n_stages) for j in range(lps)
    ]
    return {
        "embed": pp_params["embed"],
        "final_norm": pp_params["final_norm"],
        "unembed": pp_params["unembed"],
        "layers": layers,
    }


def init_pp_state(rng, cfg: transformer.TransformerConfig, n_stages: int,
                  optimizer=None) -> TrainState:
    """TrainState in pipeline layout — init equals the sequential init
    exactly (pp_stack_params of the same transformer.init)."""
    optimizer = optimizer or make_optimizer()
    params = pp_stack_params(transformer.init(rng, cfg), n_stages)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=optimizer.init(params))


def pp_state_pspecs(state: TrainState, mesh, axis_name: str = "pp") -> TrainState:
    """PartitionSpecs for a pipeline TrainState: stage-stacked leaves shard
    their leading stage axis over ``pp``; embed/head replicate."""
    p_specs = {
        "embed": PartitionSpec(),
        "final_norm": PartitionSpec(),
        "unembed": PartitionSpec(),
        "stages": jax.tree.map(lambda _: PartitionSpec(axis_name),
                               state.params["stages"]),
    }
    return TrainState(
        step=PartitionSpec(),
        params=p_specs,
        opt_state=_opt_specs_like(p_specs, state.opt_state),
    )


def shard_pp_state(state: TrainState, mesh,
                   axis_name: str = "pp") -> Tuple[TrainState, TrainState]:
    specs = pp_state_pspecs(state, mesh, axis_name)
    return device_put_tree(state, specs, mesh), specs


def make_pp_train_step(cfg: transformer.TransformerConfig, mesh,
                       n_microbatches: int, optimizer=None,
                       donate: bool = True, axis_name: str = "pp"):
    """Pipeline-parallel flagship training step (1F1B schedule).

    The REAL transformer layers split into ``pp`` contiguous stages (not
    toy stage fns): embedding runs before the pipeline (its gradient comes
    back through the 1F1B ``dx`` output), final norm + unembed + fused
    cross-entropy are the pipeline head evaluated per microbatch by the
    last stage, and each stage's blocks recompute their forward in the
    backward (activation recomputation). One optimizer update per step —
    equals the sequential full-batch step exactly (microbatch token counts
    are equal, so mean-of-microbatch-means = full mean; pinned in
    tests/test_ml_moe_pipeline.py).

    Takes/returns TrainStates in the :func:`pp_stack_params` layout.
    """
    from tpu_task.ml.parallel.pipeline import pipeline_train

    optimizer = optimizer or make_optimizer()
    n_stages = mesh.shape[axis_name]
    if cfg.n_layers % n_stages:
        raise ValueError(f"n_layers {cfg.n_layers} not divisible by "
                         f"{n_stages} pipeline stages")
    if any(cfg.is_moe_layer(i) for i in range(cfg.n_layers)):
        raise ValueError("pipeline step supports dense layers only "
                         "(MoE layers go through make_moe_train_step)")
    lps = cfg.n_layers // n_stages
    # dp×pp composition: any data axes in the mesh shard the batch dim
    # (each dp group pipelines its own slice; grads/loss dp-average inside
    # pipeline_train). Resolved from the shared helper like every other
    # step builder so token sharding and the shard_map specs agree.
    batch_axes = mesh_batch_axes(mesh)

    def attn(q, k, v):
        from tpu_task.ml.ops.attention import dot_product_attention

        return dot_product_attention(
            q, transformer.expand_kv(k, cfg.n_heads),
            transformer.expand_kv(v, cfg.n_heads), True)

    def stage_fn(stage_layers, h):
        # stage_layers leaves: (layers_per_stage, ...) — static unroll.
        for j in range(lps):
            layer = jax.tree.map(lambda p: p[j], stage_layers)
            h, _aux = transformer._block(h, layer, cfg, attn)
        return h

    def head_loss(head, out_mb, tgt_mb):
        h = transformer._rmsnorm(out_mb, head["final_norm"])
        b, s, d = h.shape
        return transformer.fused_xent(
            h.reshape(b * s, d), head["unembed"].astype(cfg.dtype),
            tgt_mb.reshape(-1))

    def step(state: TrainState, tokens):
        params = state.params
        inp, tgt = tokens[:, :-1], tokens[:, 1:]
        x, embed_vjp = jax.vjp(
            lambda table: transformer.embed_lookup(
                table.astype(cfg.dtype), inp),
            params["embed"])
        head = {"final_norm": params["final_norm"],
                "unembed": params["unembed"]}
        loss, stage_grads, head_grads, dx = pipeline_train(
            stage_fn, params["stages"], x, tgt, head_loss, mesh,
            n_microbatches, axis_name=axis_name, head_params=head,
            batch_axes=batch_axes)
        (d_embed,) = embed_vjp(dx.astype(x.dtype))
        grads = {"embed": d_embed,
                 "final_norm": head_grads["final_norm"],
                 "unembed": head_grads["unembed"],
                 "stages": stage_grads}
        updates, opt_state = optimizer.update(grads, state.opt_state, params)
        new_params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               opt_state=opt_state)
        return new_state, {"loss": loss, "grad_norm": gnorm}

    def jit_with_state(state: TrainState):
        specs = pp_state_pspecs(state, mesh, axis_name)
        token_spec = (PartitionSpec(batch_axes, None) if batch_axes
                      else PartitionSpec())
        return compile_step(step, PartitionPlan(
            mesh=mesh,
            in_specs=(specs, token_spec),
            out_specs=(specs, PartitionSpec()),
            donate=(0,) if donate else (),
        ))

    return jit_with_state


def make_moe_train_step(cfg: transformer.TransformerConfig, mesh,
                        optimizer=None, donate: bool = True,
                        axis_name: str = "ep", accum_steps: int = 1):
    """Expert-parallel training step for a MoE flagship config.

    The config's MoE layers (``moe_every``/``n_experts``) dispatch through
    the all_to_all expert exchange over the mesh's ``ep`` axis instead of
    the dense one-hot reference path: experts shard one group per ep slot
    (logical axis "expert" → ep), tokens shard over every data axis in the
    mesh PLUS ep, and each MoE layer's two ``lax.all_to_all``s stay inside
    the ep groups. With ample capacity the step equals the dense-dispatch
    step exactly (pinned in tests/test_ml_moe_pipeline.py).

    The reference analog is TPI's parallelism knob driving the real task,
    not a demo (/root/reference/task/k8s/resources/resource_job.go:135-140)
    — here the ep axis drives the real flagship train step.
    """
    from tpu_task.ml.models import moe

    if mesh.shape.get(axis_name) is None:
        raise ValueError(f"mesh has no {axis_name!r} axis: {mesh.axis_names}")
    if not any(cfg.is_moe_layer(i) for i in range(cfg.n_layers)):
        raise ValueError("config has no MoE layers (set moe_every/n_experts)")
    mcfg = cfg.moe_cfg

    # Tokens shard over the usual data axes INCLUDING ep (the "batch" rule
    # lists ep as a data axis): each ep slot routes its own token shard, so
    # the all_to_all moves capacity buffers, not the whole batch, and the
    # dense compute between MoE layers parallelizes over ep too. Resolving
    # from the shared helper keeps the shard_map spec, the activation
    # constraint, and make_train_step's token sharding in agreement.
    batch_axes = mesh_batch_axes(mesh)
    if axis_name not in batch_axes:
        batch_axes = (*batch_axes, axis_name)

    def moe_fn(layer, h):
        return moe.apply_sharded(layer, mcfg, h, mesh, axis_name=axis_name,
                                 batch_axes=batch_axes)

    activation_spec = NamedSharding(
        mesh, PartitionSpec(batch_axes, None, None))
    return make_train_step(cfg, optimizer=optimizer, mesh=mesh,
                           donate=donate, moe_fn=moe_fn,
                           activation_spec=activation_spec,
                           accum_steps=accum_steps)


def make_sp_train_step(cfg: transformer.TransformerConfig, mesh,
                       optimizer=None, donate: bool = True,
                       axis_name: str = "sp", context_parallel: str = "zigzag"):
    """Sequence-parallel (long-context) training step.

    One document's activations shard over the ``sp`` mesh axis; the fused
    loss reduces globally, and parameters/optimizer state replicate over
    sp (they carry no seq axis) while following the usual logical rules on
    any other mesh axes. Combine with dp in the same mesh for batch
    parallelism: ``make_mesh(n, axis_names=("dp", "sp"), axis_sizes=(a, b))``.

    ``context_parallel`` picks how attention crosses the shards:

    - ``"zigzag"`` (default): balanced causal ring — k/v circulate, ~half
      the uniform ring's attention FLOPs, parallel degree unbounded by the
      head count. 2 × sp (the stripe count) must divide the MODEL sequence
      length, i.e. feed token arrays of length (2·sp·k) + 1.
    - ``"ulysses"``: two all_to_all reshards (seq↔heads) around one
      full-length fused attention call (the flash kernel on TPU). Needs
      ``heads % sp == 0``; sp must divide the model sequence length.
    """
    from tpu_task.ml.parallel.ring_attention import zigzag_ring_attention
    from tpu_task.ml.parallel.ulysses import ulysses_attention

    # Resolve the batch placement from the shared helper so the activation
    # constraint, the attention shard_map batch spec, and
    # make_train_step's token sharding all agree — a mismatch would
    # all-gather the batch dim every layer and compute attention
    # redundantly on every replica. PartitionSpec entries want None (not an
    # empty tuple) for "replicated", hence the `or None`.
    batch_axes = mesh_batch_axes(mesh) or None

    # GQA: k/v cross the shard boundary at KV-head width — the ring's
    # ppermutes and the Ulysses all_to_all move narrow bytes, and the
    # expansion to query width happens inside each shard right before the
    # block kernel (ring_attention._expand_kv / ulysses_attention_shard).
    # sp-GQA stays exactly equal to the replicated step: expansion commutes
    # with the seq sharding (pinned in tests/test_ml_parallel.py, which
    # also asserts the narrow wire format from the compiled HLO).
    if context_parallel == "zigzag":
        def attn(q, k, v):
            return zigzag_ring_attention(q, k, v, mesh, axis_name=axis_name,
                                         batch_axes=batch_axes)
    elif context_parallel == "ulysses":
        def attn(q, k, v):
            return ulysses_attention(q, k, v, mesh, axis_name=axis_name,
                                     batch_axes=batch_axes)
    else:
        raise ValueError(f"unknown context_parallel {context_parallel!r} "
                         "(use 'zigzag' or 'ulysses')")

    activation_spec = NamedSharding(
        mesh, PartitionSpec(batch_axes, axis_name, None))
    return make_train_step(cfg, optimizer=optimizer, mesh=mesh,
                           attn_fn=attn, donate=donate,
                           activation_spec=activation_spec)
