"""Host→device input pipeline: sharded batching with device prefetch.

TPUs starve without overlapped input: batches must be on-device before the
step needs them. This is the minimal, dependency-free input pipeline for the
task library — deterministic epoch shuffling, drop-remainder batching, and a
double-buffered prefetch that places each batch with the step's input
sharding while the previous step runs.
"""

from __future__ import annotations

import collections
import itertools
from typing import Iterable, Iterator, Optional

import jax
import numpy as np


def epoch_batches(data: np.ndarray, labels: Optional[np.ndarray],
                  batch_size: int, *, seed: int = 0,
                  epochs: Optional[int] = None,
                  process_index: Optional[int] = None,
                  process_count: Optional[int] = None,
                  start_step: int = 0) -> Iterator:
    """Shuffled, drop-remainder batches; deterministic per (seed, epoch).

    Multi-host: ``batch_size`` is the GLOBAL batch; with
    ``process_count > 1`` each host yields only its contiguous slice of
    every global batch. The permutation depends only on (seed, epoch), so
    all hosts agree on the global batch with zero communication — the
    orchestrator's `TPU_TASK_WORKER_ID`/`NUM_WORKERS` contract supplies the
    indices (defaults: `jax.process_index()`/`jax.process_count()`).

    Resume: ``start_step`` skips the first N GLOBAL steps, so a restored
    task continues the exact sequence it would have seen — pair it with the
    step restored from the checkpoint. Whole skipped epochs don't pay their
    permutation."""
    n = len(data)
    if batch_size > n:
        raise ValueError(f"batch_size {batch_size} > dataset size {n}")
    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    if batch_size % process_count:
        raise ValueError(f"global batch {batch_size} not divisible by "
                         f"{process_count} processes")
    if not 0 <= process_index < process_count:
        # Fail here, not as a cross-host shape mismatch deep in the sharded
        # step (a 1-based worker id would otherwise slice empty batches).
        raise ValueError(f"process_index {process_index} out of range for "
                         f"process_count {process_count}")
    local = batch_size // process_count
    steps_per_epoch = (n - batch_size) // batch_size + 1
    if start_step < 0:
        raise ValueError(f"start_step must be >= 0, got {start_step}")
    skip = start_step

    epoch_iter = range(epochs) if epochs is not None else itertools.count()
    for epoch in epoch_iter:
        if skip >= steps_per_epoch:
            skip -= steps_per_epoch
            continue
        order = np.random.default_rng(seed + epoch).permutation(n)
        for step, start in enumerate(
                range(0, n - batch_size + 1, batch_size)):
            if step < skip:
                continue
            base = start + process_index * local
            index = order[base:base + local]
            if labels is None:
                yield data[index]
            else:
                yield data[index], labels[index]
        skip = 0


def prefetch_to_device(iterator: Iterable, sharding=None, depth: int = 2):
    """Stage ``depth`` batches ahead on device (double-buffering by default).

    ``sharding``: a NamedSharding (or pytree of them) for the batch — the
    same in_sharding the jitted step declares, so no resharding at step time.
    """
    if depth < 1:
        raise ValueError("depth must be >= 1")

    def place(batch):
        if sharding is None:
            return jax.tree.map(jax.numpy.asarray, batch)
        # device_put accepts a matching pytree of shardings directly.
        return jax.device_put(batch, sharding)

    queue = collections.deque()
    iterator = iter(iterator)
    try:
        for _ in range(depth):
            queue.append(place(next(iterator)))
    except StopIteration:
        pass
    while queue:
        batch = queue.popleft()
        try:
            queue.append(place(next(iterator)))
        except StopIteration:
            pass
        yield batch
