"""Host→device input pipeline: sharded batching with device prefetch.

TPUs starve without overlapped input: batches must be on-device before the
step needs them. This is the minimal, dependency-free input pipeline for the
task library — deterministic epoch shuffling, drop-remainder batching, and a
double-buffered prefetch that places each batch with the step's input
sharding while the previous step runs.
"""

from __future__ import annotations

import collections
import itertools
from typing import Iterable, Iterator, Optional

import jax
import numpy as np


def epoch_batches(data: np.ndarray, labels: Optional[np.ndarray],
                  batch_size: int, *, seed: int = 0,
                  epochs: Optional[int] = None) -> Iterator:
    """Shuffled, drop-remainder batches; deterministic per (seed, epoch)."""
    n = len(data)
    if batch_size > n:
        raise ValueError(f"batch_size {batch_size} > dataset size {n}")
    epoch_iter = range(epochs) if epochs is not None else itertools.count()
    for epoch in epoch_iter:
        order = np.random.default_rng(seed + epoch).permutation(n)
        for start in range(0, n - batch_size + 1, batch_size):
            index = order[start:start + batch_size]
            if labels is None:
                yield data[index]
            else:
                yield data[index], labels[index]


def prefetch_to_device(iterator: Iterable, sharding=None, depth: int = 2):
    """Stage ``depth`` batches ahead on device (double-buffering by default).

    ``sharding``: a NamedSharding (or pytree of them) for the batch — the
    same in_sharding the jitted step declares, so no resharding at step time.
    """
    if depth < 1:
        raise ValueError("depth must be >= 1")

    def place(batch):
        if sharding is None:
            return jax.tree.map(jax.numpy.asarray, batch)
        # device_put accepts a matching pytree of shardings directly.
        return jax.device_put(batch, sharding)

    queue = collections.deque()
    iterator = iter(iterator)
    try:
        for _ in range(depth):
            queue.append(place(next(iterator)))
    except StopIteration:
        pass
    while queue:
        batch = queue.popleft()
        try:
            queue.append(place(next(iterator)))
        except StopIteration:
            pass
        yield batch
