"""XLA/TPU trace capture wired into the task data plane.

The reference ships no tracing or profiling at all — its closest facility is
step-progress logging (SURVEY.md §5; /root/reference/task/common/steps.go:19).
On TPU the record that matters is the XLA profiler trace (TensorBoard's
profile plugin reads it: per-op device timelines, HLO cost breakdowns, MXU
utilization), and the orchestrator's existing data plane gives a free export
path: anything written under the task WORKDIR is picked up by the on-worker
10 s sync loop and lands in the bucket, so ``tpu-task delete``/``storage
pull`` brings traces home with the checkpoints — no extra channel needed.

Usage in a task script::

    from tpu_task.ml import profiling

    with profiling.trace("profiles"):        # explicit dir: always traced
        state, metrics = step(state, batch)

    with profiling.trace():                  # env-gated: no-op unless
        state, metrics = step(state, batch)  # TPU_TASK_PROFILE=<dir> is set

    for step_ix in range(n):                 # or: trace a step window
        with profiling.step_window(step_ix, start=100, stop=105):
            state, metrics = step(state, batch)

    with profiling.annotate("data-load"):    # named span inside a trace
        batch = next(batches)
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager, nullcontext
from typing import Optional

#: One capture at a time: the XLA profiler is process-global state.
_capture_lock = threading.Lock()


def busy() -> bool:
    """Whether a :func:`capture` is currently recording."""
    return _capture_lock.locked()


def capture(log_dir: str, duration_s: float) -> str:
    """Blocking on-demand XLA profiler capture: record ``duration_s``
    seconds of whatever the process is doing into ``log_dir`` (the
    TensorBoard profile-plugin layout). The replica's ``/profile?ms=``
    endpoint runs this on a worker thread with ``log_dir`` under the
    task WORKDIR, so the agent's data sync ships the trace home — the
    same free export path :func:`trace` documents. Raises RuntimeError
    when a capture is already running (the profiler is process-global).

    Best-effort by design: the capture directory always lands, but the
    CPU host tracer has been observed to emit an empty trace in deeply
    nested child processes (a TSL quirk; the device tracer on a real
    TPU backend is the actual target) — readers must treat an empty
    capture as "nothing recorded", never as an error."""
    if not acquire_capture():
        raise RuntimeError("a profiler capture is already running")
    return capture_reserved(log_dir, duration_s)


def acquire_capture() -> bool:
    """Reserve the process-global profiler for a caller that will run
    :func:`capture_reserved` (possibly on another thread). Returns False
    when a capture is already running — callers that must answer a
    concurrent request (the replica's 409) take the reservation HERE,
    synchronously, so two racing requests can never both win."""
    return _capture_lock.acquire(blocking=False)


def capture_reserved(log_dir: str, duration_s: float) -> str:
    """Run one capture under a reservation taken with
    :func:`acquire_capture`; the reservation is released on completion
    (success or failure)."""
    import jax

    try:
        os.makedirs(log_dir, exist_ok=True)
        jax.profiler.start_trace(log_dir)
        try:
            time.sleep(duration_s)
        finally:
            jax.profiler.stop_trace()
    finally:
        _capture_lock.release()
    return log_dir


@contextmanager
def trace(log_dir: Optional[str] = None):
    """Capture an XLA profiler trace of the enclosed block.

    An explicit ``log_dir`` always traces. With ``log_dir=None`` the
    capture is gated on ``TPU_TASK_PROFILE``: unset → no-op (and nothing
    touches the filesystem), set → its value is the trace directory — so
    production scripts leave the call sites in place and opt in per run."""
    import jax

    if log_dir is None:
        log_dir = os.environ.get("TPU_TASK_PROFILE", "")
        if not log_dir:
            yield
            return
    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named span visible on the device timeline (TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def step_window(step: int, *, start: int, stop: int,
                log_dir: Optional[str] = None):
    """Trace only steps in [start, stop) — the usual capture pattern: skip
    compilation/warmup, record a handful of steady-state steps. The
    ``log_dir``/env gating matches :func:`trace`."""
    if start <= step < stop:
        return trace(log_dir)
    return nullcontext()


def device_memory_summary() -> str:
    """Human-readable live-buffer summary per device (HBM pressure at a
    glance; empty string when the runtime doesn't expose stats)."""
    import jax

    lines = []
    for device in jax.devices():
        stats = getattr(device, "memory_stats", lambda: None)()
        if not stats:
            continue
        in_use = stats.get("bytes_in_use", 0)
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        line = f"{device.device_kind} {device.id}: {in_use / 1e9:.2f} GB in use"
        if limit:
            line += f" of {limit / 1e9:.2f} GB ({in_use / limit:.0%})"
        lines.append(line)
    return "\n".join(lines)
