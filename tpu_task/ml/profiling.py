"""XLA/TPU trace capture wired into the task data plane.

The reference ships no tracing or profiling at all — its closest facility is
step-progress logging (SURVEY.md §5; /root/reference/task/common/steps.go:19).
On TPU the record that matters is the XLA profiler trace (TensorBoard's
profile plugin reads it: per-op device timelines, HLO cost breakdowns, MXU
utilization), and the orchestrator's existing data plane gives a free export
path: anything written under the task WORKDIR is picked up by the on-worker
10 s sync loop and lands in the bucket, so ``tpu-task delete``/``storage
pull`` brings traces home with the checkpoints — no extra channel needed.

Usage in a task script::

    from tpu_task.ml import profiling

    with profiling.trace("profiles"):        # explicit dir: always traced
        state, metrics = step(state, batch)

    with profiling.trace():                  # env-gated: no-op unless
        state, metrics = step(state, batch)  # TPU_TASK_PROFILE=<dir> is set

    for step_ix in range(n):                 # or: trace a step window
        with profiling.step_window(step_ix, start=100, stop=105):
            state, metrics = step(state, batch)

    with profiling.annotate("data-load"):    # named span inside a trace
        batch = next(batches)
"""

from __future__ import annotations

import os
from contextlib import contextmanager, nullcontext
from typing import Optional


@contextmanager
def trace(log_dir: Optional[str] = None):
    """Capture an XLA profiler trace of the enclosed block.

    An explicit ``log_dir`` always traces. With ``log_dir=None`` the
    capture is gated on ``TPU_TASK_PROFILE``: unset → no-op (and nothing
    touches the filesystem), set → its value is the trace directory — so
    production scripts leave the call sites in place and opt in per run."""
    import jax

    if log_dir is None:
        log_dir = os.environ.get("TPU_TASK_PROFILE", "")
        if not log_dir:
            yield
            return
    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named span visible on the device timeline (TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def step_window(step: int, *, start: int, stop: int,
                log_dir: Optional[str] = None):
    """Trace only steps in [start, stop) — the usual capture pattern: skip
    compilation/warmup, record a handful of steady-state steps. The
    ``log_dir``/env gating matches :func:`trace`."""
    if start <= step < stop:
        return trace(log_dir)
    return nullcontext()


def device_memory_summary() -> str:
    """Human-readable live-buffer summary per device (HBM pressure at a
    glance; empty string when the runtime doesn't expose stats)."""
    import jax

    lines = []
    for device in jax.devices():
        stats = getattr(device, "memory_stats", lambda: None)()
        if not stats:
            continue
        in_use = stats.get("bytes_in_use", 0)
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        line = f"{device.device_kind} {device.id}: {in_use / 1e9:.2f} GB in use"
        if limit:
            line += f" of {limit / 1e9:.2f} GB ({in_use / limit:.0%})"
        lines.append(line)
    return "\n".join(lines)
