"""Checkpoint-to-workdir: what makes the orchestrator's 10 s data sync useful.

The reference's recovery story is "user script checkpoints into the workdir,
the agent syncs the workdir to the bucket every 10 s, a respawned machine
restores the workdir before restarting" (machine-script.sh.tpl:89,118-124 and
docs/resources/task.md:33-42 — the epoch-file pattern). This module is the
user-script half of that contract for JAX pytrees:

* atomic writes (temp file + rename) so the sync loop never ships a torn file;
* monotonically numbered steps + a LATEST pointer written last;
* restore returns the template pytree's structure/dtypes/shardings;
* :class:`AsyncCheckpointer` — overlapped saves: device→host snapshot on the
  caller, serialization + publish (+ optional direct bucket streaming) on a
  background writer, so frequent preemption-recovery checkpoints cost the
  train loop only the snapshot.
"""

from __future__ import annotations

import json
import os
import queue
import re
import tempfile
import threading
from pathlib import Path
from typing import Any, Iterable, Optional

import jax
import numpy as np

_STEP_RE = re.compile(r"^ckpt-(\d+)\.npz$")


def _write_npz_atomic(directory: Path, final_name: str, arrays: dict) -> Path:
    """Serialize ``arrays`` to ``directory/final_name`` via temp file +
    rename, so the sync loop (and a crash) never observes a torn file."""
    final = directory / final_name
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as handle:
            np.savez(handle, **arrays)
        os.replace(tmp, final)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return final


def save_checkpoint(directory, step: int, tree: Any,
                    keep: Optional[int] = None) -> Path:
    """Write ``ckpt-{step}.npz`` atomically, then update LATEST.

    ``keep``: retain the newest N checkpoints plus, always, the one just
    written (an out-of-order re-save must never delete its own file and
    leave LATEST dangling). The workdir sync mirrors deletions, so
    retention bounds bucket usage too — long runs otherwise accumulate
    every step's full state."""
    if keep is not None and keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    leaves = [np.asarray(l) for l in jax.tree.leaves(tree)]
    arrays = {f"leaf_{i}": a for i, a in enumerate(leaves)}

    final = _write_npz_atomic(directory, f"ckpt-{step}.npz", arrays)

    pointer = directory / "LATEST.tmp"
    pointer.write_text(json.dumps({"step": step, "file": final.name}))
    os.replace(pointer, directory / "LATEST")
    if keep is not None:
        steps = sorted(
            int(match.group(1)) for path in directory.iterdir()
            if (match := _STEP_RE.match(path.name)))
        retained = set(steps[-keep:]) | {step}
        for old in steps:
            if old not in retained:
                (directory / f"ckpt-{old}.npz").unlink(missing_ok=True)
    return final


def latest_step(directory) -> Optional[int]:
    """Highest complete checkpoint step in ``directory``, or None."""
    directory = Path(directory)
    pointer = directory / "LATEST"
    if pointer.exists():
        try:
            meta = json.loads(pointer.read_text())
            if (directory / meta["file"]).exists():
                return int(meta["step"])
        except (ValueError, KeyError):
            pass
    steps = [
        int(m.group(1))
        for p in (directory.iterdir() if directory.is_dir() else [])
        if (m := _STEP_RE.match(p.name))
    ]
    return max(steps) if steps else None


# -- process-sharded checkpoints (multi-host) ---------------------------------
#
# np.asarray on a multi-host sharded jax.Array would gather (or fail: shards
# on other hosts aren't addressable). The sharded format writes, per process,
# only the shards that process holds — ckpt-{step}.shard-{process}.npz with
# entries keyed by each shard's GLOBAL index range — and restore reassembles
# from whichever files hold the ranges the local devices need, so a respawned
# slice restores correctly even if worker/process numbering changed. The
# worker agent syncs each worker's own shard files to the bucket
# (tpu-worker-script.sh.tpl), so the bucket always holds the full set.

_SHARD_RE = re.compile(r"^ckpt-(\d+)\.shard-(\d+)\.npz$")


def _index_key(leaf_index: int, index, shape) -> str:
    """Stable string key for a shard's global index range."""
    parts = []
    for dim, slc in enumerate(index):
        start = 0 if slc.start is None else int(slc.start)
        stop = shape[dim] if slc.stop is None else int(slc.stop)
        parts.append(f"{start}:{stop}")
    return f"leaf_{leaf_index}|" + ",".join(parts)


def save_checkpoint_sharded(directory, step: int, tree: Any,
                            keep: Optional[int] = None) -> Path:
    """Write this process's shards of a (possibly multi-host) pytree.

    Every process calls this; each writes only its addressable, replica-0
    shards. Process 0 also writes a LATEST_SHARDED pointer naming the step
    and the expected shard-file count — restore uses it to reject partial
    sets consistently across hosts (the plain-format LATEST is untouched).

    ``keep``: retain the newest N steps (plus, always, the one just
    written). Each process prunes its OWN old shard files (never a
    sibling's — a slow process may still be writing an older step's shard
    it owns); process 0 also prunes the old per-step manifests. Minimum 2:
    with keep=1 a worker deletes its previous shard the moment it writes
    the new one, and during the inter-worker sync-skew window NO step has
    a complete shard set in the bucket — a preemption there would be
    unrecoverable. More generally ``keep`` must exceed the worst-case
    inter-worker save skew measured in save intervals; 2 covers loops
    that save in lockstep, size it up for loosely-coupled savers."""
    _validate_sharded_keep(keep)
    directory = Path(directory)
    process = jax.process_index()
    arrays = _snapshot_sharded(tree, process)
    final, _pruned = _publish_sharded(
        directory, step, arrays, process, jax.process_count(), keep)
    return final


def _validate_sharded_keep(keep: Optional[int]) -> None:
    if keep is not None and keep < 2:
        raise ValueError(
            f"sharded keep must be >= 2 (got {keep}): with 1 retained "
            "step, inter-worker sync skew leaves windows where no step "
            "has a complete shard set")


def _decoupled(array: np.ndarray) -> np.ndarray:
    """A host array safe to serialize after control returns to the caller.

    ``np.asarray`` of a device shard is a fresh owning buffer when a real
    transfer happened (TPU/GPU) but a zero-copy VIEW of the runtime's
    buffer on the CPU backend — where the train loop's next donated step
    would overwrite it under the background writer. Copy only the views;
    a second memcpy of an already-owning transfer would double the one
    cost the async path is built to minimize."""
    if array.base is None and array.flags.owndata:
        return array
    return np.array(array, copy=True)


def _snapshot_sharded(tree: Any, process: int, copy: bool = False) -> dict:
    """Device→host snapshot of this process's replica-0 addressable shards.

    ``copy=True`` decouples every leaf from caller-owned memory: the async
    pipeline serializes AFTER returning control to the train loop, whose
    next step may donate/overwrite the buffers a zero-copy view aliases."""
    arrays = {}
    for leaf_index, leaf in enumerate(jax.tree.leaves(tree)):
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            shape = leaf.shape
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue  # one copy of replicated shards is enough
                data = np.asarray(shard.data)
                arrays[_index_key(leaf_index, shard.index, shape)] = \
                    _decoupled(data) if copy else data
        else:
            array = np.asarray(leaf)
            if process == 0:  # plain host values: process 0's copy wins
                if copy:
                    # Always copy plain host leaves: np.asarray of a numpy
                    # input IS the caller's array (owning or not), and the
                    # caller may mutate it after save() returns.
                    array = np.array(array, copy=True)
                index = tuple(slice(0, dim) for dim in array.shape)
                arrays[_index_key(leaf_index, index, array.shape)] = array
    return arrays


def _publish_sharded(directory: Path, step: int, arrays: dict, process: int,
                     process_count: int, keep: Optional[int],
                     protect: Iterable[int] = ()) -> tuple:
    """Serialize + atomically publish one process's shard of ``step``.

    Shared by the sync and async paths, so both produce byte-identical
    layouts (same shard filenames, same meta/LATEST_SHARDED contract).
    ``protect``: steps that must survive pruning regardless of age — the
    async writer passes its in-flight set so retention can never delete a
    step another queued save still depends on. Returns ``(final_path,
    pruned_paths)``; the pruned list lets the direct-upload pipeline mirror
    deletions into the bucket."""
    directory.mkdir(parents=True, exist_ok=True)
    final = _write_npz_atomic(
        directory, f"ckpt-{step}.shard-{process}.npz", arrays)

    if process == 0:
        # Reap shard files beyond this topology: a re-save of the same step
        # after a topology SHRINK would otherwise leave stale higher-index
        # shards that make the completeness check (indices == 0..expected-1)
        # reject the step forever.
        for stale in directory.glob(f"ckpt-{step}.shard-*.npz"):
            match = _SHARD_RE.match(stale.name)
            if match and int(match.group(2)) >= process_count:
                try:
                    stale.unlink()
                except OSError:
                    pass
        # Per-step manifest: records THIS step's save-time topology so a
        # later restore under a different process count can still judge the
        # step's completeness by the count it was saved with.
        meta = directory / f"ckpt-{step}.meta.tmp"
        meta.write_text(json.dumps({
            "step": step, "process_count": process_count}))
        os.replace(meta, directory / f"ckpt-{step}.meta")
        # A SEPARATE pointer file: repointing the plain LATEST at a shard
        # file would make latest_step()/restore_checkpoint() chase a
        # nonexistent ckpt-{step}.npz.
        pointer = directory / "LATEST_SHARDED.tmp"
        pointer.write_text(json.dumps({
            "step": step, "file": final.name,
            "process_count": process_count}))
        os.replace(pointer, directory / "LATEST_SHARDED")
    pruned = []
    if keep is not None:
        own = sorted(
            int(match.group(1)) for path in directory.iterdir()
            if (match := _SHARD_RE.match(path.name))
            and int(match.group(2)) == process)
        retained = set(own[-keep:]) | {step} | set(protect)
        for old in own:
            if old in retained:
                continue
            shard_path = directory / f"ckpt-{old}.shard-{process}.npz"
            shard_path.unlink(missing_ok=True)
            pruned.append(shard_path)
            if process == 0:
                meta_path = directory / f"ckpt-{old}.meta"
                meta_path.unlink(missing_ok=True)
                pruned.append(meta_path)
    return final, pruned


# -- async overlapped checkpointing -------------------------------------------
#
# Every sync save stalls the train loop on device→host transfer + npz
# serialization + rename, and frequent checkpoints are exactly what spot/
# preemptible recovery needs (Check-N-Run NSDI '22; Orbax/T5X async). The
# async pipeline splits the save: the caller pays ONLY the device→host
# snapshot; one background writer thread serializes, atomically publishes,
# and (optionally) streams the shard files straight into the task bucket —
# the next training steps overlap all of it.


class AsyncCheckpointError(RuntimeError):
    """A background save (write or bucket upload) failed. Raised on the next
    ``save()``/``wait()``/``close()`` after the failure — async errors are
    deferred, never dropped."""


class AsyncCheckpointer:
    """Overlapped sharded checkpointing: snapshot → background write →
    optional streaming bucket upload.

    ``save(step, tree)`` snapshots this process's replica-0 addressable
    shards to host memory (a copy — the train loop may donate the device
    buffers to its next step) and returns immediately; a single background
    writer thread then serializes and atomically publishes the same files
    ``save_checkpoint_sharded`` would have written (same shard names, same
    meta/LATEST_SHARDED contract — restore via
    :func:`restore_checkpoint_sharded`). The single writer is the barrier:
    overlapping saves queue FIFO and can never interleave their writes.

    ``upload_remote``: a storage connection string (or plain path) naming
    the bucket prefix for this checkpoint directory (e.g.
    ``f"{os.environ['TPU_TASK_DATA_REMOTE']}/checkpoints"`` under the worker
    agent — or pass ``upload_remote="auto"`` to derive exactly that). When
    set, each published step streams straight into the bucket through the
    storage backends (chunked resumable / multipart for large shards) instead
    of waiting for the agent's next whole-directory sync tick; source mtimes
    are preserved so the agent's size+mtime diff skips what was already
    pushed. The remote pointer uploads LAST, so a remote reader never sees
    LATEST_SHARDED name a step whose files haven't landed.

    Failure semantics: a background failure is stored and raised (wrapped in
    :class:`AsyncCheckpointError`) on the next ``save()``/``wait()``/
    ``close()``. A crash mid-save never corrupts the previous step: shard
    files publish via temp-file + rename and restore rejects partial sets.

    Retention: ``keep`` prunes exactly like the sync path, and the writer
    protects every queued/in-flight step from pruning, so a save can never
    delete a step still being written. Multi-host: every process runs its
    own ``AsyncCheckpointer`` over the same directory, like every process
    calls ``save_checkpoint_sharded``.
    """

    def __init__(self, directory, keep: Optional[int] = None,
                 upload_remote: Optional[str] = None,
                 upload_workers: int = 4, max_pending: int = 2):
        _validate_sharded_keep(keep)
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        if upload_remote == "auto":
            upload_remote = resolve_upload_remote(directory)
        self.directory = Path(directory)
        self.keep = keep
        self.upload_remote = upload_remote
        self.upload_workers = upload_workers
        # Bounded: each queued save holds a FULL host copy of the tree, so
        # an unbounded queue is an OOM under saves that outpace the writer.
        # When full, save() blocks until the writer drains — backpressure,
        # never unbounded memory (worst case max_pending+1 copies live).
        self._queue: queue.Queue = queue.Queue(maxsize=max_pending)
        self._lock = threading.Lock()
        self._inflight: set = set()
        self._error: Optional[BaseException] = None
        self._backend = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    # -- train-loop side -----------------------------------------------------
    def save(self, step: int, tree: Any) -> Path:
        """Snapshot ``tree`` and schedule the write; returns the path the
        background writer will publish. Blocked time is the device→host
        snapshot — plus, when ``max_pending`` saves are already queued, the
        wait for the writer to drain one (bounded memory over latency)."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        self._raise_pending()
        process = jax.process_index()
        arrays = _snapshot_sharded(tree, process, copy=True)
        with self._lock:
            self._inflight.add(step)
        self._ensure_writer()
        self._queue.put((step, arrays, process, jax.process_count()))
        return self.directory / f"ckpt-{step}.shard-{process}.npz"

    def wait(self) -> None:
        """Block until every queued save is published (and uploaded, when
        direct upload is on); re-raise any background failure."""
        self._queue.join()
        self._raise_pending()

    def close(self) -> None:
        """Drain the queue, stop the writer, surface any pending failure."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            self._queue.put(None)
            self._thread.join()
        self._raise_pending()

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()

    def _raise_pending(self) -> None:
        with self._lock:
            error, self._error = self._error, None
        if error is not None:
            raise AsyncCheckpointError(
                f"background checkpoint save failed: {error}") from error

    # -- writer side ---------------------------------------------------------
    def _ensure_writer(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer, name="async-checkpoint-writer",
                daemon=True)
            self._thread.start()

    def _writer(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                self._queue.task_done()
                return
            step, arrays, process, process_count = item
            try:
                with self._lock:
                    protect = frozenset(self._inflight - {step})
                final, pruned = _publish_sharded(
                    self.directory, step, arrays, process, process_count,
                    self.keep, protect=protect)
                if self.upload_remote:
                    self._upload_step(step, final, process, pruned)
            except BaseException as error:
                with self._lock:
                    if self._error is None:  # first failure wins
                        self._error = error
            finally:
                with self._lock:
                    self._inflight.discard(step)
                self._queue.task_done()

    def _upload_step(self, step: int, final: Path, process: int,
                     pruned: list) -> None:
        """Stream this step's artifacts into the bucket prefix: shard file
        (+ manifest) first, pointer strictly LAST — the remote durability
        order must match the local publish order. Pruned steps are deleted
        remotely best-effort (the agent's mirror sync also reaps them)."""
        from tpu_task.storage.backends import parallel_map

        backend = self._open_backend()

        def push(name: str) -> None:
            path = self.directory / name
            backend.write_from_file(name, str(path))
            if hasattr(backend, "set_mtime"):
                # Preserved mtimes are what lets the agent's incremental
                # size+mtime diff skip files this pipeline already pushed.
                backend.set_mtime(name, os.path.getmtime(path))

        names = [final.name]
        meta = self.directory / f"ckpt-{step}.meta"
        if process == 0 and meta.exists():
            names.append(meta.name)
        parallel_map([lambda name=name: push(name) for name in names],
                     min(self.upload_workers, len(names)))
        if process == 0 and (self.directory / "LATEST_SHARDED").exists():
            push("LATEST_SHARDED")
        for path in pruned:
            try:
                backend.delete(path.name)
            except Exception:
                pass  # mirror sync reaps leftovers; never fail a save on this

    def _open_backend(self):
        if self._backend is None:
            from tpu_task.storage.backends import open_backend

            self._backend, _ = open_backend(self.upload_remote)
        return self._backend


def resolve_upload_remote(directory) -> Optional[str]:
    """Bucket prefix for direct checkpoint upload under the worker agent:
    ``$TPU_TASK_DATA_REMOTE/<directory relative to the workdir>`` — the
    agent exports that variable, runs the task with cwd=workdir, and
    mirrors the workdir to ``<remote>/data``, so the upload prefix must be
    the same RELATIVE path the mirror uses (``out/ckpts`` → ``data/out/
    ckpts``; a bare basename would upload beside the mirror's copy and the
    next delete pass would reap it as extraneous). None outside an agent,
    and None for directories that escape the workdir (the mirror never
    ships those, so a direct upload would be deleted the same way) —
    AsyncCheckpointer then skips direct upload gracefully."""
    root = os.environ.get("TPU_TASK_DATA_REMOTE", "")
    if not root:
        return None
    relative = os.path.relpath(os.path.abspath(directory), os.getcwd())
    if relative.split(os.sep, 1)[0] == os.pardir:
        return None
    return f"{root.rstrip('/')}/{relative.replace(os.sep, '/')}"


def restore_checkpoint_sharded(directory, template: Any,
                               step: Optional[int] = None) -> Any:
    """Reassemble a sharded checkpoint into ``template``'s shardings.

    Reads every ``ckpt-{step}.shard-*.npz`` present (the workdir restore
    pulls all of them from the bucket) and places, per template leaf, the
    global index ranges each LOCAL device needs — shard files are matched
    by index range, not by process number, so recovery survives process
    renumbering. With no explicit ``step``, tries steps NEWEST → OLDEST and
    falls back past incomplete sets: workers upload shards on independent
    sync loops, so a preemption can land mid-upload and the newest step may
    be partial — the last complete one must still restore.
    """
    directory = Path(directory)
    if step is not None:
        return _restore_sharded_step(directory, template, step)
    steps = sorted({int(m.group(1))
                    for p in (directory.iterdir()
                              if directory.is_dir() else [])
                    if (m := _SHARD_RE.match(p.name))}, reverse=True)
    if not steps:
        raise FileNotFoundError(f"no sharded checkpoint in {directory}")
    # Step eligibility must be decided IDENTICALLY on every host — a
    # per-host "whatever ranges my devices need" check would let different
    # hosts resume from different steps after a partial upload. A step is
    # eligible only when the full shard-file set FOR THAT STEP'S SAVE-TIME
    # TOPOLOGY is present: the per-step manifest when available, the
    # LATEST_SHARDED pointer for its own step, else (legacy checkpoints
    # without a manifest) this topology's process count — never "whatever
    # files happen to be present", which would bless truncated prefixes.
    pointer = directory / "LATEST_SHARDED"
    pointer_step = pointer_count = None
    if pointer.exists():
        try:
            meta = json.loads(pointer.read_text())
            pointer_step = int(meta["step"])
            if meta.get("process_count"):
                pointer_count = int(meta["process_count"])
        except (ValueError, KeyError):
            pass
    last_error: Optional[Exception] = None
    for candidate in steps:
        indices = {int(m.group(2))
                   for p in directory.glob(f"ckpt-{candidate}.shard-*.npz")
                   if (m := _SHARD_RE.match(p.name))}
        expected = None
        manifest = directory / f"ckpt-{candidate}.meta"
        if manifest.exists():
            try:
                expected = int(json.loads(
                    manifest.read_text())["process_count"])
            except (ValueError, KeyError, TypeError):
                pass
        if expected is None and candidate == pointer_step:
            expected = pointer_count
        if expected is None:
            expected = jax.process_count()
        if not indices or indices != set(range(expected)):
            last_error = FileNotFoundError(
                f"step {candidate}: shard indices {sorted(indices)} != "
                f"expected 0..{expected - 1}")
            continue
        try:
            return _restore_sharded_step(directory, template, candidate)
        except Exception as error:  # torn file (BadZipFile), missing entry…
            last_error = error
    raise FileNotFoundError(
        f"no complete sharded checkpoint in {directory} "
        f"(tried steps {steps}): {last_error}")


def _restore_sharded_step(directory: Path, template: Any, step: int) -> Any:
    # NpzFile members decompress lazily on access: index key → handle and
    # load only the ranges this host's devices actually need — each host
    # must NOT materialize the whole global checkpoint (that's the point
    # of sharded restore).
    paths = sorted(directory.glob(f"ckpt-{step}.shard-*.npz"))
    handles = []
    try:
        index: dict = {}
        for path in paths:
            handle = np.load(path)
            handles.append(handle)
            for key in handle.files:
                index[key] = handle

        if not index:
            raise FileNotFoundError(f"no shard files for step {step}")

        def lookup(key: str):
            if key not in index:
                raise FileNotFoundError(
                    f"shard {key} missing at step {step} — incomplete "
                    f"checkpoint ({len(index)} entries present)")
            return index[key][key]

        leaves, treedef = jax.tree.flatten(template)
        restored = []
        for leaf_index, leaf in enumerate(leaves):
            if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
                shape = leaf.shape
                index_map = leaf.sharding.addressable_devices_indices_map(shape)
                device_arrays = []
                for device, device_index in index_map.items():
                    key = _index_key(leaf_index, device_index, shape)
                    device_arrays.append(jax.device_put(
                        lookup(key).astype(leaf.dtype), device))
                restored.append(jax.make_array_from_single_device_arrays(
                    shape, leaf.sharding, device_arrays))
            else:
                array = np.asarray(leaf)
                full = tuple(slice(0, dim) for dim in array.shape)
                restored.append(lookup(_index_key(leaf_index, full,
                                                  array.shape)))
        return jax.tree.unflatten(treedef, restored)
    finally:
        for handle in handles:
            handle.close()


def restore_checkpoint(directory, template: Any, step: Optional[int] = None) -> Any:
    """Restore into ``template``'s structure (dtypes/shardings preserved)."""
    directory = Path(directory)
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {directory}")
    with np.load(directory / f"ckpt-{step}.npz") as data:
        arrays = [data[f"leaf_{i}"] for i in range(len(data.files))]
    leaves, treedef = jax.tree.flatten(template)
    if len(arrays) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(arrays)} leaves, template has {len(leaves)}"
        )
    restored = []
    for arr, leaf in zip(arrays, leaves):
        if hasattr(leaf, "sharding") and hasattr(leaf, "dtype"):
            restored.append(
                jax.device_put(arr.astype(leaf.dtype), leaf.sharding)
            )
        else:
            restored.append(arr)
    return jax.tree.unflatten(treedef, restored)
