"""Checkpoint-to-workdir: what makes the orchestrator's 10 s data sync useful.

The reference's recovery story is "user script checkpoints into the workdir,
the agent syncs the workdir to the bucket every 10 s, a respawned machine
restores the workdir before restarting" (machine-script.sh.tpl:89,118-124 and
docs/resources/task.md:33-42 — the epoch-file pattern). This module is the
user-script half of that contract for JAX pytrees:

* atomic writes (temp file + rename) so the sync loop never ships a torn file;
* monotonically numbered steps + a LATEST pointer written last;
* restore returns the template pytree's structure/dtypes/shardings.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np

_STEP_RE = re.compile(r"^ckpt-(\d+)\.npz$")


def save_checkpoint(directory, step: int, tree: Any) -> Path:
    """Write ``ckpt-{step}.npz`` atomically, then update LATEST."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    leaves = [np.asarray(l) for l in jax.tree.leaves(tree)]
    arrays = {f"leaf_{i}": a for i, a in enumerate(leaves)}

    final = directory / f"ckpt-{step}.npz"
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, final)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise

    pointer = directory / "LATEST.tmp"
    pointer.write_text(json.dumps({"step": step, "file": final.name}))
    os.replace(pointer, directory / "LATEST")
    return final


def latest_step(directory) -> Optional[int]:
    """Highest complete checkpoint step in ``directory``, or None."""
    directory = Path(directory)
    pointer = directory / "LATEST"
    if pointer.exists():
        try:
            meta = json.loads(pointer.read_text())
            if (directory / meta["file"]).exists():
                return int(meta["step"])
        except (ValueError, KeyError):
            pass
    steps = [
        int(m.group(1))
        for p in (directory.iterdir() if directory.is_dir() else [])
        if (m := _STEP_RE.match(p.name))
    ]
    return max(steps) if steps else None


def restore_checkpoint(directory, template: Any, step: Optional[int] = None) -> Any:
    """Restore into ``template``'s structure (dtypes/shardings preserved)."""
    directory = Path(directory)
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {directory}")
    with np.load(directory / f"ckpt-{step}.npz") as data:
        arrays = [data[f"leaf_{i}"] for i in range(len(data.files))]
    leaves, treedef = jax.tree.flatten(template)
    if len(arrays) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(arrays)} leaves, template has {len(leaves)}"
        )
    restored = []
    for arr, leaf in zip(arrays, leaves):
        if hasattr(leaf, "sharding") and hasattr(leaf, "dtype"):
            restored.append(
                jax.device_put(arr.astype(leaf.dtype), leaf.sharding)
            )
        else:
            restored.append(arr)
    return jax.tree.unflatten(treedef, restored)
