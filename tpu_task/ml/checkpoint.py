"""Checkpoint-to-workdir: what makes the orchestrator's 10 s data sync useful.

The reference's recovery story is "user script checkpoints into the workdir,
the agent syncs the workdir to the bucket every 10 s, a respawned machine
restores the workdir before restarting" (machine-script.sh.tpl:89,118-124 and
docs/resources/task.md:33-42 — the epoch-file pattern). This module is the
user-script half of that contract for JAX pytrees:

* atomic writes (temp file + rename) so the sync loop never ships a torn file;
* monotonically numbered steps + a LATEST pointer written last;
* restore returns the template pytree's structure/dtypes/shardings.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np

_STEP_RE = re.compile(r"^ckpt-(\d+)\.npz$")


def save_checkpoint(directory, step: int, tree: Any,
                    keep: Optional[int] = None) -> Path:
    """Write ``ckpt-{step}.npz`` atomically, then update LATEST.

    ``keep``: retain the newest N checkpoints plus, always, the one just
    written (an out-of-order re-save must never delete its own file and
    leave LATEST dangling). The workdir sync mirrors deletions, so
    retention bounds bucket usage too — long runs otherwise accumulate
    every step's full state."""
    if keep is not None and keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    leaves = [np.asarray(l) for l in jax.tree.leaves(tree)]
    arrays = {f"leaf_{i}": a for i, a in enumerate(leaves)}

    final = directory / f"ckpt-{step}.npz"
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, final)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise

    pointer = directory / "LATEST.tmp"
    pointer.write_text(json.dumps({"step": step, "file": final.name}))
    os.replace(pointer, directory / "LATEST")
    if keep is not None:
        steps = sorted(
            int(match.group(1)) for path in directory.iterdir()
            if (match := _STEP_RE.match(path.name)))
        retained = set(steps[-keep:]) | {step}
        for old in steps:
            if old not in retained:
                (directory / f"ckpt-{old}.npz").unlink(missing_ok=True)
    return final


def latest_step(directory) -> Optional[int]:
    """Highest complete checkpoint step in ``directory``, or None."""
    directory = Path(directory)
    pointer = directory / "LATEST"
    if pointer.exists():
        try:
            meta = json.loads(pointer.read_text())
            if (directory / meta["file"]).exists():
                return int(meta["step"])
        except (ValueError, KeyError):
            pass
    steps = [
        int(m.group(1))
        for p in (directory.iterdir() if directory.is_dir() else [])
        if (m := _STEP_RE.match(p.name))
    ]
    return max(steps) if steps else None


# -- process-sharded checkpoints (multi-host) ---------------------------------
#
# np.asarray on a multi-host sharded jax.Array would gather (or fail: shards
# on other hosts aren't addressable). The sharded format writes, per process,
# only the shards that process holds — ckpt-{step}.shard-{process}.npz with
# entries keyed by each shard's GLOBAL index range — and restore reassembles
# from whichever files hold the ranges the local devices need, so a respawned
# slice restores correctly even if worker/process numbering changed. The
# worker agent syncs each worker's own shard files to the bucket
# (tpu-worker-script.sh.tpl), so the bucket always holds the full set.

_SHARD_RE = re.compile(r"^ckpt-(\d+)\.shard-(\d+)\.npz$")


def _index_key(leaf_index: int, index, shape) -> str:
    """Stable string key for a shard's global index range."""
    parts = []
    for dim, slc in enumerate(index):
        start = 0 if slc.start is None else int(slc.start)
        stop = shape[dim] if slc.stop is None else int(slc.stop)
        parts.append(f"{start}:{stop}")
    return f"leaf_{leaf_index}|" + ",".join(parts)


def save_checkpoint_sharded(directory, step: int, tree: Any,
                            keep: Optional[int] = None) -> Path:
    """Write this process's shards of a (possibly multi-host) pytree.

    Every process calls this; each writes only its addressable, replica-0
    shards. Process 0 also writes a LATEST_SHARDED pointer naming the step
    and the expected shard-file count — restore uses it to reject partial
    sets consistently across hosts (the plain-format LATEST is untouched).

    ``keep``: retain the newest N steps (plus, always, the one just
    written). Each process prunes its OWN old shard files (never a
    sibling's — a slow process may still be writing an older step's shard
    it owns); process 0 also prunes the old per-step manifests. Minimum 2:
    with keep=1 a worker deletes its previous shard the moment it writes
    the new one, and during the inter-worker sync-skew window NO step has
    a complete shard set in the bucket — a preemption there would be
    unrecoverable. More generally ``keep`` must exceed the worst-case
    inter-worker save skew measured in save intervals; 2 covers loops
    that save in lockstep, size it up for loosely-coupled savers."""
    if keep is not None and keep < 2:
        raise ValueError(
            f"sharded keep must be >= 2 (got {keep}): with 1 retained "
            "step, inter-worker sync skew leaves windows where no step "
            "has a complete shard set")
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    process = jax.process_index()

    arrays = {}
    for leaf_index, leaf in enumerate(jax.tree.leaves(tree)):
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            shape = leaf.shape
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue  # one copy of replicated shards is enough
                arrays[_index_key(leaf_index, shard.index, shape)] = \
                    np.asarray(shard.data)
        else:
            array = np.asarray(leaf)
            if process == 0:  # plain host values: process 0's copy wins
                index = tuple(slice(0, dim) for dim in array.shape)
                arrays[_index_key(leaf_index, index, array.shape)] = array

    final = directory / f"ckpt-{step}.shard-{process}.npz"
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as handle:
            np.savez(handle, **arrays)
        os.replace(tmp, final)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise

    if process == 0:
        # Reap shard files beyond this topology: a re-save of the same step
        # after a topology SHRINK would otherwise leave stale higher-index
        # shards that make the completeness check (indices == 0..expected-1)
        # reject the step forever.
        for stale in directory.glob(f"ckpt-{step}.shard-*.npz"):
            match = _SHARD_RE.match(stale.name)
            if match and int(match.group(2)) >= jax.process_count():
                try:
                    stale.unlink()
                except OSError:
                    pass
        # Per-step manifest: records THIS step's save-time topology so a
        # later restore under a different process count can still judge the
        # step's completeness by the count it was saved with.
        meta = directory / f"ckpt-{step}.meta.tmp"
        meta.write_text(json.dumps({
            "step": step, "process_count": jax.process_count()}))
        os.replace(meta, directory / f"ckpt-{step}.meta")
        # A SEPARATE pointer file: repointing the plain LATEST at a shard
        # file would make latest_step()/restore_checkpoint() chase a
        # nonexistent ckpt-{step}.npz.
        pointer = directory / "LATEST_SHARDED.tmp"
        pointer.write_text(json.dumps({
            "step": step, "file": final.name,
            "process_count": jax.process_count()}))
        os.replace(pointer, directory / "LATEST_SHARDED")
    if keep is not None:
        own = sorted(
            int(match.group(1)) for path in directory.iterdir()
            if (match := _SHARD_RE.match(path.name))
            and int(match.group(2)) == process)
        retained = set(own[-keep:]) | {step}
        for old in own:
            if old in retained:
                continue
            (directory /
             f"ckpt-{old}.shard-{process}.npz").unlink(missing_ok=True)
            if process == 0:
                (directory / f"ckpt-{old}.meta").unlink(missing_ok=True)
    return final


def restore_checkpoint_sharded(directory, template: Any,
                               step: Optional[int] = None) -> Any:
    """Reassemble a sharded checkpoint into ``template``'s shardings.

    Reads every ``ckpt-{step}.shard-*.npz`` present (the workdir restore
    pulls all of them from the bucket) and places, per template leaf, the
    global index ranges each LOCAL device needs — shard files are matched
    by index range, not by process number, so recovery survives process
    renumbering. With no explicit ``step``, tries steps NEWEST → OLDEST and
    falls back past incomplete sets: workers upload shards on independent
    sync loops, so a preemption can land mid-upload and the newest step may
    be partial — the last complete one must still restore.
    """
    directory = Path(directory)
    if step is not None:
        return _restore_sharded_step(directory, template, step)
    steps = sorted({int(m.group(1))
                    for p in (directory.iterdir()
                              if directory.is_dir() else [])
                    if (m := _SHARD_RE.match(p.name))}, reverse=True)
    if not steps:
        raise FileNotFoundError(f"no sharded checkpoint in {directory}")
    # Step eligibility must be decided IDENTICALLY on every host — a
    # per-host "whatever ranges my devices need" check would let different
    # hosts resume from different steps after a partial upload. A step is
    # eligible only when the full shard-file set FOR THAT STEP'S SAVE-TIME
    # TOPOLOGY is present: the per-step manifest when available, the
    # LATEST_SHARDED pointer for its own step, else (legacy checkpoints
    # without a manifest) this topology's process count — never "whatever
    # files happen to be present", which would bless truncated prefixes.
    pointer = directory / "LATEST_SHARDED"
    pointer_step = pointer_count = None
    if pointer.exists():
        try:
            meta = json.loads(pointer.read_text())
            pointer_step = int(meta["step"])
            if meta.get("process_count"):
                pointer_count = int(meta["process_count"])
        except (ValueError, KeyError):
            pass
    last_error: Optional[Exception] = None
    for candidate in steps:
        indices = {int(m.group(2))
                   for p in directory.glob(f"ckpt-{candidate}.shard-*.npz")
                   if (m := _SHARD_RE.match(p.name))}
        expected = None
        manifest = directory / f"ckpt-{candidate}.meta"
        if manifest.exists():
            try:
                expected = int(json.loads(
                    manifest.read_text())["process_count"])
            except (ValueError, KeyError, TypeError):
                pass
        if expected is None and candidate == pointer_step:
            expected = pointer_count
        if expected is None:
            expected = jax.process_count()
        if not indices or indices != set(range(expected)):
            last_error = FileNotFoundError(
                f"step {candidate}: shard indices {sorted(indices)} != "
                f"expected 0..{expected - 1}")
            continue
        try:
            return _restore_sharded_step(directory, template, candidate)
        except Exception as error:  # torn file (BadZipFile), missing entry…
            last_error = error
    raise FileNotFoundError(
        f"no complete sharded checkpoint in {directory} "
        f"(tried steps {steps}): {last_error}")


def _restore_sharded_step(directory: Path, template: Any, step: int) -> Any:
    # NpzFile members decompress lazily on access: index key → handle and
    # load only the ranges this host's devices actually need — each host
    # must NOT materialize the whole global checkpoint (that's the point
    # of sharded restore).
    paths = sorted(directory.glob(f"ckpt-{step}.shard-*.npz"))
    handles = []
    try:
        index: dict = {}
        for path in paths:
            handle = np.load(path)
            handles.append(handle)
            for key in handle.files:
                index[key] = handle

        if not index:
            raise FileNotFoundError(f"no shard files for step {step}")

        def lookup(key: str):
            if key not in index:
                raise FileNotFoundError(
                    f"shard {key} missing at step {step} — incomplete "
                    f"checkpoint ({len(index)} entries present)")
            return index[key][key]

        leaves, treedef = jax.tree.flatten(template)
        restored = []
        for leaf_index, leaf in enumerate(leaves):
            if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
                shape = leaf.shape
                index_map = leaf.sharding.addressable_devices_indices_map(shape)
                device_arrays = []
                for device, device_index in index_map.items():
                    key = _index_key(leaf_index, device_index, shape)
                    device_arrays.append(jax.device_put(
                        lookup(key).astype(leaf.dtype), device))
                restored.append(jax.make_array_from_single_device_arrays(
                    shape, leaf.sharding, device_arrays))
            else:
                array = np.asarray(leaf)
                full = tuple(slice(0, dim) for dim in array.shape)
                restored.append(lookup(_index_key(leaf_index, full,
                                                  array.shape)))
        return jax.tree.unflatten(treedef, restored)
    finally:
        for handle in handles:
            handle.close()


def restore_checkpoint(directory, template: Any, step: Optional[int] = None) -> Any:
    """Restore into ``template``'s structure (dtypes/shardings preserved)."""
    directory = Path(directory)
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {directory}")
    with np.load(directory / f"ckpt-{step}.npz") as data:
        arrays = [data[f"leaf_{i}"] for i in range(len(data.files))]
    leaves, treedef = jax.tree.flatten(template)
    if len(arrays) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(arrays)} leaves, template has {len(leaves)}"
        )
    restored = []
    for arr, leaf in zip(arrays, leaves):
        if hasattr(leaf, "sharding") and hasattr(leaf, "dtype"):
            restored.append(
                jax.device_put(arr.astype(leaf.dtype), leaf.sharding)
            )
        else:
            restored.append(arr)
    return jax.tree.unflatten(treedef, restored)
