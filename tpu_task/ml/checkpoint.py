"""Checkpoint-to-workdir: what makes the orchestrator's 10 s data sync useful.

The reference's recovery story is "user script checkpoints into the workdir,
the agent syncs the workdir to the bucket every 10 s, a respawned machine
restores the workdir before restarting" (machine-script.sh.tpl:89,118-124 and
docs/resources/task.md:33-42 — the epoch-file pattern). This module is the
user-script half of that contract for JAX pytrees:

* atomic writes (temp file + rename) so the sync loop never ships a torn file;
* monotonically numbered steps + a LATEST pointer written last;
* restore returns the template pytree's structure/dtypes/shardings.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np

_STEP_RE = re.compile(r"^ckpt-(\d+)\.npz$")


def save_checkpoint(directory, step: int, tree: Any) -> Path:
    """Write ``ckpt-{step}.npz`` atomically, then update LATEST."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    leaves = [np.asarray(l) for l in jax.tree.leaves(tree)]
    arrays = {f"leaf_{i}": a for i, a in enumerate(leaves)}

    final = directory / f"ckpt-{step}.npz"
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, final)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise

    pointer = directory / "LATEST.tmp"
    pointer.write_text(json.dumps({"step": step, "file": final.name}))
    os.replace(pointer, directory / "LATEST")
    return final


def latest_step(directory) -> Optional[int]:
    """Highest complete checkpoint step in ``directory``, or None."""
    directory = Path(directory)
    pointer = directory / "LATEST"
    if pointer.exists():
        try:
            meta = json.loads(pointer.read_text())
            if (directory / meta["file"]).exists():
                return int(meta["step"])
        except (ValueError, KeyError):
            pass
    steps = [
        int(m.group(1))
        for p in (directory.iterdir() if directory.is_dir() else [])
        if (m := _STEP_RE.match(p.name))
    ]
    return max(steps) if steps else None


# -- process-sharded checkpoints (multi-host) ---------------------------------
#
# np.asarray on a multi-host sharded jax.Array would gather (or fail: shards
# on other hosts aren't addressable). The sharded format writes, per process,
# only the shards that process holds — ckpt-{step}.shard-{process}.npz with
# entries keyed by each shard's GLOBAL index range — and restore reassembles
# from whichever files hold the ranges the local devices need, so a respawned
# slice restores correctly even if worker/process numbering changed. The
# worker agent syncs each worker's own shard files to the bucket
# (tpu-worker-script.sh.tpl), so the bucket always holds the full set.

_SHARD_RE = re.compile(r"^ckpt-(\d+)\.shard-(\d+)\.npz$")


def _index_key(leaf_index: int, index, shape) -> str:
    """Stable string key for a shard's global index range."""
    parts = []
    for dim, slc in enumerate(index):
        start = 0 if slc.start is None else int(slc.start)
        stop = shape[dim] if slc.stop is None else int(slc.stop)
        parts.append(f"{start}:{stop}")
    return f"leaf_{leaf_index}|" + ",".join(parts)


def save_checkpoint_sharded(directory, step: int, tree: Any) -> Path:
    """Write this process's shards of a (possibly multi-host) pytree.

    Every process calls this; each writes only its addressable, replica-0
    shards. LATEST is written by process 0 only, and names the expected
    shard-file count so restore can detect a partial set.
    """
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    process = jax.process_index()

    arrays = {}
    for leaf_index, leaf in enumerate(jax.tree.leaves(tree)):
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            shape = leaf.shape
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue  # one copy of replicated shards is enough
                arrays[_index_key(leaf_index, shard.index, shape)] = \
                    np.asarray(shard.data)
        else:
            array = np.asarray(leaf)
            if process == 0:  # plain host values: process 0's copy wins
                index = tuple(slice(0, dim) for dim in array.shape)
                arrays[_index_key(leaf_index, index, array.shape)] = array

    final = directory / f"ckpt-{step}.shard-{process}.npz"
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as handle:
            np.savez(handle, **arrays)
        os.replace(tmp, final)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise

    if process == 0:
        pointer = directory / "LATEST.tmp"
        pointer.write_text(json.dumps({
            "step": step, "file": final.name, "sharded": True,
            "process_count": jax.process_count()}))
        os.replace(pointer, directory / "LATEST")
    return final


def restore_checkpoint_sharded(directory, template: Any,
                               step: Optional[int] = None) -> Any:
    """Reassemble a sharded checkpoint into ``template``'s shardings.

    Reads every ``ckpt-{step}.shard-*.npz`` present (the workdir restore
    pulls all of them from the bucket) and places, per template leaf, the
    global index ranges each LOCAL device needs — shard files are matched
    by index range, not by process number, so recovery survives process
    renumbering. With no explicit ``step``, tries steps NEWEST → OLDEST and
    falls back past incomplete sets: workers upload shards on independent
    sync loops, so a preemption can land mid-upload and the newest step may
    be partial — the last complete one must still restore.
    """
    directory = Path(directory)
    if step is not None:
        return _restore_sharded_step(directory, template, step)
    steps = sorted({int(m.group(1))
                    for p in (directory.iterdir()
                              if directory.is_dir() else [])
                    if (m := _SHARD_RE.match(p.name))}, reverse=True)
    if not steps:
        raise FileNotFoundError(f"no sharded checkpoint in {directory}")
    last_error: Optional[Exception] = None
    for candidate in steps:
        try:
            return _restore_sharded_step(directory, template, candidate)
        except FileNotFoundError as error:
            last_error = error
    raise FileNotFoundError(
        f"no complete sharded checkpoint in {directory} "
        f"(tried steps {steps}): {last_error}")


def _restore_sharded_step(directory: Path, template: Any, step: int) -> Any:
    data: dict = {}
    for path in sorted(directory.glob(f"ckpt-{step}.shard-*.npz")):
        with np.load(path) as payload:
            for key in payload.files:
                data[key] = payload[key]
    if not data:
        raise FileNotFoundError(f"no shard files for step {step}")

    def lookup(key: str):
        if key not in data:
            raise FileNotFoundError(
                f"shard {key} missing at step {step} — incomplete "
                f"checkpoint ({len(data)} entries present)")
        return data[key]

    leaves, treedef = jax.tree.flatten(template)
    restored = []
    for leaf_index, leaf in enumerate(leaves):
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            shape = leaf.shape
            index_map = leaf.sharding.addressable_devices_indices_map(shape)
            device_arrays = []
            for device, index in index_map.items():
                key = _index_key(leaf_index, index, shape)
                device_arrays.append(jax.device_put(
                    lookup(key).astype(leaf.dtype), device))
            restored.append(jax.make_array_from_single_device_arrays(
                shape, leaf.sharding, device_arrays))
        else:
            array = np.asarray(leaf)
            index = tuple(slice(0, dim) for dim in array.shape)
            restored.append(lookup(_index_key(leaf_index, index, array.shape)))
    return jax.tree.unflatten(treedef, restored)


def restore_checkpoint(directory, template: Any, step: Optional[int] = None) -> Any:
    """Restore into ``template``'s structure (dtypes/shardings preserved)."""
    directory = Path(directory)
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {directory}")
    with np.load(directory / f"ckpt-{step}.npz") as data:
        arrays = [data[f"leaf_{i}"] for i in range(len(data.files))]
    leaves, treedef = jax.tree.flatten(template)
    if len(arrays) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(arrays)} leaves, template has {len(leaves)}"
        )
    restored = []
    for arr, leaf in zip(arrays, leaves):
        if hasattr(leaf, "sharding") and hasattr(leaf, "dtype"):
            restored.append(
                jax.device_put(arr.astype(leaf.dtype), leaf.sharding)
            )
        else:
            restored.append(arr)
    return jax.tree.unflatten(treedef, restored)
