"""Pallas paged-decode attention: stream KV straight from the block pools.

The serving engine's decode attention (ROADMAP item 3) was a two-step XLA
program: ``gather_kv`` materializes every slot's KV through its block table
into a dense ``(slots, L, kv, d)`` buffer, then the shared dense core
(``ml.ops.attention.gqa_cached_attention``) attends it. That streams
O(slots × max_len) bytes per token whether or not the slots are full —
exactly the cost PagedAttention (Kwon et al., SOSP 2023) exists to avoid.

:func:`paged_decode_attention` is the kernel analogue: one grid program per
``(slot, kv_head, block)`` walks the slot's block table (a scalar-prefetch
argument, so the table entry indexes the KV block's DMA before the body
runs), streams each physical block ``(block_size, d_head)`` of the pool
into VMEM exactly once, and folds it into an online softmax — the gathered
dense buffer never exists. Blocks past a slot's current position are
skipped (their table entries are the scratch sentinel 0 and the position
mask would zero them anyway), so compute follows live tokens, not
capacity. Grouped-query attention keeps the pool at KV-head width: each
grid cell loads one KV head's block once and attends the whole
``n_heads / kv_heads`` query group against it.

**int8 KV blocks** ride the same walk: when the pool stores int8 codes
with a per-``(block, kv_head)`` fp32 scale sidecar
(``ml.serving.cache`` quantizes at append/COW time), the kernel
dequantizes IN REGISTER — the scale is constant over a grid cell, so it
factors out of both matmuls (``scores = (q·kᵀ)·k_scale``,
``out = (p·v)·v_scale``) and the dequantized block never round-trips
through memory either. **int4 pools** (uint8 elements — two codes per
byte, ``cache.pack_int4``) add one in-register step: the block's
nibbles sign-extend to int8 codes right after the VMEM load (or the
manual DMA), before the same scale factoring — the packed block is
what crosses HBM→VMEM, so the DMA bytes halve along with the pool.

Exactness contract (docs/parity.md "Decode kernel + quantized KV"):
the kernel is tolerance-pinned against the XLA gather+dense reference
(same values, different accumulation order — online softmax vs one
rectangle); the fp32 ENGINE keeps its bit-exact greedy-stream pins by
leaving the XLA path byte-identical and selecting the kernel only where
configured. The int8 path is a documented tolerance contract.

**DMA pipelining** (ROADMAP item 4): the PR 9 kernel above leans on the
automatic Pallas pipeline — one grid cell per block, the BlockSpec
index_map (scalar-prefetched table entry) driving each block's HBM→VMEM
copy. :func:`paged_decode_pipelined_attention` takes manual control of
that copy instead: one grid cell per ``(slot, kv_head)`` walks the
slot's WHOLE block list with the KV pools left in HBM
(``memory_space=ANY``), double-buffering two VMEM block slots — block
N+1's ``make_async_copy`` is issued before block N's compute runs, so
the DMA engine fills one buffer while the MXU consumes the other, and
the walk stops at the slot's live depth (a dynamic loop bound off the
scalar-prefetched positions — dead capacity is neither copied nor
computed). Same online-softmax math, same masking, same int8/fp8 scale
factoring; parity against the reference is pinned in interpret mode and
the wall-clock claim is TPU-gated (``bench.py generation
decode_kernel`` compares it against the PR 9 kernel on the
long-fragmented-table case, ``make bench-decode`` fails on regression).

``interpret=True`` runs the kernel through the Pallas interpreter on any
backend — the CPU parity suite (tests/test_paged_attention.py) and the
``decode_impl="interpret"`` engine mode use it; real-TPU runs compile the
same kernel (``decode_impl="pallas"`` / auto-selection on a TPU backend).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from tpu_task.ml.ops.attention import (
    LANES,
    NEG_INF,
    _out_struct,
    _use_pallas,
    _vma,
    gqa_cached_attention,
)

#: Mosaic tile constraints the COMPILED kernel's block shapes must satisfy
#: (the interpreter has none): the trailing (lane) dim of every VMEM block
#: is ``d_head`` and must tile by 128; the second-to-last (sublane) dim of
#: the KV blocks is ``block_size`` and must tile by the POOL dtype's
#: native sublane count — 8 at fp32, 16 at bf16, 32 at int8 (the narrower
#: the element, the taller the (sublane, 128) tile). The pool dtype is
#: the KV storage dtype, so int8 KV tightens the block_size constraint.
LANE_TILE = 128


def kernel_sublane_tile(kv_itemsize: int) -> int:
    """Native Mosaic sublane count for a KV element of ``kv_itemsize``
    bytes: the (sublane × 128-lane) tile holds 32 bytes per lane."""
    return 32 // kv_itemsize


def use_pallas_paged() -> bool:
    """Whether auto-selection picks the compiled kernel on this backend."""
    return _use_pallas()


def _unpack_int4(blk):
    """In-register nibble→code expansion for uint8 (int4-packed) KV
    blocks: (..., d/2) uint8 → (..., d) int8, the inverse of
    ``cache.pack_int4``. Imported lazily — ml.ops must not import
    ml.serving at module load (the serving package init imports the
    engine, which imports this file)."""
    from tpu_task.ml.serving.cache import unpack_int4

    return unpack_int4(blk)


#: Conservative budget for the kernel's scalar-prefetch operands (block
#: tables, positions, int8 scale sidecars — all SMEM-resident on the
#: compiled path). TPU SMEM is tens of KB per core; staying under this
#: keeps headroom for Mosaic's own scalar state. Interpret mode ignores
#: it (no SMEM exists to exhaust).
PREFETCH_SMEM_BUDGET = 32 * 1024


def kernel_constraint_violation(block_size: int, d_head: int,
                                kv_itemsize: int = 4, *,
                                n_blocks: int = 0, kv_heads: int = 0,
                                slots: int = 0, max_blocks: int = 0,
                                q_width: int = 1,
                                quantized: bool = False,
                                packed: bool = False) -> Optional[str]:
    """Why the COMPILED kernel cannot run on this pool geometry, or None.
    ``kv_itemsize``: bytes per KV POOL element (1 for int8 pools, else the
    model dtype's) — it sets the sublane tile ``block_size`` must honor.
    ``packed``: the pool is int4 (uint8 pairs) — the KV VMEM blocks'
    trailing dim is ``d_head / 2``, which must itself tile by the lane
    count.
    The optional sizes enable the scalar-prefetch SMEM budget check: the
    block tables, positions, and (when ``quantized``) the per-(block,
    kv-head) scale sidecars all ride SMEM on the compiled path, so a huge
    pool can exceed it even with perfect tiling.

    The serving engine consults this at construction: an unsatisfiable
    geometry under ``decode_impl="auto"`` falls back to the XLA gather
    path with a one-time warning, and under an explicit
    ``decode_impl="pallas"`` raises this reason as an actionable error —
    never a Pallas trace/allocation failure mid-decode. ``interpret``
    mode has no constraints (the interpreter imposes no tiling or SMEM)."""
    if d_head % LANE_TILE:
        return (f"d_head {d_head} is not a multiple of the {LANE_TILE}-lane "
                f"tile the compiled kernel's VMEM blocks need")
    if packed and (d_head // 2) % LANE_TILE:
        return (f"int4 KV blocks carry d_head/2 = {d_head // 2} packed "
                f"bytes in the lane dim, not a multiple of the "
                f"{LANE_TILE}-lane tile — int4 on the compiled kernel "
                f"needs d_head % {2 * LANE_TILE} == 0")
    sublane = kernel_sublane_tile(kv_itemsize)
    if block_size % sublane:
        return (f"block_size {block_size} is not a multiple of the "
                f"{sublane}-sublane tile the compiled kernel's KV blocks "
                f"need at a {kv_itemsize}-byte pool element")
    # tables + positions; positions are (slots, q_width) — the widest
    # program is the spec_k+1 scoring step.
    prefetch = 4 * (slots * max_blocks + slots * max(1, q_width))
    if quantized:
        prefetch += 2 * 4 * n_blocks * kv_heads        # k_scale + v_scale
    if prefetch > PREFETCH_SMEM_BUDGET:
        return (f"scalar-prefetch operands need {prefetch} bytes of SMEM "
                f"(tables + positions{' + int8 scale sidecars' if quantized else ''}), "
                f"over the {PREFETCH_SMEM_BUDGET}-byte budget — shrink "
                f"n_blocks/max_len or use decode_impl='xla'")
    return None


# -- the kernel ---------------------------------------------------------------

def _paged_decode_kernel(tables_ref, pos_ref, *rest, bs: int, w: int,
                         group: int, num_blocks: int, quantized: bool,
                         packed: bool = False):
    """One (slot, kv_head, block) grid cell: fold one physical KV block
    into the running online softmax of the slot's whole query group.

    ``tables_ref`` (slots, max_blocks) and ``pos_ref`` (slots, w) are
    scalar-prefetch SMEM refs — the table entry already indexed this
    cell's KV DMA via the BlockSpec index_map; the kernel re-reads it only
    for the scale lookup and the liveness test. q_ref: (w, group, d);
    k_ref/v_ref: (bs, d) — ONE physical block, the VMEM residency is
    O(block) whatever the sequence length. The (m, l, acc) state carries
    across the block walk in VMEM scratch, exactly the flash forward's
    discipline (``_flash_fwd_kernel``)."""
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    s = pl.program_id(0)
    kh = pl.program_id(1)
    b = pl.program_id(2)
    d = q_ref.shape[-1]
    rows = w * group

    @pl.when(b == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Blocks wholly past the row's deepest query position hold nothing the
    # position mask would keep (their table entries are the scratch
    # sentinel anyway) — skip their compute. ``w`` is static and small, so
    # the max unrolls to scalar SMEM reads.
    max_pos = pos_ref[s, 0]
    for i in range(1, w):
        max_pos = jnp.maximum(max_pos, pos_ref[s, i])
    live = b * bs <= max_pos

    @pl.when(live)
    def _compute():
        q = q_ref[...].reshape(rows, d).astype(jnp.float32) / math.sqrt(d)
        k_blk = _unpack_int4(k_ref[...]) if packed else k_ref[...]
        k_blk = k_blk.astype(jnp.float32)
        sm = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        if quantized:
            # Per-(block, kv_head) scale is constant over this grid cell:
            # dequantization factors out of the dot products entirely.
            sm = sm * ks_ref[tables_ref[s, b], kh]
        cols = b * bs + lax.broadcasted_iota(jnp.int32, (rows, bs), 1)
        rpos = jnp.repeat(jnp.stack([pos_ref[s, i] for i in range(w)]),
                          group)
        mask = cols <= rpos[:, None]
        sm = jnp.where(mask, sm, NEG_INF)
        m = m_ref[...][:, 0]
        l = l_ref[...][:, 0]
        m_new = jnp.maximum(m, sm.max(axis=-1))
        shift = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(sm - shift[:, None])
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(jnp.where(m <= NEG_INF / 2, NEG_INF, m) - shift)
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(
            (l * corr + p.sum(axis=-1))[:, None], l_ref.shape)
        v_blk = _unpack_int4(v_ref[...]) if packed else v_ref[...]
        v_blk = v_blk.astype(jnp.float32)
        pv = lax.dot_general(p, v_blk, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        if quantized:
            pv = pv * vs_ref[tables_ref[s, b], kh]
        acc_ref[...] = acc_ref[...] * corr[:, None] + pv

    @pl.when(b == num_blocks - 1)
    def _finalize():
        l = l_ref[...][:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_ref[...] / l_safe[:, None]).reshape(
            o_ref.shape).astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, block_tables, q_positions,
                           k_scale=None, v_scale=None, *,
                           interpret: bool = False):
    """Block-table-aware paged GQA decode attention — the kernel analogue
    of ``gather_kv`` + ``gqa_cached_attention`` that never materializes
    the gathered dense buffer.

    q: (slots, w, h, d) — w = 1 for plain decode, w = spec_k + 1 for the
    speculative scoring step (the engine's one fused multi-token shape).
    k_pool/v_pool: (n_blocks, block_size, kv, d) PHYSICAL pools in their
    storage dtype (fp32/bf16, or int8 when the scale sidecars are given).
    block_tables: (slots, max_blocks) int32; q_positions: (slots, w) int32
    absolute positions (invalid rows carry 0, same contract as the XLA
    path — their outputs are garbage the host discards).
    k_scale/v_scale: (n_blocks, kv) float32 per-(block, kv_head) sidecars;
    both or neither. Returns (slots, w, h, d) in q.dtype.

    Semantics match the reference exactly: cache slot j participates iff
    ``j <= q_pos`` (masked scores pin to NEG_INF → exact 0.0 weight), so
    scratch/unallocated garbage never reaches an output bit at fp32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    slots, w, h, d = q.shape
    n_blocks, bs, kv, dp = k_pool.shape
    if h % kv:
        raise ValueError(f"n_heads {h} not divisible by kv_heads {kv}")
    group = h // kv
    max_blocks = block_tables.shape[1]
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if q_positions.ndim != 2 or q_positions.shape != (slots, w):
        raise ValueError(
            f"q_positions must be (slots, w) = ({slots}, {w}), got "
            f"{q_positions.shape}")
    # An int4 pool's trailing dim is d/2 packed uint8 pairs — the KV
    # BlockSpecs stage the pool's OWN width; the kernel unpacks.
    packed = k_pool.dtype == jnp.uint8

    kernel = functools.partial(
        _paged_decode_kernel, bs=bs, w=w, group=group,
        num_blocks=max_blocks, quantized=quantized, packed=packed)
    n_prefetch = 4 if quantized else 2

    def idx_q(s, kh, b, *refs):
        return (s, 0, kh, 0)

    def idx_kv(s, kh, b, *refs):
        return (refs[0][s, b], 0, kh, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(slots, kv, max_blocks),
        in_specs=[
            pl.BlockSpec((None, w, group, d), idx_q),
            pl.BlockSpec((None, bs, None, dp), idx_kv),
            pl.BlockSpec((None, bs, None, dp), idx_kv),
        ],
        out_specs=pl.BlockSpec((None, w, group, d), idx_q),
        scratch_shapes=[
            pltpu.VMEM((w * group, LANES), jnp.float32),  # running max
            pltpu.VMEM((w * group, LANES), jnp.float32),  # running sum
            pltpu.VMEM((w * group, d), jnp.float32),      # out accumulator
        ],
    )
    vma = _vma(q, k_pool, v_pool)
    call = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=_out_struct((slots, w, h, d), q.dtype, vma),
        interpret=interpret,
    )
    scalars = (block_tables, q_positions)
    if quantized:
        scalars += (k_scale, v_scale)
    return call(*scalars, q, k_pool, v_pool)


# -- DMA-pipelined kernel (double-buffered manual block copies) ---------------

def _paged_decode_pipelined_kernel(tables_ref, pos_ref, *rest, bs: int,
                                   w: int, group: int, max_blocks: int,
                                   quantized: bool, packed: bool = False):
    """One (slot, kv_head) grid cell: walk the slot's live blocks with the
    KV pools still in HBM, double-buffering the block DMA.

    ``k_hbm``/``v_hbm`` are ANY-memory-space refs of the WHOLE pools —
    nothing is staged by the automatic pipeline. The cell issues block
    b+1's async copy into the other VMEM buffer slot before it computes
    block b (the guide's double-buffer pattern), so the HBM read of the
    next block overlaps the current block's two matmuls. The online
    softmax state rides the loop carry ((rows, LANES)-shaped running
    max/sum as in the PR 9 kernel's scratch, (rows, d) accumulator); the
    loop bound is the slot's LIVE depth — ``max_pos // bs + 1`` off the
    scalar-prefetched positions — so dead capacity costs neither DMA nor
    compute (the PR 9 kernel still iterates its grid over dead blocks,
    merely skipping their compute)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if quantized:
        (ks_ref, vs_ref, q_ref, k_hbm, v_hbm, o_ref,
         k_buf, v_buf, sems) = rest
    else:
        q_ref, k_hbm, v_hbm, o_ref, k_buf, v_buf, sems = rest
        ks_ref = vs_ref = None
    s = pl.program_id(0)
    kh = pl.program_id(1)
    d = q_ref.shape[-1]
    rows = w * group

    max_pos = pos_ref[s, 0]
    for i in range(1, w):
        max_pos = jnp.maximum(max_pos, pos_ref[s, i])
    num_live = jnp.minimum(max_pos // bs + 1, max_blocks)

    def copies(b, slot):
        blk = tables_ref[s, b]
        return (pltpu.make_async_copy(
                    k_hbm.at[blk, :, kh, :], k_buf.at[slot],
                    sems.at[slot, 0]),
                pltpu.make_async_copy(
                    v_hbm.at[blk, :, kh, :], v_buf.at[slot],
                    sems.at[slot, 1]))

    for dma in copies(0, 0):
        dma.start()

    q = q_ref[...].reshape(rows, d).astype(jnp.float32) / math.sqrt(d)
    rpos = jnp.repeat(jnp.stack([pos_ref[s, i] for i in range(w)]), group)

    def body(b, carry):
        m2d, l2d, acc = carry
        slot = b % 2

        @pl.when(b + 1 < num_live)
        def _prefetch_next():
            for dma in copies(b + 1, (b + 1) % 2):
                dma.start()

        for dma in copies(b, slot):
            dma.wait()
        k_blk = _unpack_int4(k_buf[slot]) if packed else k_buf[slot]
        k_blk = k_blk.astype(jnp.float32)
        sm = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        if quantized:
            sm = sm * ks_ref[tables_ref[s, b], kh]
        cols = b * bs + lax.broadcasted_iota(jnp.int32, (rows, bs), 1)
        mask = cols <= rpos[:, None]
        sm = jnp.where(mask, sm, NEG_INF)
        m = m2d[:, 0]
        l = l2d[:, 0]
        m_new = jnp.maximum(m, sm.max(axis=-1))
        shift = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(sm - shift[:, None])
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(jnp.where(m <= NEG_INF / 2, NEG_INF, m) - shift)
        v_blk = _unpack_int4(v_buf[slot]) if packed else v_buf[slot]
        v_blk = v_blk.astype(jnp.float32)
        pv = lax.dot_general(p, v_blk, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        if quantized:
            pv = pv * vs_ref[tables_ref[s, b], kh]
        return (jnp.broadcast_to(m_new[:, None], m2d.shape),
                jnp.broadcast_to((l * corr + p.sum(axis=-1))[:, None],
                                 l2d.shape),
                acc * corr[:, None] + pv)

    m0 = jnp.full((rows, LANES), NEG_INF, jnp.float32)
    l0 = jnp.zeros((rows, LANES), jnp.float32)
    acc0 = jnp.zeros((rows, d), jnp.float32)
    _, l2d, acc = lax.fori_loop(0, num_live, body, (m0, l0, acc0))
    l = l2d[:, 0]
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[...] = (acc / l_safe[:, None]).reshape(
        o_ref.shape).astype(o_ref.dtype)


def paged_decode_pipelined_attention(q, k_pool, v_pool, block_tables,
                                     q_positions, k_scale=None,
                                     v_scale=None, *,
                                     interpret: bool = False):
    """The DMA-pipelined variant of :func:`paged_decode_attention` — same
    arguments, same semantics, same tolerance class vs the reference
    (online softmax over blocks, exact 0.0 masked weights). Differences
    are purely in data movement: grid (slots, kv_heads), pools stay in
    HBM (ANY memory space), each cell double-buffers its own block
    copies and walks only the slot's live depth."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    slots, w, h, d = q.shape
    n_blocks, bs, kv, dp = k_pool.shape
    if h % kv:
        raise ValueError(f"n_heads {h} not divisible by kv_heads {kv}")
    group = h // kv
    max_blocks = block_tables.shape[1]
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if q_positions.ndim != 2 or q_positions.shape != (slots, w):
        raise ValueError(
            f"q_positions must be (slots, w) = ({slots}, {w}), got "
            f"{q_positions.shape}")
    packed = k_pool.dtype == jnp.uint8

    kernel = functools.partial(
        _paged_decode_pipelined_kernel, bs=bs, w=w, group=group,
        max_blocks=max_blocks, quantized=quantized, packed=packed)
    n_prefetch = 4 if quantized else 2

    def idx_q(s, kh, *refs):
        return (s, 0, kh, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(slots, kv),
        in_specs=[
            pl.BlockSpec((None, w, group, d), idx_q),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        ],
        out_specs=pl.BlockSpec((None, w, group, d), idx_q),
        scratch_shapes=[
            # Buffers hold the pool's OWN block width (d/2 packed bytes
            # for int4) — the DMA moves packed bytes; unpack is in-register.
            pltpu.VMEM((2, bs, dp), k_pool.dtype),  # double-buffered K
            pltpu.VMEM((2, bs, dp), v_pool.dtype),  # double-buffered V
            pltpu.SemaphoreType.DMA((2, 2)),        # (buffer, k|v)
        ],
    )
    vma = _vma(q, k_pool, v_pool)
    call = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=_out_struct((slots, w, h, d), q.dtype, vma),
        interpret=interpret,
    )
    scalars = (block_tables, q_positions)
    if quantized:
        scalars += (k_scale, v_scale)
    return call(*scalars, q, k_pool, v_pool)


# -- dispatch (XLA reference / kernel / tp-sharded kernel) --------------------

def paged_reference_attention(q, k_pool, v_pool, block_tables, q_positions,
                              k_scale=None, v_scale=None):
    """The XLA gather+dense reference the kernel is pinned against: gather
    the logical (slots, L, kv, d) view through the block tables (dequantize
    it when the pool is int8) and run the ONE shared dense core. This IS
    the pre-kernel serving decode path, spelled over the same argument
    layout as :func:`paged_decode_attention` so parity tests and the
    engine fallback call one function."""
    from tpu_task.ml.serving.cache import flat_pool, gather_kv

    bs = k_pool.shape[1]
    k_view = gather_kv(flat_pool(k_pool), block_tables, bs)
    v_view = gather_kv(flat_pool(v_pool), block_tables, bs)
    if k_scale is not None:
        k_view = dequantize_view(k_view, k_scale, block_tables, bs, q.dtype)
        v_view = dequantize_view(v_view, v_scale, block_tables, bs, q.dtype)
    return gqa_cached_attention(q, k_view, v_view, q_positions)


def dequantize_view(view, scale, block_tables, block_size: int, dtype):
    """(slots, L, kv, d) int8 gathered view × its per-(block, kv_head)
    scales → dense values in ``dtype``. The scale gathers through the same
    block tables and broadcasts over each block's ``block_size`` tokens.
    A uint8 view is int4-packed (d/2 trailing bytes) and unpacks to the
    full head dim first."""
    if view.dtype == jnp.uint8:
        view = _unpack_int4(view)
    s_view = jnp.repeat(scale[block_tables], block_size, axis=1)
    return (view.astype(jnp.float32) * s_view[..., None]).astype(dtype)


@functools.lru_cache(maxsize=None)
def _tp_kernel(mesh, axis_name: str, interpret: bool, quantized: bool,
               pipelined: bool = False):
    """shard_map wrapper of the kernel over the kv-head axis — one memo
    per (mesh, axis, mode) so repeated traces reuse the closure. The
    kv-head axis is already LOCAL per shard (pools shard it, q's head axis
    shards with it, tables/positions replicate) and the kernel has no
    cross-shard reduction — per-kv-head independence makes the sharded
    call bit-exact against running the kernel on each head slice. The
    pipelined kernel shards identically: its grid is (slots, kv_heads)
    and every DMA stays within the shard-local pool."""
    from jax.sharding import PartitionSpec

    from tpu_task.ml.parallel.mesh import shard_map

    heads4 = PartitionSpec(None, None, axis_name, None)
    heads_scale = PartitionSpec(None, axis_name)
    rep = PartitionSpec()
    kern = (paged_decode_pipelined_attention if pipelined
            else paged_decode_attention)

    if quantized:
        def fn(q, kp, vp, tables, pos, ks, vs):
            return kern(q, kp, vp, tables, pos, ks, vs,
                        interpret=interpret)
        in_specs = (heads4, heads4, heads4, rep, rep, heads_scale,
                    heads_scale)
    else:
        def fn(q, kp, vp, tables, pos):
            return kern(q, kp, vp, tables, pos, interpret=interpret)
        in_specs = (heads4, heads4, heads4, rep, rep)
    return shard_map(fn, mesh, in_specs=in_specs, out_specs=heads4,
                     check_vma=False)


def paged_attention(q, k_pool, v_pool, block_tables, q_positions,
                    k_scale=None, v_scale=None, *, impl: str = "xla",
                    mesh=None, axis_name: str = "tp"):
    """The ONE paged-attention entry the serving programs call.

    ``impl``: ``"xla"`` = gather+dense reference (the CPU fallback and the
    bit-exact fp32 path), ``"pallas"`` = compiled PR 9 kernel,
    ``"pipelined"`` = the compiled double-buffered-DMA kernel,
    ``"interpret"``/``"interpret_pipelined"`` = the same kernels through
    the Pallas interpreter (any backend — the parity suite and CPU
    engine smokes). With ``mesh`` the kernel modes run under
    ``shard_map`` with the kv-head axis sharded over ``axis_name`` (the
    XLA mode needs no wrapper — SPMD partitions the gather+einsum
    exactly as before this kernel existed)."""
    if impl not in ("xla", "pallas", "interpret", "pipelined",
                    "interpret_pipelined"):
        raise ValueError(f"unknown paged-attention impl {impl!r}")
    if q_positions.ndim == 1:
        q_positions = q_positions[:, None]
    if impl == "xla":
        return paged_reference_attention(
            q, k_pool, v_pool, block_tables, q_positions, k_scale, v_scale)
    interpret = impl.startswith("interpret")
    pipelined = impl.endswith("pipelined")
    if mesh is None:
        kern = (paged_decode_pipelined_attention if pipelined
                else paged_decode_attention)
        return kern(
            q, k_pool, v_pool, block_tables, q_positions, k_scale, v_scale,
            interpret=interpret)
    fn = _tp_kernel(mesh, axis_name, interpret, k_scale is not None,
                    pipelined)
    args = (q, k_pool, v_pool, block_tables, q_positions)
    if k_scale is not None:
        args += (k_scale, v_scale)
    return fn(*args)
