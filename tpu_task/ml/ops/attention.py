"""Fused attention: pallas flash kernel on TPU, XLA reference elsewhere.

Forward is a flash-attention pallas kernel (online softmax, blocked over the
query sequence, MXU-shaped tiles); backward recomputes through the XLA
reference implementation (rematerialisation — trades FLOPs for the O(S²)
attention matrix that would otherwise live in HBM).

Shapes follow (batch, seq, heads, head_dim) throughout.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def mha_reference(q, k, v, causal: bool = True):
    """Plain XLA attention — the numerical ground truth for the kernels."""
    *_, d = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    if causal:
        q_len, k_len = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((q_len, k_len), dtype=bool), k=k_len - q_len)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool, q_offset_blocks: int):
    """One (batch*head, q_block) grid cell: online softmax over kv blocks.

    q_ref: (block_q, d); k_ref/v_ref: (seq_k, d); o_ref: (block_q, d).
    """
    from jax.experimental import pallas as pl

    block_q, d = q_ref.shape
    seq_k = k_ref.shape[0]
    q = q_ref[...].astype(jnp.float32) / math.sqrt(d)

    q_block_idx = pl.program_id(1)
    q_start = (q_block_idx + q_offset_blocks) * block_q

    m = jnp.full((block_q,), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((block_q,), dtype=jnp.float32)
    acc = jnp.zeros((block_q, d), dtype=jnp.float32)

    num_k_blocks = seq_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = q @ k_blk.T  # (block_q, block_k)
        if causal:
            q_pos = q_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + p.sum(axis=-1)
        acc_new = acc * correction[:, None] + p @ v_blk
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, num_k_blocks, body, (m, l, acc))
    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)


def flash_attention(
    q, k, v, causal: bool = True, *, block_q: int = 256, block_k: int = 256,
    interpret: bool = False,
):
    """Pallas flash attention forward. q: (b, sq, h, d), k/v: (b, sk, h, d)."""
    from jax.experimental import pallas as pl

    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(f"seq lengths ({sq},{sk}) must divide blocks ({block_q},{block_k})")
    if causal and sq != sk:
        raise ValueError(
            f"causal flash attention requires sq == sk (prefix-aligned mask); "
            f"got ({sq},{sk}) — use mha_reference for cross-length causal")

    # Fold heads into the leading grid dim: (b*h, seq, d).
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    # For cross-chunk (ring) use the caller aligns positions itself; here
    # q offset 0 matches self-attention and sq == sk causal semantics.
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, q_offset_blocks=0
    )
    grid = (b * h, sq // block_q)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qb: (bh, qb, 0)),
            pl.BlockSpec((None, sk, d), lambda bh, qb: (bh, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda bh, qb: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda bh, qb: (bh, qb, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def dot_product_attention(q, k, v, causal: bool = True):
    """Attention with a flash forward on TPU and recompute backward."""
    # Flash path only for self-attention shapes: its causal mask is
    # prefix-aligned (q_pos >= k_pos), matching mha_reference's suffix-aligned
    # tril only when sq == sk.
    if (_use_pallas() and q.shape[1] == k.shape[1] and q.shape[1] % 128 == 0):
        return flash_attention(q, k, v, causal, block_q=128, block_k=128)
    return mha_reference(q, k, v, causal)


def _dpa_fwd(q, k, v, causal):
    return dot_product_attention(q, k, v, causal), (q, k, v)


def _dpa_bwd(causal, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: mha_reference(q, k, v, causal), q, k, v)
    return vjp(g)


dot_product_attention.defvjp(_dpa_fwd, _dpa_bwd)
