"""Fused attention: Pallas flash kernels (forward AND backward) on TPU.

Forward is a flash-attention pallas kernel (online softmax, blocked over the
query sequence, MXU-shaped tiles) that also emits the per-row logsumexp.
Backward is a pair of pallas kernels (dq; dk/dv) that recompute attention
probabilities block-by-block from the saved logsumexp — the O(S²) attention
matrix never materializes in HBM in either direction.

The kernels support a static ``q_offset`` (global position of q row 0
relative to k col 0) so causal masking works for sq != sk and for ring
attention's off-diagonal blocks. ``block_attention_fwd``/``block_attention_bwd``
are the block primitives the ring (sequence-parallel) path folds over.
The serving engine's decode analogue — a block-table-aware paged kernel
that walks the physical KV pools with the same online-softmax discipline
— lives in the sibling ``ml.ops.paged_attention`` and shares this
module's layout helpers (``LANES``, ``NEG_INF``, the vma shims).

Shapes follow (batch, seq, heads, head_dim) throughout.

Reference has no attention code at all (SURVEY.md §2.9) — this implements the
flash-attention construction (Dao et al.) TPU-natively.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30

# TPU vector lanes: per-row statistics (lse, delta) are stored broadcast over
# a 128-wide trailing dim because Mosaic requires the last block dim to be a
# multiple of 128 (same layout as jax's reference TPU flash kernels).
LANES = 128


def _dot_nt(a, b):  # a @ b.T with f32 accumulation
    return lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                           preferred_element_type=jnp.float32)


def _dot_nn(a, b):  # a @ b with f32 accumulation
    return lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)


def _dot_tn(a, b):  # a.T @ b with f32 accumulation
    return lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)


def _vma(*arrays):
    """Union of the inputs' varying-mesh-axes (for pallas under shard_map)."""
    from tpu_task.ml.parallel.mesh import value_vma

    out = frozenset()
    for a in arrays:
        out = out | value_vma(a)
    return out


def _out_struct(shape, dtype, vma):
    """``jax.ShapeDtypeStruct`` carrying ``vma`` where the jax version
    supports the kwarg; plain struct otherwise (pre-vma jax tracks no
    varying axes, so there is nothing to declare)."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:
        return jax.ShapeDtypeStruct(shape, dtype)


def expand_kv_heads(kv, n_heads: int):
    """(b, s, kv_heads, d) → (b, s, n_heads, d): repeat each kv head over
    its (contiguous) query group — grouped-query attention's single
    expansion rule, shared by the model block, the ring schedules, and the
    Ulysses reshard so the grouping semantics cannot drift apart. Identity
    for MHA (XLA folds the no-op repeat)."""
    group = n_heads // kv.shape[2]
    return kv if group == 1 else jnp.repeat(kv, group, axis=2)


def reduce_kv_heads(d_expanded, kv_heads: int):
    """Transpose of :func:`expand_kv_heads`: sum the expanded-width
    gradient over each query group back to kv_heads width."""
    b, s, h, d = d_expanded.shape
    if h == kv_heads:
        return d_expanded
    return d_expanded.reshape(b, s, kv_heads, h // kv_heads, d).sum(axis=3)


def gqa_cached_attention(q, k_cache, v_cache, q_positions):
    """Grouped-query attention of q against a positional k/v cache — the ONE
    attention core both cache layouts decode through: the dense per-sequence
    cache (``ml.models.decoding``) feeds its (b, L, kv, d) buffers directly,
    the paged cache (``ml.serving``) gathers the same layout through its
    block tables first. Keeping a single core is what makes the paged/dense
    parity contract (docs/parity.md) checkable: given equal gathered k/v the
    two paths are the same arithmetic, bit for bit.

    q: (b, s, h, d) at absolute ``q_positions`` — shape (s,) when every
    batch row decodes the same positions (the dense ``generate`` path) or
    (b, s) for per-row positions (continuous batching: every slot sits at
    its own depth). Caches stay at KV-head width (b, L, kv, d) and the
    einsums group q heads over them directly — expanding the cache to h per
    step would stream group-factor times the bytes through the memory-bound
    decode loop, forfeiting GQA's win. Cache slot j holds the token at
    position j (arbitrary values beyond the filled region are masked off by
    the position test j <= q_pos: their scores pin to NEG_INF, so softmax
    contributes exactly 0.0 for them at any finite k/v)."""
    b, s, h, d = q.shape
    kv = k_cache.shape[2]
    if h % kv:
        raise ValueError(f"n_heads {h} not divisible by kv_heads {kv}")
    qg = q.reshape(b, s, kv, h // kv, d)
    scores = jnp.einsum("bskgd,blkd->bkgsl", qg, k_cache) / (d ** 0.5)
    slot = jnp.arange(k_cache.shape[1])
    if q_positions.ndim == 1:                               # (s, L)
        mask = slot[None, :] <= q_positions[:, None]
        mask = mask[None, None, None]
    else:                                                   # (b, s, L)
        mask = slot[None, None, :] <= q_positions[:, :, None]
        mask = mask[:, None, None]
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bkgsl,blkd->bskgd", probs.astype(q.dtype), v_cache)
    return out.reshape(b, s, h, d)


def gqa_cached_attention_tp(q, k_cache, v_cache, q_positions, mesh,
                            axis_name: str = "tp"):
    """The gqa cached core under ``shard_map`` — the per-shard spelling the
    partition registry's ``mode="shard_map"`` plans compile to. The kv-head
    axis shards over ``axis_name``; q's head axis shards with it (each kv
    head's whole query group stays on its shard, so the grouped reshape
    inside the core is local), positions replicate, and the output gathers
    back at query-head width. No cross-shard reduction exists — softmax and
    both einsums are per-kv-head — so the result is BIT-EXACT against
    running the core on each head slice separately (pinned in
    tests/test_ml_parallel.py). Against the MONOLITHIC unsharded program it
    agrees only to kernel-scheduling tolerance: XLA may order the d-axis
    contraction differently for the fused full-width einsum (the tolerance
    half of the parity contract, docs/parity.md)."""
    kv = k_cache.shape[2]
    tp = dict(mesh.shape)[axis_name]
    if kv % tp:
        raise ValueError(f"kv_heads {kv} not divisible by {axis_name}={tp}")
    return _gqa_tp_compiled(mesh, axis_name)(q, k_cache, v_cache,
                                             q_positions)


@functools.lru_cache(maxsize=None)
def _gqa_tp_compiled(mesh, axis_name: str):
    """One compiled shard_map program per (mesh, axis_name) — jit's own
    cache covers shape variation inside it; without this memo every call
    would rebuild the closure and retrace."""
    from jax.sharding import PartitionSpec

    from tpu_task.ml.parallel.sharding import PartitionPlan, compile_step

    heads = PartitionSpec(None, None, axis_name, None)
    plan = PartitionPlan(
        mesh=mesh, mode="shard_map",
        in_specs=(heads, heads, heads, PartitionSpec()),
        out_specs=heads, check_vma=False)
    return compile_step(gqa_cached_attention, plan)


def mha_reference(q, k, v, causal: bool = True):
    """Plain XLA attention — the numerical ground truth for the kernels."""
    *_, d = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    if causal:
        q_len, k_len = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((q_len, k_len), dtype=bool), k=k_len - q_len)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


# -- forward kernel ----------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *maybe_lse_and_scratch,
                      causal: bool, q_offset: int, num_k_blocks: int):
    """One (batch*head, q_block, kv_block) grid cell: online softmax.

    q_ref: (block_q, d); k_ref/v_ref: (block_k, d) — the kv axis is a GRID
    dimension, so VMEM residency is O(block), not O(seq); the running
    (m, l, acc) state lives in VMEM scratch carried across kv iterations.
    Optional lse_ref: (block_q, LANES) float32 lane-broadcast logsumexp
    (only when the caller needs it for a backward pass).
    """
    from jax.experimental import pallas as pl

    if len(maybe_lse_and_scratch) == 4:
        lse_ref, m_ref, l_ref, acc_ref = maybe_lse_and_scratch
    else:
        lse_ref = None
        m_ref, l_ref, acc_ref = maybe_lse_and_scratch
    block_q, d = q_ref.shape
    block_k = k_ref.shape[0]
    scale = 1.0 / math.sqrt(d)
    kb = pl.program_id(2)
    q_start = pl.program_id(1) * block_q + q_offset

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Causal: kv blocks entirely past the diagonal of this q block's last
    # row contribute nothing — skip their compute (their DMA still streams,
    # but attention at these shapes is MXU-bound).
    live = (kb * block_k <= q_start + block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        # Keep inputs in their storage dtype (bf16 on TPU) and accumulate
        # the matmuls in f32 via preferred_element_type — f32 MXU passes are
        # several times slower than bf16 ones.
        q = q_ref[...]
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        m = m_ref[...][:, 0]
        l = l_ref[...][:, 0]
        s = _dot_nt(q, k_blk) * scale  # (block_q, block_k) f32
        if causal:
            q_pos = q_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # Fully-masked rows keep m == NEG_INF; clamp the shift so exp stays 0.
        shift = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - shift[:, None])
        if causal:
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        correction = jnp.exp(jnp.where(m <= NEG_INF / 2, NEG_INF, m) - shift)
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(
            (l * correction + p.sum(axis=-1))[:, None], l_ref.shape)
        acc_ref[...] = acc_ref[...] * correction[:, None] + _dot_nn(
            p.astype(v_blk.dtype), v_blk)

    @pl.when(kb == num_k_blocks - 1)
    def _finalize():
        m = m_ref[...][:, 0]
        l = l_ref[...][:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        if lse_ref is not None:
            shift = jnp.where(m <= NEG_INF / 2, 0.0, m)
            lse = jnp.where(l == 0.0, NEG_INF, shift + jnp.log(l_safe))
            lse_ref[...] = jnp.broadcast_to(lse[:, None], lse_ref.shape)


def flash_attention(
    q, k, v, causal: bool = True, *, q_offset=None,
    block_q: int | None = None, block_k: int | None = None,
    interpret: bool = False, return_lse: bool = False,
):
    """Pallas flash attention forward. q: (b, sq, h, d), k/v: (b, sk, h, d).

    ``q_offset`` is the global position of q row 0 relative to k col 0; the
    default ``sk - sq`` matches :func:`mha_reference`'s suffix-aligned causal
    mask (equal for self-attention). With ``return_lse`` also returns the
    float32 per-row logsumexp with shape (b, h, sq).
    """
    from jax.experimental import pallas as pl

    b, sq, h, d = q.shape
    sk = k.shape[1]
    if q_offset is None:
        q_offset = sk - sq
    # Default to the largest MXU-friendly block that DIVIDES the length —
    # a fixed default would reject e.g. 1536-chunk ring shards. The
    # aggressive 2048-q / whole-kv picks apply only to the pure forward:
    # with the f32 lane-broadcast lse output in the pipeline they push the
    # kernel past v5e's 16M scoped-vmem limit (measured 17.8M at seq 2048).
    if return_lse:
        block_q = min(block_q or _pick_block(sq), sq)
        block_k = min(block_k or _pick_block(sk), sk)
    else:
        block_q = min(block_q or _pick_block_fwd_q(sq), sq)
        block_k = min(block_k or _pick_block_fwd_k(sk, causal), sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq},{sk}) must divide blocks ({block_q},{block_k})")

    # Fold heads into the leading grid dim: (b*h, seq, d).
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    from jax.experimental.pallas import tpu as pltpu

    vma = _vma(q, k, v)
    num_k_blocks = sk // block_k
    kernel = functools.partial(
        _flash_fwd_kernel, causal=causal, q_offset=q_offset,
        num_k_blocks=num_k_blocks,
    )
    # kv is the minor grid dim: (m, l, acc) scratch carries across it, so
    # VMEM holds one q/k/v block at a time — O(block), any sequence length.
    grid = (b * h, sq // block_q, num_k_blocks)
    out_specs = [
        pl.BlockSpec((None, block_q, d), lambda bh, qb, kb: (bh, qb, 0))]
    out_shape = [_out_struct((b * h, sq, d), q.dtype, vma)]
    if return_lse:
        out_specs.append(
            pl.BlockSpec((None, block_q, LANES), lambda bh, qb, kb: (bh, qb, 0)))
        out_shape.append(
            _out_struct((b * h, sq, LANES), jnp.float32, vma))
    results = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qb, kb: (bh, qb, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, qb, kb: (bh, kb, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, qb, kb: (bh, kb, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running sum
            pltpu.VMEM((block_q, d), jnp.float32),      # output accumulator
        ],
        interpret=interpret,
    )(qf, kf, vf)
    out = results[0].reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    if return_lse:
        return out, results[1][..., 0].reshape(b, h, sq)
    return out


# -- backward kernels --------------------------------------------------------

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc_ref, *, causal: bool, q_offset: int,
                         num_k_blocks: int):
    """dq for one (q block, kv block) grid cell: recompute p from lse.

    q_ref/do_ref/dq_ref: (block_q, d); k_ref/v_ref: (block_k, d) — kv is a
    grid dimension (O(block) VMEM); dq accumulates in VMEM scratch;
    lse_ref/delta_ref: (block_q, LANES) lane-broadcast row stats.
    """
    from jax.experimental import pallas as pl

    block_q, d = q_ref.shape
    block_k = k_ref.shape[0]
    scale = 1.0 / math.sqrt(d)
    kb = pl.program_id(2)
    q_start = pl.program_id(1) * block_q + q_offset

    @pl.when(kb == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    live = (kb * block_k <= q_start + block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[...]  # storage dtype; f32 accumulation via the dots below
        do = do_ref[...]
        lse = lse_ref[...][:, 0]
        delta = delta_ref[...][:, 0]
        lse_safe = jnp.where(lse <= NEG_INF / 2, 0.0, lse)
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        s = _dot_nt(q, k_blk) * scale
        p = jnp.exp(s - lse_safe[:, None])
        if causal:
            q_pos = q_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = _dot_nt(do, v_blk)
        ds = p * (dp - delta[:, None])
        dq_acc_ref[...] = dq_acc_ref[...] + _dot_nn(
            ds.astype(k_blk.dtype), k_blk)

    @pl.when(kb == num_k_blocks - 1)
    def _finalize():
        dq_ref[...] = (dq_acc_ref[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *,
                          causal: bool, q_offset: int, num_q_blocks: int):
    """dk/dv for one (kv block, q block) grid cell: recompute p from lse.

    k_ref/v_ref/dk_ref/dv_ref: (block_kv, d); q_ref/do_ref: (block_q, d) —
    q is a grid dimension (O(block) VMEM); dk/dv accumulate in VMEM scratch;
    lse_ref/delta_ref: (block_q, LANES) lane-broadcast row stats.
    """
    from jax.experimental import pallas as pl

    block_kv, d = k_ref.shape
    block_q = q_ref.shape[0]
    scale = 1.0 / math.sqrt(d)
    qb = pl.program_id(2)
    k_start = pl.program_id(1) * block_kv

    @pl.when(qb == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    # Causal: only q blocks whose last row reaches this kv block contribute.
    live = ((qb + 1) * block_q - 1 + q_offset >= k_start) if causal else True

    @pl.when(live)
    def _compute():
        k = k_ref[...]  # storage dtype; f32 accumulation via the dots below
        v = v_ref[...]
        q_blk = q_ref[...]
        do_blk = do_ref[...]
        lse = lse_ref[...][:, 0]
        delta = delta_ref[...][:, 0]
        s = _dot_nt(q_blk, k) * scale  # (block_q, block_kv)
        lse_safe = jnp.where(lse <= NEG_INF / 2, 0.0, lse)
        p = jnp.exp(s - lse_safe[:, None])
        if causal:
            q_pos = qb * block_q + q_offset + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            k_pos = k_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        pc = p.astype(do_blk.dtype)
        dv_acc_ref[...] = dv_acc_ref[...] + _dot_tn(pc, do_blk)
        dp = _dot_nt(do_blk, v)
        ds = p * (dp - delta[:, None])
        dk_acc_ref[...] = dk_acc_ref[...] + _dot_tn(
            ds.astype(q_blk.dtype), q_blk)

    @pl.when(qb == num_q_blocks - 1)
    def _finalize():
        dk_ref[...] = (dk_acc_ref[...] * scale).astype(dk_ref.dtype)
        dv_ref[...] = dv_acc_ref[...].astype(dv_ref.dtype)


def flash_attention_bwd(
    q, k, v, o, lse, do, causal: bool = True, *, q_offset=None,
    block_q: int | None = None, block_k: int | None = None,
    interpret: bool = False,
):
    """Pallas flash attention backward: (dq, dk, dv).

    ``lse``: (b, h, sq) float32 from the forward pass. Recomputes attention
    probabilities per block — O(seq·d) memory, no S² matrix.
    """
    # delta_i = sum_d dO_i · O_i — the softmax-normalization term of ds.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)  # (b, h, sq)
    return _flash_bwd_with_stats(
        q, k, v, do, lse, delta, causal, q_offset=q_offset,
        block_q=block_q, block_k=block_k, interpret=interpret)


# -- block primitives (used standalone and by ring attention) ----------------

def block_attention_fwd(q, k, v, causal: bool, *, q_offset=None,
                        impl: str = "xla", interpret: bool = False,
                        block_q: int | None = None,
                        block_k: int | None = None):
    """(o, lse) for one attention block pair; ``impl`` = "xla" | "pallas".

    o: (b, sq, h, d) in q.dtype (rows with no valid keys are 0);
    lse: (b, h, sq) float32 (NEG_INF for fully-masked rows).
    """
    if impl == "pallas":
        return flash_attention(
            q, k, v, causal, q_offset=q_offset, block_q=block_q,
            block_k=block_k, interpret=interpret, return_lse=True)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if q_offset is None:
        q_offset = sk - sq
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / math.sqrt(d)
    if causal:
        q_pos = q_offset + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    m = s.max(axis=-1)
    shift = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.exp(s - shift[..., None])
    if causal:
        p = jnp.where((q_pos >= k_pos)[None, None], p, 0.0)
    l = p.sum(axis=-1)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = jnp.einsum("bhqk,bkhd->bqhd", p / l_safe[..., None],
                   v.astype(jnp.float32))
    lse = jnp.where(l == 0.0, NEG_INF, shift + jnp.log(l_safe))
    return o.astype(q.dtype), lse


def block_attention_bwd(q, k, v, do, lse, delta, causal: bool, *,
                        q_offset=None, impl: str = "xla",
                        interpret: bool = False,
                        block_q: int | None = None,
                        block_k: int | None = None):
    """(dq, dk, dv) for one block pair given global lse/delta.

    ``delta``: (b, h, sq) float32 = rowsum(dO · O) over the *global* output.
    Contributions are exact partial sums: summing over all kv blocks of a row
    reproduces the full gradient.
    """
    if impl == "pallas":
        return _flash_bwd_with_stats(
            q, k, v, do, lse, delta, causal, q_offset=q_offset,
            block_q=block_q, block_k=block_k, interpret=interpret)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if q_offset is None:
        q_offset = sk - sq
    scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        q_pos = q_offset + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        valid = (q_pos >= k_pos)[None, None]
    else:
        valid = None
    lse_safe = jnp.where(lse <= NEG_INF / 2, 0.0, lse)
    p = jnp.exp(s - lse_safe[..., None])
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    do32 = do.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, do32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", do32, v.astype(jnp.float32))
    ds = p * (dp - delta[..., None])
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, k.astype(jnp.float32)) * scale
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, q.astype(jnp.float32)) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_bwd_with_stats(q, k, v, do, lse, delta, causal, *, q_offset,
                          block_q, block_k, interpret):
    """Pallas backward given externally-computed (lse, delta)."""
    from jax.experimental import pallas as pl

    b, sq, h, d = q.shape
    sk = k.shape[1]
    if q_offset is None:
        q_offset = sk - sq
    block_q = min(block_q or _pick_block(sq), sq)
    block_k = min(block_k or _pick_block(sk), sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq},{sk}) must divide blocks ({block_q},{block_k})")
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    dof = do.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    # Lane-broadcast the per-row stats (Mosaic block layout; see LANES).
    lsef = jnp.broadcast_to(
        lse.reshape(b * h, sq)[..., None], (b * h, sq, LANES))
    deltaf = jnp.broadcast_to(
        delta.reshape(b * h, sq)[..., None], (b * h, sq, LANES))
    vma = _vma(q, k, v, do)

    from jax.experimental.pallas import tpu as pltpu

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, causal=causal, q_offset=q_offset,
        num_k_blocks=sk // block_k)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qb, kb: (bh, qb, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, qb, kb: (bh, kb, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, qb, kb: (bh, kb, 0)),
            pl.BlockSpec((None, block_q, d), lambda bh, qb, kb: (bh, qb, 0)),
            pl.BlockSpec((None, block_q, LANES), lambda bh, qb, kb: (bh, qb, 0)),
            pl.BlockSpec((None, block_q, LANES), lambda bh, qb, kb: (bh, qb, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda bh, qb, kb: (bh, qb, 0)),
        out_shape=_out_struct((b * h, sq, d), q.dtype, vma),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, causal=causal, q_offset=q_offset,
        num_q_blocks=sq // block_q)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, sk // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, kb, qb: (bh, qb, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, kb, qb: (bh, kb, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, kb, qb: (bh, kb, 0)),
            pl.BlockSpec((None, block_q, d), lambda bh, kb, qb: (bh, qb, 0)),
            pl.BlockSpec((None, block_q, LANES), lambda bh, kb, qb: (bh, qb, 0)),
            pl.BlockSpec((None, block_q, LANES), lambda bh, kb, qb: (bh, qb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda bh, kb, qb: (bh, kb, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, kb, qb: (bh, kb, 0)),
        ],
        out_shape=[
            _out_struct((b * h, sk, d), k.dtype, vma),
            _out_struct((b * h, sk, d), v.dtype, vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    unflatten = lambda x, s: x.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return unflatten(dq, sq), unflatten(dk, sk), unflatten(dv, sk)


# -- fused op with custom vjp ------------------------------------------------

def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


def _pick_block(s: int, cap: int = 1024) -> int:
    """Largest MXU-friendly block dividing s, bounded by ``cap``.

    The default 1024 cap is the backward kernels' (and the lse-emitting
    forward's) sweet spot on v5e: bq=2048 slows dq by 1.6x at seq 2048 and
    fails to compile at 8192 (min-of-5 timings on chip)."""
    for b in (2048, 1024, 512, 256, 128):
        if b <= cap and s % b == 0:
            return b
    return s


def _pick_block_fwd_q(s: int) -> int:
    """Pure-forward q-block: 2048 over 1024 on v5e — the no-lse forward
    holds few enough VMEM tiles that the larger tile fits and amortizes
    the softmax rescale passes. Block sweep at the bench shape (b=2 h=8
    d=128 seq=2048, interleaved min-of-8 vs XLA, 2026-07-30): bq/bk
    2048/whole-kv 2.55 ms, 1024/1024 3.65 ms, 2048/1024 3.48 ms,
    1024/2048 2.53 ms — big tiles win even though whole-kv computes the
    full causal rectangle."""
    return _pick_block(s, cap=2048)


def _pick_block_fwd_k(sk: int, causal: bool) -> int:
    """Pure-forward k-block: single block when the whole kv sequence fits
    one (<=2048) — no grid streaming, no rescale passes; the fastest
    measured config at seq 2048 (see _pick_block_fwd_q's sweep table).
    NOTE on magnitude: at seq 2048 the win over XLA is modest and
    load-sensitive — driver captures across rounds r02-r05 put it at
    1.03-1.14x (both paths sit near the same dispatch/DMA floor on v5e);
    the flash advantage grows with sequence length (~2x at 8k, larger at
    32k where XLA's S^2 materialization thrashes HBM). bench.py logs the
    block picks it compiles so claim and capture stay auditable against
    each other. Causal only: the non-causal kernel with a 2048 k-tile
    exceeds the 16M scoped-vmem limit on v5e (Mosaic keeps the full
    rectangle live without the diagonal gating), so it stays on the 1024
    cap, as does any longer kv sequence."""
    if causal and sk <= 2048:
        return sk
    return _pick_block(sk)


def _pallas_ok(q, k, causal: bool, block: int = 128) -> bool:
    if q.shape[1] % block or k.shape[1] % block:
        return False
    # Causal with sq > sk leaves leading q rows with zero valid keys —
    # attention over the empty set. The flash kernel zeroes those rows while
    # mha_reference softmaxes uniform garbage; keep one semantics per call
    # by routing the degenerate case to the fallback on every backend.
    return not causal or q.shape[1] <= k.shape[1]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _pallas_attention(q, k, v, causal, interpret):
    # Same 1024 blocks as _pa_fwd, NOT the tuned pure-forward picks: the
    # primal runs outside jax.grad and the fwd rule inside it, and a block
    # mismatch would give train and eval bitwise-different activations
    # (bf16 accumulation order). Pure inference wanting the big-block
    # forward calls flash_attention directly.
    return flash_attention(
        q, k, v, causal, block_q=_pick_block(q.shape[1]),
        block_k=_pick_block(k.shape[1]), interpret=interpret)


def _pa_fwd(q, k, v, causal, interpret):
    # lse path: conservative 1024 blocks (see the scoped-vmem note in
    # flash_attention's default-block selection).
    o, lse = flash_attention(
        q, k, v, causal, block_q=_pick_block(q.shape[1]),
        block_k=_pick_block(k.shape[1]),
        interpret=interpret, return_lse=True)
    return o, (q, k, v, o, lse)


def _pa_bwd(causal, interpret, res, g):
    q, k, v, o, lse = res
    return flash_attention_bwd(
        q, k, v, o, lse, g, causal, block_q=_pick_block(q.shape[1]),
        block_k=_pick_block(k.shape[1]), interpret=interpret)


_pallas_attention.defvjp(_pa_fwd, _pa_bwd)


def dot_product_attention(q, k, v, causal: bool = True):
    """Attention: flash kernels (fwd+bwd) on TPU, remat XLA elsewhere.

    The XLA fallback is wrapped in ``jax.checkpoint`` so its backward also
    recomputes instead of saving the S² attention matrix.
    """
    if _use_pallas() and _pallas_ok(q, k, causal):
        return _pallas_attention(q, k, v, causal, False)
    return jax.checkpoint(
        lambda q, k, v: mha_reference(q, k, v, causal))(q, k, v)
