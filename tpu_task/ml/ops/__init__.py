"""TPU kernels (pallas) and their XLA reference implementations."""

from tpu_task.ml.ops.attention import dot_product_attention, mha_reference

__all__ = ["dot_product_attention", "mha_reference"]
