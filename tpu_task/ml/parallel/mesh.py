"""Device-mesh construction and multi-host JAX bring-up.

The orchestrator exports ``TPU_TASK_COORDINATOR`` / ``TPU_TASK_NUM_WORKERS`` /
``TPU_TASK_WORKER_ID`` on every TPU-VM worker (the TPU-native analog of the
reference's only rank mechanism, K8s IndexedCompletion —
/root/reference/task/k8s/resources/resource_job.go:135-140).
``distributed_init_from_env`` turns those into ``jax.distributed.initialize``
so a user script gets a global view of every chip in the slice.

Meshes carry the standard axis vocabulary:

* ``dp``   — pure data parallelism (params replicated)
* ``fsdp`` — data parallelism with parameter sharding (ZeRO-3 style)
* ``tp``   — tensor (model) parallelism inside each layer
* ``sp``   — sequence/context parallelism (ring attention)

XLA inserts the collectives; shardings ride ICI within a slice.
"""

from __future__ import annotations

import math
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np


def balanced_mesh_shape(n_devices: int, n_axes: int = 3) -> Tuple[int, ...]:
    """Factor ``n_devices`` into ``n_axes`` near-equal power-of-two-ish factors.

    Greedy: repeatedly divide by the largest prime factor, assigning to the
    currently smallest axis. For 8 devices / 3 axes → (2, 2, 2).
    """
    if n_devices < 1:
        raise ValueError("n_devices must be >= 1")
    axes = [1] * n_axes
    remaining = n_devices
    while remaining > 1:
        # smallest prime factor
        factor = next(
            (p for p in range(2, int(math.isqrt(remaining)) + 1) if remaining % p == 0),
            remaining,
        )
        axes[axes.index(min(axes))] *= factor
        remaining //= factor
    return tuple(sorted(axes, reverse=True))


def make_mesh(
    n_devices: Optional[int] = None,
    *,
    axis_names: Sequence[str] = ("dp", "fsdp", "tp"),
    axis_sizes: Optional[Sequence[int]] = None,
    devices: Optional[Sequence] = None,
):
    """Build a ``jax.sharding.Mesh`` over the first ``n_devices`` devices.

    ``axis_sizes`` defaults to a balanced factorization of the device count.
    """
    import jax

    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if n_devices > len(devices):
                raise ValueError(
                    f"asked for {n_devices} devices, have {len(devices)}"
                )
            devices = devices[:n_devices]
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = balanced_mesh_shape(n, len(axis_names))
    if math.prod(axis_sizes) != n:
        raise ValueError(f"axis sizes {axis_sizes} != {n} devices")
    dev_array = np.asarray(devices).reshape(axis_sizes)
    return jax.sharding.Mesh(dev_array, tuple(axis_names))


def pvary(x, axis_names):
    """Mark ``x`` as device-varying over ``axis_names`` inside shard_map.

    Idempotent: an input already varying over the axes passes through (the
    raw primitive rejects varying→varying). Wraps ``lax.pcast(...,
    to='varying')`` (new name) with a fallback to the deprecated
    ``lax.pvary`` on older jax.
    """
    import jax
    from jax import lax

    vma = getattr(jax.typeof(x), "vma", frozenset())
    missing = tuple(axis for axis in axis_names if axis not in vma)
    if not missing:
        return x
    if hasattr(lax, "pcast"):
        try:
            return lax.pcast(x, missing, to="varying")
        except TypeError:
            pass
    return lax.pvary(x, missing)


def worker_env(worker_id: int, num_workers: int, coordinator: str) -> dict:
    """The env-var contract the orchestrator writes on each TPU-VM worker."""
    return {
        "TPU_TASK_WORKER_ID": str(worker_id),
        "TPU_TASK_NUM_WORKERS": str(num_workers),
        "TPU_TASK_COORDINATOR": coordinator,
    }


def distributed_init_from_env(environ=None) -> bool:
    """Call ``jax.distributed.initialize`` from orchestrator env vars.

    Returns True if multi-host init happened, False for single-host (no env
    or one worker). Safe to call unconditionally at the top of a user script.
    """
    env = os.environ if environ is None else environ
    num_workers = int(env.get("TPU_TASK_NUM_WORKERS", "1"))
    if num_workers <= 1:
        return False
    coordinator = env.get("TPU_TASK_COORDINATOR")
    worker_id = env.get("TPU_TASK_WORKER_ID")
    if not coordinator or worker_id is None:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_workers,
        process_id=int(worker_id),
    )
    return True


def local_batch_slice(global_batch: int, mesh) -> int:
    """Per-process batch size for a mesh whose batch axes span processes."""
    import jax

    return global_batch // jax.process_count()
