"""Device-mesh construction and multi-host JAX bring-up.

The orchestrator exports ``TPU_TASK_COORDINATOR`` / ``TPU_TASK_NUM_WORKERS`` /
``TPU_TASK_WORKER_ID`` on every TPU-VM worker (the TPU-native analog of the
reference's only rank mechanism, K8s IndexedCompletion —
/root/reference/task/k8s/resources/resource_job.go:135-140).
``distributed_init_from_env`` turns those into ``jax.distributed.initialize``
so a user script gets a global view of every chip in the slice.

Meshes carry the standard axis vocabulary:

* ``dp``   — pure data parallelism (params replicated)
* ``fsdp`` — data parallelism with parameter sharding (ZeRO-3 style)
* ``tp``   — tensor (model) parallelism inside each layer
* ``sp``   — sequence/context parallelism (ring attention)

XLA inserts the collectives; shardings ride ICI within a slice.
"""

from __future__ import annotations

import math
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np


def balanced_mesh_shape(n_devices: int, n_axes: int = 3) -> Tuple[int, ...]:
    """Factor ``n_devices`` into ``n_axes`` near-equal power-of-two-ish factors.

    Greedy: repeatedly divide by the largest prime factor, assigning to the
    currently smallest axis. For 8 devices / 3 axes → (2, 2, 2).
    """
    if n_devices < 1:
        raise ValueError("n_devices must be >= 1")
    axes = [1] * n_axes
    remaining = n_devices
    while remaining > 1:
        # smallest prime factor
        factor = next(
            (p for p in range(2, int(math.isqrt(remaining)) + 1) if remaining % p == 0),
            remaining,
        )
        axes[axes.index(min(axes))] *= factor
        remaining //= factor
    return tuple(sorted(axes, reverse=True))


def make_mesh(
    n_devices: Optional[int] = None,
    *,
    axis_names: Sequence[str] = ("dp", "fsdp", "tp"),
    axis_sizes: Optional[Sequence[int]] = None,
    devices: Optional[Sequence] = None,
):
    """Build a ``jax.sharding.Mesh`` over the first ``n_devices`` devices.

    ``axis_sizes`` defaults to a balanced factorization of the device count.
    """
    import jax

    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if n_devices > len(devices):
                raise ValueError(
                    f"asked for {n_devices} devices, have {len(devices)}"
                )
            devices = devices[:n_devices]
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = balanced_mesh_shape(n, len(axis_names))
    if math.prod(axis_sizes) != n:
        raise ValueError(f"axis sizes {axis_sizes} != {n} devices")
    dev_array = np.asarray(devices).reshape(axis_sizes)
    return jax.sharding.Mesh(dev_array, tuple(axis_names))


def value_vma(x) -> frozenset:
    """``jax.typeof(x).vma`` — the mesh axes ``x`` varies over under
    shard_map — or ``frozenset()`` on jax versions predating the vma
    system (no ``jax.typeof``/``lax.pcast``/``lax.pvary``: those versions
    track no varying axes, so the degenerate answer is exact, not a lie).
    The single version gate every vma consumer shares."""
    import jax

    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return getattr(typeof(x), "vma", frozenset())


def shard_map(f, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` with a fallback for jax versions predating the
    top-level API: ``jax.experimental.shard_map.shard_map``, whose
    equivalent of ``check_vma`` is spelled ``check_rep``. The one place
    that knows both spellings — every shard_map call in the tree routes
    through here."""
    import jax

    if hasattr(jax, "shard_map"):
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as legacy_shard_map

    kwargs = {} if check_vma is None else {"check_rep": check_vma}
    return legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, **kwargs)


def axis_size(axis_name) -> int:
    """Static size of a mesh axis inside shard_map: ``lax.axis_size`` on
    jax versions that have it, the axis-env lookup (private module — the
    pre-axis_size spelling of the same table) on older ones."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    from jax._src.core import get_axis_env

    return get_axis_env().axis_size(axis_name)


def pvary(x, axis_names):
    """Mark ``x`` as device-varying over ``axis_names`` inside shard_map.

    Idempotent: an input already varying over the axes passes through (the
    raw primitive rejects varying→varying). Wraps ``lax.pcast(...,
    to='varying')`` (new name) with a fallback to the deprecated
    ``lax.pvary`` on older jax; on jax predating the vma system entirely
    it is the identity (there is no varying-axis bookkeeping to satisfy).
    """
    from jax import lax

    vma = value_vma(x)
    missing = tuple(axis for axis in axis_names if axis not in vma)
    if not missing:
        return x
    if hasattr(lax, "pcast"):
        try:
            return lax.pcast(x, missing, to="varying")
        except TypeError:
            pass
    if hasattr(lax, "pvary"):
        return lax.pvary(x, missing)
    return x


def worker_env(worker_id: int, num_workers: int, coordinator: str) -> dict:
    """The env-var contract the orchestrator writes on each TPU-VM worker."""
    return {
        "TPU_TASK_WORKER_ID": str(worker_id),
        "TPU_TASK_NUM_WORKERS": str(num_workers),
        "TPU_TASK_COORDINATOR": coordinator,
    }


def distributed_init_from_env(environ=None) -> bool:
    """Call ``jax.distributed.initialize`` from orchestrator env vars.

    Returns True if multi-host init happened, False for single-host (no env
    or one worker). Safe to call unconditionally at the top of a user script.
    """
    env = os.environ if environ is None else environ
    num_workers = int(env.get("TPU_TASK_NUM_WORKERS", "1"))
    if num_workers <= 1:
        return False
    coordinator = env.get("TPU_TASK_COORDINATOR")
    worker_id = env.get("TPU_TASK_WORKER_ID")
    if not coordinator or worker_id is None:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_workers,
        process_id=int(worker_id),
    )
    return True


def local_batch_slice(global_batch: int, mesh) -> int:
    """Per-process batch size for a mesh whose batch axes span processes."""
    import jax

    return global_batch // jax.process_count()
