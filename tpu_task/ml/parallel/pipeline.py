"""Pipeline parallelism over a ``pp`` mesh axis: GPipe and 1F1B schedules.

Layers are split into P contiguous stages, one per device along ``pp``;
the batch is split into M microbatches that stream through the stages with
``lax.ppermute`` hand-offs.

* :func:`pipeline_apply` — GPipe forward: M + P - 1 ticks (fill + drain);
  bubble fraction (P-1)/(M+P-1) shrinks as M grows.
* :func:`pipeline_train` — 1F1B training schedule: forward and backward
  interleave per microbatch, so a stage holds at most ~2P in-flight
  activations instead of all M (the reason 1F1B exists); the backward
  recomputes each stage's forward from its saved INPUT via ``jax.vjp``
  (activation recomputation), and gradients accumulate per stage.

Activations and outputs stay static-shaped (rolling buffers per stage) so
XLA compiles one program per stage — no data-dependent Python control flow.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from tpu_task.ml.parallel.mesh import shard_map as _shard_map


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
    mesh,
    n_microbatches: int,
    axis_name: str = "pp",
):
    """Run ``x`` through P pipeline stages.

    ``stage_params``: pytree whose leaves have a leading axis of size P
    (one slice per stage — sharded over ``axis_name``).
    ``stage_fn(params_slice, x_mb) -> y_mb`` must preserve the microbatch
    shape (it is one stage's chunk of layers).
    ``x``: (batch, ...) with batch divisible by ``n_microbatches``.
    """
    n_stages = mesh.shape[axis_name]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} not divisible by microbatches "
                         f"{n_microbatches}")
    mb = batch // n_microbatches
    micro = x.reshape(n_microbatches, mb, *x.shape[1:])

    def shard_fn(params_slice, micro_local):
        # params_slice leaves: (1, ...) — this stage's slice; drop the axis.
        params_stage = jax.tree.map(lambda p: p[0], params_slice)
        stage = lax.axis_index(axis_name)
        ticks = n_microbatches + n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        from tpu_task.ml.parallel.mesh import pvary

        carry = pvary(jnp.zeros_like(micro_local[0]), (axis_name,))
        outputs = pvary(jnp.zeros_like(micro_local), (axis_name,))

        def tick(t, state):
            carry, outputs = state
            mb_index = jnp.clip(t, 0, n_microbatches - 1)
            inject = micro_local[mb_index]
            inp = jnp.where(stage == 0, inject, carry)
            out = stage_fn(params_stage, inp)
            # Last stage banks its result for microbatch t - (P-1).
            out_index = jnp.clip(t - (n_stages - 1), 0, n_microbatches - 1)
            is_valid = (stage == n_stages - 1) & (t >= n_stages - 1)
            outputs = jnp.where(
                is_valid,
                outputs.at[out_index].set(out),
                outputs)
            carry = lax.ppermute(out, axis_name, perm)
            return carry, outputs

        _, outputs = lax.fori_loop(0, ticks, tick, (carry, outputs))
        # Only the last stage holds real outputs; psum replicates them.
        mask = (stage == n_stages - 1).astype(outputs.dtype)
        return lax.psum(outputs * mask, axis_name)

    fn = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            PartitionSpec(axis_name),   # prefix: every param leaf stage-sharded
            PartitionSpec(),            # microbatches replicated
        ),
        out_specs=PartitionSpec(),      # outputs replicated
    )
    return fn(stage_params, micro).reshape(batch, *x.shape[1:])


def pipeline_train(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
    targets: jnp.ndarray,
    loss_fn: Callable[..., jnp.ndarray],
    mesh,
    n_microbatches: int,
    axis_name: str = "pp",
    head_params: Any = None,
    batch_axes: tuple = (),
):
    """1F1B pipelined training step.

    Schedule: stage s runs the forward of microbatch m at tick ``m + s`` and
    its backward at tick ``m + 2(P-1) - s`` — the last stage's backward for
    m starts right after its forward (one-forward-one-backward steady
    state). A stage therefore keeps at most ``2(P-1-s)+1 ≤ 2P-1`` saved
    INPUTS in a ring buffer; the backward recomputes the stage forward from
    the saved input with ``jax.vjp`` and accumulates parameter gradients.
    Total ticks: M + 2P - 2.

    Without ``head_params``: ``loss_fn(out_mb, target_mb) -> scalar`` is
    evaluated by the LAST stage only; returns ``(mean_loss, grads)`` where
    ``grads`` has the same stage-stacked structure (leading axis P, sharded
    over ``pp``) as ``stage_params``.

    With ``head_params`` (a model head living after the last stage — final
    norm + unembed for an LM): ``loss_fn(head_params, out_mb, target_mb) ->
    scalar``, and the return grows to ``(mean_loss, grads, head_grads,
    dx)`` — ``head_grads`` matches ``head_params`` (replicated), ``dx`` is
    the loss gradient w.r.t. ``x`` (for backpropagating into an embedding
    that runs BEFORE the pipeline). Both are scaled to the microbatch-mean
    loss, like ``grads``.

    ``batch_axes``: mesh axes the BATCH dim shards over (dp×pp
    composition): each dp group pipelines its own batch slice through the
    same stages — the microbatch split happens PER SHARD (shard-local rows
    regroup into ``n_microbatches`` equal chunks: movement-free, and exact
    because an equal-size regrouping changes neither the full-batch mean
    loss, any parameter gradient, nor any row's dx). loss/grads/head_grads
    dp-average (equal shard sizes make the mean exact) while ``dx`` stays
    batch-sharded like ``x``. Requires ``loss_fn`` to be a mean over its
    microbatch tokens.
    """
    n_stages = mesh.shape[axis_name]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} not divisible by microbatches "
                         f"{n_microbatches}")
    mb = batch // n_microbatches
    buffer_slots = 2 * n_stages  # ≥ max in-flight (2P-1), power-of-2-ish
    with_head = head_params is not None
    batch_axes = tuple(batch_axes)
    batch_shards = 1
    for ax in batch_axes:
        batch_shards *= mesh.shape[ax]
    if mb % batch_shards:
        raise ValueError(
            f"microbatch size {mb} (batch {batch} / {n_microbatches}) not "
            f"divisible by the {batch_shards}-way batch sharding "
            f"({batch_axes})")
    mb_local = mb // batch_shards

    def shard_fn(params_slice, x_local, targets_local, head_local):
        from tpu_task.ml.parallel.mesh import pvary

        # Shard-local microbatch split: x arrives batch-sharded on dim 0
        # and regroups locally — a dim-1-of-(M, mb) spec would own
        # different rows than the dim-0 batch sharding and force a
        # whole-activation reshard collective every step.
        micro_local = x_local.reshape(
            n_microbatches, mb_local, *x_local.shape[1:])
        targets_micro = targets_local.reshape(
            n_microbatches, mb_local, *targets_local.shape[1:])

        # Mark per-stage params (and the head) varying over EVERY axis this
        # body computes across: differentiating w.r.t. an UNVARYING input
        # inside shard_map makes its cotangent psum over the unvaried axes
        # — over pp that would pollute the last stage's real head gradient
        # with every other stage's garbage one, and over dp it would turn
        # the per-shard mean-loss gradients into a sum (dp× too large).
        # With everything varying, reductions below are explicit.
        all_axes = (axis_name, *batch_axes)
        params_stage = jax.tree.map(
            lambda p: pvary(p[0], all_axes), params_slice)
        stage = lax.axis_index(axis_name)
        if with_head:
            head_local = jax.tree.map(
                lambda p: pvary(p, all_axes), head_local)
        ticks = n_microbatches + 2 * (n_stages - 1)
        fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]
        bwd_perm = [(i + 1, i) for i in range(n_stages - 1)]

        zero_mb = pvary(jnp.zeros_like(micro_local[0]), all_axes)
        state = (
            zero_mb,                                      # forward carry
            zero_mb,                                      # backward carry (dx)
            pvary(jnp.zeros((buffer_slots,) + micro_local.shape[1:],
                            micro_local.dtype), all_axes),  # input ring
            jax.tree.map(lambda p: pvary(jnp.zeros_like(p), all_axes),
                         params_stage),                   # grad accumulators
            pvary(jnp.zeros((), jnp.float32), all_axes),  # loss sum
            # Head-grad accumulators + banked per-microbatch dx (only
            # materialized when a head is attached).
            jax.tree.map(lambda p: pvary(jnp.zeros_like(p), all_axes),
                         head_local) if with_head else (),
            pvary(jnp.zeros_like(micro_local), all_axes)
            if with_head else (),
        )

        def tick(t, state):
            (fwd_carry, bwd_carry, ring, grads, loss_sum,
             head_grads, dx_bank) = state

            # ---- forward half: microbatch f = t - stage ----
            f = t - stage
            do_fwd = (f >= 0) & (f < n_microbatches)
            f_index = jnp.clip(f, 0, n_microbatches - 1)
            inject = micro_local[f_index]
            inp = jnp.where(stage == 0, inject, fwd_carry)
            slot_f = jnp.mod(f_index, buffer_slots)
            ring = jnp.where(do_fwd, ring.at[slot_f].set(inp), ring)
            out = stage_fn(params_stage, inp)

            # ---- backward half: microbatch b = t - 2(P-1) + stage ----
            b = t - 2 * (n_stages - 1) + stage
            do_bwd = (b >= 0) & (b < n_microbatches)
            b_index = jnp.clip(b, 0, n_microbatches - 1)
            saved_inp = ring[jnp.mod(b_index, buffer_slots)]
            out_b, vjp_fn = jax.vjp(stage_fn, params_stage, saved_inp)
            # Last stage: cotangent from the loss on its own (recomputed)
            # output; other stages: cotangent arriving from stage s+1.
            if with_head:
                # lax.cond, not compute-and-mask: with a model head the
                # loss fwd+bwd is a whole-vocab matmul pair comparable to a
                # stage's own compute — running it on every stage and
                # masking would waste ~P-fold head FLOPs. The predicate is
                # device-varying inside shard_map, so only the last stage
                # executes the head branch.
                def head_branch(operands):
                    out_v, target_v = operands
                    loss_v, (dhead_v, dloss_v) = jax.value_and_grad(
                        loss_fn, argnums=(0, 1))(head_local, out_v, target_v)
                    return loss_v, dhead_v, dloss_v.astype(out_v.dtype)

                def skip_branch(operands):
                    out_v, _target_v = operands
                    # pvary: fresh zeros are unvarying, but the head
                    # branch's outputs vary over the mesh axes — cond
                    # demands equal types from both branches.
                    return (pvary(jnp.zeros((), jnp.float32), all_axes),
                            jax.tree.map(
                                lambda p: pvary(jnp.zeros_like(p),
                                                all_axes), head_local),
                            pvary(jnp.zeros_like(out_v), all_axes))

                loss_b, dhead, dloss = lax.cond(
                    stage == n_stages - 1, head_branch, skip_branch,
                    (out_b, targets_micro[b_index]))
            else:
                loss_b, dloss = jax.value_and_grad(loss_fn)(
                    out_b, targets_micro[b_index])
            cot = jnp.where(stage == n_stages - 1,
                            dloss.astype(out_b.dtype), bwd_carry)
            dparams, dx = vjp_fn(cot)
            # jnp.where, not a 0/1 multiplier: bubble ticks run the backward
            # on ring zeros, and 0 * NaN (e.g. a stage whose VJP is singular
            # at 0) would poison the accumulator.
            grads = jax.tree.map(
                lambda g, d: g + jnp.where(do_bwd, d, jnp.zeros_like(d)),
                grads, dparams)
            loss_sum = loss_sum + jnp.where(
                do_bwd & (stage == n_stages - 1), loss_b, 0.0)
            if with_head:
                # Every stage computes a dhead from ITS out_b; only the
                # last stage's is the real head gradient — masked here so
                # the final psum replicates exactly it.
                head_live = do_bwd & (stage == n_stages - 1)
                head_grads = jax.tree.map(
                    lambda g, d: g + jnp.where(
                        head_live, d, jnp.zeros_like(d)),
                    head_grads, dhead)
                # Stage 0's dx w.r.t. its saved input IS dL/d(embedding)
                # for this microbatch; bank it (masked to stage 0 by the
                # final psum).
                dx_bank = jnp.where(
                    do_bwd & (stage == 0),
                    dx_bank.at[b_index].set(dx.astype(dx_bank.dtype)),
                    dx_bank)

            # ---- hand-offs (issued together so transfers overlap) ----
            fwd_carry = lax.ppermute(out, axis_name, fwd_perm)
            bwd_carry = lax.ppermute(dx, axis_name, bwd_perm)
            return (fwd_carry, bwd_carry, ring, grads, loss_sum,
                    head_grads, dx_bank)

        (_, _, _, grads, loss_sum, head_grads, dx_bank) = lax.fori_loop(
            0, ticks, tick, state)

        def batch_mean(value):
            for ax in batch_axes:
                value = lax.pmean(value, ax)
            return value

        # Loss lives on the last stage only; replicate over pp, average the
        # per-dp-shard means (equal shard sizes → exact). Grads stay
        # per-stage, scaled to match the MEAN loss (each tick accumulated
        # one microbatch's unscaled gradient), dp-averaged.
        loss = batch_mean(lax.psum(loss_sum, axis_name) / n_microbatches)
        grads = jax.tree.map(
            lambda g: batch_mean(g / n_microbatches), grads)
        stacked = jax.tree.map(lambda g: g[None], grads)
        if not with_head:
            return loss, stacked
        # Head grads live (masked) on the last stage, banked dx on stage 0:
        # one psum each replicates them from their owning stage. dx stays
        # batch-sharded (it backs the embedding's batch-sharded cotangent)
        # and carries the SAME 1/(M·dp_shards) scaling a global-mean loss
        # implies per token — the dp mean that batch_mean applies to the
        # parameter grads shows up here as a plain divide.
        head_grads = jax.tree.map(
            lambda g: batch_mean(lax.psum(g, axis_name) / n_microbatches),
            head_grads)
        dx = lax.psum(
            jnp.where(stage == 0, dx_bank, jnp.zeros_like(dx_bank)),
            axis_name) / (n_microbatches * batch_shards)
        # Undo the local microbatch regrouping so dx rows line up with this
        # shard's slice of x.
        return loss, stacked, head_grads, dx.reshape(
            n_microbatches * mb_local, *dx.shape[2:])

    batch_spec = (PartitionSpec(batch_axes) if batch_axes
                  else PartitionSpec())
    out_specs = (PartitionSpec(), PartitionSpec(axis_name))
    if with_head:
        out_specs = out_specs + (PartitionSpec(), batch_spec)
    fn = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            PartitionSpec(axis_name),   # stage-sharded params
            batch_spec,                 # batch dim over batch_axes
            batch_spec,                 # targets likewise
            PartitionSpec(),            # head params replicated
        ),
        out_specs=out_specs,
    )
    return fn(stage_params, x, targets,
              head_params if with_head else ())
