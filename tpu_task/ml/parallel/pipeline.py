"""GPipe-style pipeline parallelism over a ``pp`` mesh axis.

Layers are split into P contiguous stages, one per device along ``pp``;
the batch is split into M microbatches that stream through the stages with
``lax.ppermute`` hand-offs. The schedule runs M + P - 1 ticks (fill + drain);
bubble fraction (P-1)/(M+P-1) shrinks as M grows. Activations and outputs
stay static-shaped (a single rolling buffer per stage) so XLA compiles one
program per stage — no data-dependent Python control flow.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
    mesh,
    n_microbatches: int,
    axis_name: str = "pp",
):
    """Run ``x`` through P pipeline stages.

    ``stage_params``: pytree whose leaves have a leading axis of size P
    (one slice per stage — sharded over ``axis_name``).
    ``stage_fn(params_slice, x_mb) -> y_mb`` must preserve the microbatch
    shape (it is one stage's chunk of layers).
    ``x``: (batch, ...) with batch divisible by ``n_microbatches``.
    """
    n_stages = mesh.shape[axis_name]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} not divisible by microbatches "
                         f"{n_microbatches}")
    mb = batch // n_microbatches
    micro = x.reshape(n_microbatches, mb, *x.shape[1:])

    def shard_fn(params_slice, micro_local):
        # params_slice leaves: (1, ...) — this stage's slice; drop the axis.
        params_stage = jax.tree.map(lambda p: p[0], params_slice)
        stage = lax.axis_index(axis_name)
        ticks = n_microbatches + n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        from tpu_task.ml.parallel.mesh import pvary

        carry = pvary(jnp.zeros_like(micro_local[0]), (axis_name,))
        outputs = pvary(jnp.zeros_like(micro_local), (axis_name,))

        def tick(t, state):
            carry, outputs = state
            mb_index = jnp.clip(t, 0, n_microbatches - 1)
            inject = micro_local[mb_index]
            inp = jnp.where(stage == 0, inject, carry)
            out = stage_fn(params_stage, inp)
            # Last stage banks its result for microbatch t - (P-1).
            out_index = jnp.clip(t - (n_stages - 1), 0, n_microbatches - 1)
            is_valid = (stage == n_stages - 1) & (t >= n_stages - 1)
            outputs = jnp.where(
                is_valid,
                outputs.at[out_index].set(out),
                outputs)
            carry = lax.ppermute(out, axis_name, perm)
            return carry, outputs

        _, outputs = lax.fori_loop(0, ticks, tick, (carry, outputs))
        # Only the last stage holds real outputs; psum replicates them.
        mask = (stage == n_stages - 1).astype(outputs.dtype)
        return lax.psum(outputs * mask, axis_name)

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            PartitionSpec(axis_name),   # prefix: every param leaf stage-sharded
            PartitionSpec(),            # microbatches replicated
        ),
        out_specs=PartitionSpec(),      # outputs replicated
    )
    return fn(stage_params, micro).reshape(batch, *x.shape[1:])
