"""Parallelism: device meshes, sharding rules, sequence-parallel attention."""

from tpu_task.ml.parallel.mesh import (
    balanced_mesh_shape,
    distributed_init_from_env,
    make_mesh,
)
from tpu_task.ml.parallel.sharding import (
    PartitionPlan,
    compile_step,
    device_put_tree,
    logical_to_mesh_axes,
    match_partition_rules,
    named_sharding,
    pspecs_to_shardings,
    shard_pytree,
)

__all__ = [
    "PartitionPlan",
    "balanced_mesh_shape",
    "compile_step",
    "device_put_tree",
    "distributed_init_from_env",
    "logical_to_mesh_axes",
    "make_mesh",
    "match_partition_rules",
    "named_sharding",
    "pspecs_to_shardings",
    "shard_pytree",
]
