"""Parallelism: device meshes, sharding rules, sequence-parallel attention."""

from tpu_task.ml.parallel.mesh import (
    balanced_mesh_shape,
    distributed_init_from_env,
    make_mesh,
)
from tpu_task.ml.parallel.sharding import (
    logical_to_mesh_axes,
    named_sharding,
    shard_pytree,
)

__all__ = [
    "balanced_mesh_shape",
    "distributed_init_from_env",
    "logical_to_mesh_axes",
    "make_mesh",
    "named_sharding",
    "shard_pytree",
]
