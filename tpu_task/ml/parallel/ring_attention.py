"""Ring attention: exact attention over sequence-sharded q/k/v.

Long-context path: the sequence axis is sharded over the ``sp`` mesh axis;
each device holds one q chunk and streams k/v chunks around the ring with
``lax.ppermute`` (ICI neighbor exchange), folding each block into an online
softmax accumulator. Communication overlaps compute and per-device memory is
O(seq/P) — the standard blockwise/ring construction (Liu et al.).

Causality across chunks is decided by global chunk index: a source chunk
entirely in the future is masked out, the diagonal chunk gets the local
triangular mask, past chunks attend fully.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

NEG_INF = -1e30


def _block_attn(q, k, v, mask, m, l, acc):
    """Fold one k/v block into the online-softmax accumulator.

    q: (b, sq, h, d); k/v: (b, sk, h, d); mask: (sq, sk) bool or None.
    m, l: (b, h, sq); acc: (b, sq, h, d). All accumulators float32.
    """
    d = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / math.sqrt(d)
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # Fully-masked rows keep m == NEG_INF; exp(s - NEG_INF) would overflow,
    # so clamp the shift for those rows (their p is 0 anyway).
    shift = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - shift[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None], p, 0.0)
    correction = jnp.exp(m - shift)
    l_new = l * correction + p.sum(axis=-1)
    acc_new = acc * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
    )
    return m_new, l_new, acc_new


def ring_attention_shard(q, k, v, axis_name: str = "sp", causal: bool = True):
    """Per-shard body: call inside ``shard_map`` with seq sharded on axis_name.

    q/k/v: local chunks (batch, chunk, heads, head_dim).
    """
    axis_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape

    # pvary: mark the fresh accumulators as device-varying over the ring axis
    # so the fori_loop carry type matches after the first fold (JAX ≥0.8
    # tracks varying manual axes through shard_map).
    from tpu_task.ml.parallel.mesh import pvary

    m = pvary(jnp.full((b, h, sq), NEG_INF, jnp.float32), (axis_name,))
    l = pvary(jnp.zeros((b, h, sq), jnp.float32), (axis_name,))
    acc = pvary(jnp.zeros((b, sq, h, d), jnp.float32), (axis_name,))

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(step, carry):
        k_cur, v_cur, m, l, acc = carry
        src_idx = (my_idx - step) % axis_size
        sk = k_cur.shape[1]
        if causal:
            q_pos = my_idx * sq + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
            k_pos = src_idx * sk + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
            mask = q_pos >= k_pos
        else:
            mask = None
        m, l, acc = _block_attn(q, k_cur, v_cur, mask, m, l, acc)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m, l, acc

    k_fin, v_fin, m, l, acc = lax.fori_loop(0, axis_size, body, (k, v, m, l, acc))
    del k_fin, v_fin
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = acc / safe_l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name: str = "sp", causal: bool = True):
    """Global-view ring attention: q/k/v (batch, seq, heads, head_dim).

    Shards the sequence over ``axis_name`` with shard_map and runs the ring.
    """
    spec = PartitionSpec(None, axis_name, None, None)
    fn = jax.shard_map(
        functools.partial(ring_attention_shard, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
