"""Ring attention: exact attention over sequence-sharded q/k/v.

Long-context path: the sequence axis is sharded over the ``sp`` mesh axis;
each device holds one q chunk and streams k/v chunks around the ring with
``lax.ppermute`` (ICI neighbor exchange), folding each block's (output,
logsumexp) pair into a running softmax combination. Per-device memory is
O(seq/P) — the standard blockwise/ring construction (Liu et al.).

Per-block compute goes through ``tpu_task.ml.ops.attention``'s block
primitives: the Pallas flash kernel on TPU (``impl="pallas"``), plain XLA
elsewhere. The backward pass is a custom VJP that runs the ring again,
circulating dk/dv accumulators alongside their k/v blocks — the gradient for
each k/v chunk arrives back at its owner after one full rotation, and no
device ever materializes more than one remote chunk.

Causality across chunks is decided by global chunk index: the diagonal chunk
(step 0) gets the local triangular mask, past chunks attend fully, future
chunks are computed-and-discarded (weight 0) to keep the collective schedule
uniform.

Reference has no sequence parallelism at all (SURVEY.md §5 "long-context:
absent") — this is new TPU-first capability.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from tpu_task.ml.ops.attention import (
    NEG_INF,
    block_attention_bwd,
    block_attention_fwd,
)


def _fold(o, lse, o_b, lse_b):
    """Combine two (output, logsumexp) pairs of the same q rows.

    o/o_b: (b, sq, h, d); lse/lse_b: (b, h, sq). All-masked rows carry
    lse == NEG_INF and zero output; folding them is a no-op.
    """
    m = jnp.maximum(lse, lse_b)
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    w1 = jnp.exp(lse - m_safe)
    w2 = jnp.exp(lse_b - m_safe)
    denom = w1 + w2
    denom_safe = jnp.where(denom == 0.0, 1.0, denom)
    to_o = lambda w: (w / denom_safe).transpose(0, 2, 1)[..., None]
    o_new = o * to_o(w1) + o_b.astype(jnp.float32) * to_o(w2)
    lse_new = jnp.where(denom == 0.0, NEG_INF, m_safe + jnp.log(denom_safe))
    return o_new, lse_new


def _ring_fwd_impl(q, k, v, axis_name, causal, impl, interpret):
    axis_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)

    block = functools.partial(
        block_attention_fwd, impl=impl, interpret=interpret)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # Prefetch the first remote chunk, then compute the local (diagonal)
    # chunk while it is in flight — every block compute below reads only
    # chunks already on-device, so ICI transfers overlap attention compute.
    k_cur = lax.ppermute(k, axis_name, perm)
    v_cur = lax.ppermute(v, axis_name, perm)
    o_b, lse_b = block(q, k, v, causal, q_offset=0)
    o = o_b.astype(jnp.float32)
    lse = lse_b

    def body(step, carry):
        k_cur, v_cur, o, lse = carry
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        src_idx = (my_idx - step) % axis_size
        o_b, lse_b = block(q, k_cur, v_cur, False, q_offset=0)
        if causal:
            keep = src_idx < my_idx  # past chunk: full; future: discard
            lse_b = jnp.where(keep, lse_b, NEG_INF)
            o_b = jnp.where(keep, o_b, 0.0)
        o, lse = _fold(o, lse, o_b, lse_b)
        return k_nxt, v_nxt, o, lse

    _, _, o, lse = lax.fori_loop(1, axis_size, body, (k_cur, v_cur, o, lse))
    return o.astype(q.dtype), lse


def _ring_bwd_impl(q, k, v, o, lse, do, axis_name, causal, impl, interpret):
    """Ring backward: dk/dv accumulators circulate with their k/v blocks."""
    axis_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)

    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)  # (b, h, sq)

    block_bwd = functools.partial(
        block_attention_bwd, impl=impl, interpret=interpret)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # Same prefetch schedule as the forward: permutes are issued before the
    # block compute they overlap with. dk/dv accumulators ride one hop behind
    # their k/v chunks — the handoff received in step t belongs to the chunk
    # computed in step t, so only the cheap add waits on the transfer.
    k_cur = lax.ppermute(k, axis_name, perm)
    v_cur = lax.ppermute(v, axis_name, perm)
    dq_b, dk_b, dv_b = block_bwd(q, k, v, do, lse, delta, causal, q_offset=0)
    dq = dq_b.astype(jnp.float32)
    dk_acc = dk_b.astype(jnp.float32)
    dv_acc = dv_b.astype(jnp.float32)

    def body(step, carry):
        k_cur, v_cur, dk_acc, dv_acc, dq = carry
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        dk_in = lax.ppermute(dk_acc, axis_name, perm)
        dv_in = lax.ppermute(dv_acc, axis_name, perm)
        src_idx = (my_idx - step) % axis_size
        dq_b, dk_b, dv_b = block_bwd(
            q, k_cur, v_cur, do, lse, delta, False, q_offset=0)
        if causal:
            keep = src_idx < my_idx
            dq_b = jnp.where(keep, dq_b, 0.0)
            dk_b = jnp.where(keep, dk_b, 0.0)
            dv_b = jnp.where(keep, dv_b, 0.0)
        return (k_nxt, v_nxt,
                dk_in + dk_b.astype(jnp.float32),
                dv_in + dv_b.astype(jnp.float32),
                dq + dq_b.astype(jnp.float32))

    _, _, dk_acc, dv_acc, dq = lax.fori_loop(
        1, axis_size, body, (k_cur, v_cur, dk_acc, dv_acc, dq))
    # After the loop the accumulator for chunk j sits at device j-1: one
    # more hop brings every dk/dv home to its k/v owner.
    dk = lax.ppermute(dk_acc, axis_name, perm)
    dv = lax.ppermute(dv_acc, axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_shard(q, k, v, axis_name, causal, impl, interpret):
    o, _ = _ring_fwd_impl(q, k, v, axis_name, causal, impl, interpret)
    return o


def _ring_shard_fwd(q, k, v, axis_name, causal, impl, interpret):
    o, lse = _ring_fwd_impl(q, k, v, axis_name, causal, impl, interpret)
    return o, (q, k, v, o, lse)


def _ring_shard_bwd(axis_name, causal, impl, interpret, res, do):
    q, k, v, o, lse = res
    return _ring_bwd_impl(
        q, k, v, o, lse, do, axis_name, causal, impl, interpret)


_ring_shard.defvjp(_ring_shard_fwd, _ring_shard_bwd)


def _default_impl() -> str:
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def ring_attention_shard(q, k, v, axis_name: str = "sp", causal: bool = True,
                         impl: str | None = None, interpret: bool = False):
    """Per-shard body: call inside ``shard_map`` with seq sharded on axis_name.

    q/k/v: local chunks (batch, chunk, heads, head_dim). Differentiable:
    the VJP re-runs the ring, circulating dk/dv with their blocks.
    """
    if impl is None:
        impl = _default_impl()
    return _ring_shard(q, k, v, axis_name, causal, impl, interpret)


def ring_attention(q, k, v, mesh, axis_name: str = "sp", causal: bool = True,
                   impl: str | None = None, interpret: bool = False):
    """Global-view ring attention: q/k/v (batch, seq, heads, head_dim).

    Shards the sequence over ``axis_name`` with shard_map and runs the ring.
    """
    spec = PartitionSpec(None, axis_name, None, None)
    fn = jax.shard_map(
        functools.partial(ring_attention_shard, axis_name=axis_name,
                          causal=causal, impl=impl, interpret=interpret),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # pallas interpret mode can't track varying manual axes through its
        # HLO interpreter; the check stays on for the compiled TPU path.
        check_vma=not interpret,
    )
    return fn(q, k, v)
