"""Ring attention: exact attention over sequence-sharded q/k/v.

Long-context path: the sequence axis is sharded over the ``sp`` mesh axis;
each device holds one q chunk and streams k/v chunks around the ring with
``lax.ppermute`` (ICI neighbor exchange), folding each block's (output,
logsumexp) pair into a running softmax combination. Per-device memory is
O(seq/P) — the standard blockwise/ring construction (Liu et al.).

Per-block compute goes through ``tpu_task.ml.ops.attention``'s block
primitives: the Pallas flash kernel on TPU (``impl="pallas"``), plain XLA
elsewhere. The backward pass is a custom VJP that runs the ring again,
circulating dk/dv accumulators alongside their k/v blocks — the gradient for
each k/v chunk arrives back at its owner after one full rotation, and no
device ever materializes more than one remote chunk.

Causality across chunks is decided by global chunk index: the diagonal chunk
(step 0) gets the local triangular mask, past chunks attend fully, future
chunks are computed-and-discarded (weight 0) to keep the collective schedule
uniform.

Reference has no sequence parallelism at all (SURVEY.md §5 "long-context:
absent") — this is new TPU-first capability.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from tpu_task.ml.parallel.mesh import axis_size as _axis_size, shard_map as _shard_map
from tpu_task.ml.ops.attention import (
    NEG_INF,
    block_attention_bwd,
    block_attention_fwd,
    expand_kv_heads,
    reduce_kv_heads,
)

# Grouped-query attention's narrow k/v cross the ring NARROW — the
# ppermutes move kv_heads-width bytes and the expansion happens locally,
# right before each block kernel — so GQA's bandwidth saving survives the
# inter-chip hop (VERDICT r4 weak #5). One shared expansion rule
# (ops.attention.expand_kv_heads) keeps ring/ulysses/model semantics
# identical.
_expand_kv = expand_kv_heads
_reduce_kv_heads = reduce_kv_heads


def _fold(o, lse, o_b, lse_b):
    """Combine two (output, logsumexp) pairs of the same q rows.

    o/o_b: (b, sq, h, d); lse/lse_b: (b, h, sq). All-masked rows carry
    lse == NEG_INF and zero output; folding them is a no-op.
    """
    m = jnp.maximum(lse, lse_b)
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    w1 = jnp.exp(lse - m_safe)
    w2 = jnp.exp(lse_b - m_safe)
    denom = w1 + w2
    denom_safe = jnp.where(denom == 0.0, 1.0, denom)
    to_o = lambda w: (w / denom_safe).transpose(0, 2, 1)[..., None]
    o_new = o * to_o(w1) + o_b.astype(jnp.float32) * to_o(w2)
    lse_new = jnp.where(denom == 0.0, NEG_INF, m_safe + jnp.log(denom_safe))
    return o_new, lse_new


def _ring_fwd_impl(q, k, v, axis_name, causal, impl, interpret):
    axis_size = _axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    n_heads = q.shape[2]  # k/v may be narrower (GQA): expand per block

    def block(q_, k_, v_, causal_, q_offset):
        return block_attention_fwd(
            q_, _expand_kv(k_, n_heads), _expand_kv(v_, n_heads), causal_,
            q_offset=q_offset, impl=impl, interpret=interpret)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # Prefetch the first remote chunk, then compute the local (diagonal)
    # chunk while it is in flight — every block compute below reads only
    # chunks already on-device, so ICI transfers overlap attention compute.
    # k/v circulate at KV-head width; expansion is local (see _expand_kv).
    k_cur = lax.ppermute(k, axis_name, perm)
    v_cur = lax.ppermute(v, axis_name, perm)
    o_b, lse_b = block(q, k, v, causal, q_offset=0)
    o = o_b.astype(jnp.float32)
    lse = lse_b

    def body(step, carry):
        k_cur, v_cur, o, lse = carry
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        src_idx = (my_idx - step) % axis_size
        o_b, lse_b = block(q, k_cur, v_cur, False, q_offset=0)
        if causal:
            keep = src_idx < my_idx  # past chunk: full; future: discard
            lse_b = jnp.where(keep, lse_b, NEG_INF)
            o_b = jnp.where(keep, o_b, 0.0)
        o, lse = _fold(o, lse, o_b, lse_b)
        return k_nxt, v_nxt, o, lse

    _, _, o, lse = lax.fori_loop(1, axis_size, body, (k_cur, v_cur, o, lse))
    return o.astype(q.dtype), lse


def _ring_bwd_impl(q, k, v, o, lse, do, axis_name, causal, impl, interpret):
    """Ring backward: dk/dv accumulators circulate with their k/v blocks.

    Under GQA the accumulators stay at KV-head width: each block's expanded
    dk/dv is summed over the query group (the exact transpose of the local
    expansion) before joining the ring, so backward collective bytes shrink
    by the group factor too."""
    axis_size = _axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    n_heads = q.shape[2]
    kv_heads = k.shape[2]

    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)  # (b, h, sq)

    def block_bwd(q_, k_, v_, do_, lse_, delta_, causal_, q_offset):
        dq_b, dk_b, dv_b = block_attention_bwd(
            q_, _expand_kv(k_, n_heads), _expand_kv(v_, n_heads), do_,
            lse_, delta_, causal_, q_offset=q_offset, impl=impl,
            interpret=interpret)
        return (dq_b, _reduce_kv_heads(dk_b, kv_heads),
                _reduce_kv_heads(dv_b, kv_heads))

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # Same prefetch schedule as the forward: permutes are issued before the
    # block compute they overlap with. dk/dv accumulators ride one hop behind
    # their k/v chunks — the handoff received in step t belongs to the chunk
    # computed in step t, so only the cheap add waits on the transfer.
    k_cur = lax.ppermute(k, axis_name, perm)
    v_cur = lax.ppermute(v, axis_name, perm)
    dq_b, dk_b, dv_b = block_bwd(q, k, v, do, lse, delta, causal, q_offset=0)
    dq = dq_b.astype(jnp.float32)
    dk_acc = dk_b.astype(jnp.float32)
    dv_acc = dv_b.astype(jnp.float32)

    def body(step, carry):
        k_cur, v_cur, dk_acc, dv_acc, dq = carry
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        dk_in = lax.ppermute(dk_acc, axis_name, perm)
        dv_in = lax.ppermute(dv_acc, axis_name, perm)
        src_idx = (my_idx - step) % axis_size
        dq_b, dk_b, dv_b = block_bwd(
            q, k_cur, v_cur, do, lse, delta, False, q_offset=0)
        if causal:
            keep = src_idx < my_idx
            dq_b = jnp.where(keep, dq_b, 0.0)
            dk_b = jnp.where(keep, dk_b, 0.0)
            dv_b = jnp.where(keep, dv_b, 0.0)
        return (k_nxt, v_nxt,
                dk_in + dk_b.astype(jnp.float32),
                dv_in + dv_b.astype(jnp.float32),
                dq + dq_b.astype(jnp.float32))

    _, _, dk_acc, dv_acc, dq = lax.fori_loop(
        1, axis_size, body, (k_cur, v_cur, dk_acc, dv_acc, dq))
    # After the loop the accumulator for chunk j sits at device j-1: one
    # more hop brings every dk/dv home to its k/v owner.
    dk = lax.ppermute(dk_acc, axis_name, perm)
    dv = lax.ppermute(dv_acc, axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_shard(q, k, v, axis_name, causal, impl, interpret):
    o, _ = _ring_fwd_impl(q, k, v, axis_name, causal, impl, interpret)
    return o


def _ring_shard_fwd(q, k, v, axis_name, causal, impl, interpret):
    o, lse = _ring_fwd_impl(q, k, v, axis_name, causal, impl, interpret)
    return o, (q, k, v, o, lse)


def _ring_shard_bwd(axis_name, causal, impl, interpret, res, do):
    q, k, v, o, lse = res
    return _ring_bwd_impl(
        q, k, v, o, lse, do, axis_name, causal, impl, interpret)


_ring_shard.defvjp(_ring_shard_fwd, _ring_shard_bwd)


def _default_impl() -> str:
    return "pallas" if jax.default_backend() == "tpu" else "xla"


# -- zigzag (balanced causal) schedule ----------------------------------------
#
# The uniform schedule above computes-and-discards future chunks to keep the
# collective pattern static, wasting ~2× attention FLOPs for causal masks.
# The zigzag schedule (ring-flash-attention's balancing trick) removes the
# waste: the sequence is split into 2P stripes and device i holds the PAIR
# [stripe i, stripe 2P-1-i]. For any remote source s exactly half the
# (2 q-stripes × 2 k-stripes) rectangle is causally live —
#   s < i: both local q stripes attend k's FIRST stripe only;
#   s > i: only the local SECOND q stripe attends, but to both k stripes —
# so every device does the same 2c² block work each step (c = seq/(2P)),
# nothing is discarded, and the diagonal costs 2c² via the block kernel's
# own causal skipping. Total: ~half the uniform schedule's attention FLOPs.


def zigzag_permute(x, devices: int, axis: int = 1):
    """Global → zigzag layout: stripe order [0, 2P-1, 1, 2P-2, ...] so a
    contiguous 1/P shard holds stripes (i, 2P-1-i)."""
    stripes = 2 * devices
    length = x.shape[axis]
    if length % stripes:
        raise ValueError(f"sequence {length} not divisible by 2P={stripes}")
    order = []
    for index in range(devices):
        order += [index, stripes - 1 - index]
    parts = jnp.split(x, stripes, axis=axis)
    return jnp.concatenate([parts[j] for j in order], axis=axis)


def zigzag_unpermute(x, devices: int, axis: int = 1):
    """Inverse of :func:`zigzag_permute`."""
    stripes = 2 * devices
    order = []
    for index in range(devices):
        order += [index, stripes - 1 - index]
    inverse = [0] * stripes
    for position, stripe in enumerate(order):
        inverse[stripe] = position
    parts = jnp.split(x, stripes, axis=axis)
    return jnp.concatenate([parts[j] for j in inverse], axis=axis)


def _pad_rows(o_half, lse_half, c):
    """Extend an (o, lse) pair covering the SECOND stripe to all 2c rows
    (first stripe: zero output, NEG_INF lse — a no-op under folding)."""
    b, _, h, d = o_half.shape
    o_full = jnp.concatenate(
        [jnp.zeros((b, c, h, d), o_half.dtype), o_half], axis=1)
    lse_full = jnp.concatenate(
        [jnp.full((b, h, c), NEG_INF, lse_half.dtype), lse_half], axis=2)
    return o_full, lse_full


def _zigzag_fwd_impl(q, k, v, axis_name, impl, interpret):
    axis_size = _axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    c = q.shape[1] // 2
    n_heads = q.shape[2]  # k/v may be narrower (GQA): expand per block

    def block(q_, k_, v_, causal_, q_offset):
        return block_attention_fwd(
            q_, _expand_kv(k_, n_heads), _expand_kv(v_, n_heads), causal_,
            q_offset=q_offset, impl=impl, interpret=interpret)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    k_cur = lax.ppermute(k, axis_name, perm)
    v_cur = lax.ppermute(v, axis_name, perm)
    # Diagonal, two causally-tight blocks:
    #   rows [0,2c) vs k stripe 1 with q_offset=0 → stripe-1 causal for the
    #   first c rows, full for the second stripe's rows (col ≤ row);
    #   second stripe vs k stripe 2, plain causal (stripe-aligned positions).
    o, lse = block(q, k[:, :c], v[:, :c], True, q_offset=0)
    o = o.astype(jnp.float32)
    o_d2, lse_d2 = block(q[:, c:], k[:, c:], v[:, c:], True, q_offset=0)
    o, lse = _fold(o, lse, *_pad_rows(o_d2, lse_d2, c))

    def body(step, carry):
        k_cur, v_cur, o, lse = carry
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        src_idx = (my_idx - step) % axis_size

        def from_past(operands):
            k_c, v_c = operands  # s < i: all q rows × k's first stripe
            return block(q, k_c[:, :c], v_c[:, :c], False, q_offset=0)

        def from_future(operands):
            k_c, v_c = operands  # s > i: second q stripe × both k stripes
            o_half, lse_half = block(q[:, c:], k_c, v_c, False, q_offset=0)
            return _pad_rows(o_half, lse_half, c)

        o_b, lse_b = lax.cond(src_idx < my_idx, from_past, from_future,
                              (k_cur, v_cur))
        o, lse = _fold(o, lse, o_b, lse_b)
        return k_nxt, v_nxt, o, lse

    _, _, o, lse = lax.fori_loop(1, axis_size, body, (k_cur, v_cur, o, lse))
    return o.astype(q.dtype), lse


def _zigzag_bwd_impl(q, k, v, o, lse, do, axis_name, impl, interpret):
    axis_size = _axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    c = q.shape[1] // 2
    n_heads = q.shape[2]
    kv_heads = k.shape[2]

    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)  # (b, h, 2c)

    def block_bwd(q_, k_, v_, do_, lse_, delta_, causal_, q_offset):
        # Narrow k/v in, narrow dk/dv out (see _ring_bwd_impl).
        dq_b, dk_b, dv_b = block_attention_bwd(
            q_, _expand_kv(k_, n_heads), _expand_kv(v_, n_heads), do_,
            lse_, delta_, causal_, q_offset=q_offset, impl=impl,
            interpret=interpret)
        return (dq_b, _reduce_kv_heads(dk_b, kv_heads),
                _reduce_kv_heads(dv_b, kv_heads))

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    q2, do2 = q[:, c:], do[:, c:]
    lse2, delta2 = lse[:, :, c:], delta[:, :, c:]

    def pad_q(dq_half):
        return jnp.concatenate(
            [jnp.zeros((dq_half.shape[0], c) + dq_half.shape[2:],
                       jnp.float32), dq_half.astype(jnp.float32)], axis=1)

    def pad_k2(d_half):
        return jnp.concatenate(
            [d_half.astype(jnp.float32),
             jnp.zeros((d_half.shape[0], c) + d_half.shape[2:],
                       jnp.float32)], axis=1)

    k_cur = lax.ppermute(k, axis_name, perm)
    v_cur = lax.ppermute(v, axis_name, perm)
    # Diagonal: mirrors the forward's two causally-tight blocks.
    dq_a, dk1, dv1 = block_bwd(q, k[:, :c], v[:, :c], do, lse, delta,
                               True, q_offset=0)
    dq = dq_a.astype(jnp.float32)
    dq2_d, dk2, dv2 = block_bwd(q2, k[:, c:], v[:, c:], do2, lse2, delta2,
                                True, q_offset=0)
    dq = dq + pad_q(dq2_d)
    dk_acc = jnp.concatenate(
        [dk1.astype(jnp.float32), dk2.astype(jnp.float32)], axis=1)
    dv_acc = jnp.concatenate(
        [dv1.astype(jnp.float32), dv2.astype(jnp.float32)], axis=1)

    def body(step, carry):
        k_cur, v_cur, dk_acc, dv_acc, dq = carry
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        dk_in = lax.ppermute(dk_acc, axis_name, perm)
        dv_in = lax.ppermute(dv_acc, axis_name, perm)
        src_idx = (my_idx - step) % axis_size

        def from_past(operands):
            k_c, v_c = operands
            dq_b, dk_half, dv_half = block_bwd(
                q, k_c[:, :c], v_c[:, :c], do, lse, delta, False, q_offset=0)
            return (dq_b.astype(jnp.float32), pad_k2(dk_half),
                    pad_k2(dv_half))

        def from_future(operands):
            k_c, v_c = operands
            dq_half, dk_b, dv_b = block_bwd(
                q2, k_c, v_c, do2, lse2, delta2, False, q_offset=0)
            return (pad_q(dq_half), dk_b.astype(jnp.float32),
                    dv_b.astype(jnp.float32))

        dq_b, dk_b, dv_b = lax.cond(src_idx < my_idx, from_past, from_future,
                                    (k_cur, v_cur))
        return (k_nxt, v_nxt, dk_in + dk_b, dv_in + dv_b, dq + dq_b)

    _, _, dk_acc, dv_acc, dq = lax.fori_loop(
        1, axis_size, body, (k_cur, v_cur, dk_acc, dv_acc, dq))
    dk = lax.ppermute(dk_acc, axis_name, perm)
    dv = lax.ppermute(dv_acc, axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _zigzag_shard(q, k, v, axis_name, impl, interpret):
    o, _ = _zigzag_fwd_impl(q, k, v, axis_name, impl, interpret)
    return o


def _zigzag_shard_fwd(q, k, v, axis_name, impl, interpret):
    o, lse = _zigzag_fwd_impl(q, k, v, axis_name, impl, interpret)
    return o, (q, k, v, o, lse)


def _zigzag_shard_bwd(axis_name, impl, interpret, res, do):
    q, k, v, o, lse = res
    return _zigzag_bwd_impl(q, k, v, o, lse, do, axis_name, impl, interpret)


_zigzag_shard.defvjp(_zigzag_shard_fwd, _zigzag_shard_bwd)


def zigzag_ring_attention_shard(q, k, v, axis_name: str = "sp",
                                impl: str | None = None,
                                interpret: bool = False):
    """Per-shard zigzag body: local arrays must be in zigzag layout — the
    device's shard is [stripe i ; stripe 2P-1-i] (use zigzag_permute)."""
    if impl is None:
        impl = _default_impl()
    return _zigzag_shard(q, k, v, axis_name, impl, interpret)


def zigzag_ring_attention(q, k, v, mesh, axis_name: str = "sp",
                          impl: str | None = None, interpret: bool = False,
                          batch_axes=None):
    """Global-view balanced causal ring attention (always causal).

    Permutes the sequence into zigzag stripe order, runs the balanced ring
    under shard_map, and un-permutes the output — exact causal attention at
    ~half the uniform ring's attention FLOPs.

    ``batch_axes``: mesh axis (or tuple) the BATCH dim is sharded over —
    on a dp × sp mesh, passing "dp" keeps each dp group computing only its
    own batch slice instead of all-gathering and computing the global
    batch redundantly on every replica.
    """
    devices = mesh.shape[axis_name]
    spec = PartitionSpec(batch_axes, axis_name, None, None)
    fn = _shard_map(
        functools.partial(zigzag_ring_attention_shard, axis_name=axis_name,
                          impl=impl, interpret=interpret),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=not interpret,
    )
    qz = zigzag_permute(q, devices)
    kz = zigzag_permute(k, devices)
    vz = zigzag_permute(v, devices)
    return zigzag_unpermute(fn(qz, kz, vz), devices)


def ring_attention_shard(q, k, v, axis_name: str = "sp", causal: bool = True,
                         impl: str | None = None, interpret: bool = False):
    """Per-shard body: call inside ``shard_map`` with seq sharded on axis_name.

    q/k/v: local chunks (batch, chunk, heads, head_dim). Differentiable:
    the VJP re-runs the ring, circulating dk/dv with their blocks.
    """
    if impl is None:
        impl = _default_impl()
    return _ring_shard(q, k, v, axis_name, causal, impl, interpret)


def ring_attention(q, k, v, mesh, axis_name: str = "sp", causal: bool = True,
                   impl: str | None = None, interpret: bool = False,
                   batch_axes=None):
    """Global-view ring attention: q/k/v (batch, seq, heads, head_dim).

    Shards the sequence over ``axis_name`` with shard_map and runs the
    ring; ``batch_axes`` optionally shards the batch dim as well (see
    zigzag_ring_attention).
    """
    spec = PartitionSpec(batch_axes, axis_name, None, None)
    fn = _shard_map(
        functools.partial(ring_attention_shard, axis_name=axis_name,
                          causal=causal, impl=impl, interpret=interpret),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # pallas interpret mode can't track varying manual axes through its
        # HLO interpreter; the check stays on for the compiled TPU path.
        check_vma=not interpret,
    )
    return fn(q, k, v)
