"""All-to-all (Ulysses-style) context parallelism.

The second context-parallel mode next to the ring
(:mod:`tpu_task.ml.parallel.ring_attention`): instead of circulating k/v
blocks around a ring, two ``all_to_all`` collectives reshard the activations
from sequence-sharded to HEAD-sharded and back. In between, every device
holds the FULL sequence for its head group, so attention itself is the
plain fused kernel — the flash Pallas path on TPU — with exact causal
masking and no schedule bookkeeping.

Trade-offs vs the ring (why both exist):

- Ulysses moves each activation twice per attention call (a2a in, a2a out)
  regardless of sequence length; the ring moves k/v P-1 times but overlaps
  transfers with block compute. On ICI-rich slices the a2a is cheap and the
  kernel runs at full length (better MXU utilization than per-block calls).
- Ulysses caps the parallel degree at the head count (heads % sp == 0);
  the ring has no such cap — 32 devices on 8 heads needs the ring.
- Memory: Ulysses holds (b, s, h/P, d) per device — full sequence, fewer
  heads; the ring holds (b, s/P, h, d). Same totals, different shapes.

Reference: DeepSpeed-Ulysses (public technique; no reference-code analog —
the reference orchestrates machines, SURVEY.md §2.9).
"""

from __future__ import annotations

import functools

import jax
from jax import lax
from jax.sharding import PartitionSpec

from tpu_task.ml.parallel.mesh import shard_map as _shard_map
from tpu_task.ml.ops.attention import dot_product_attention


def _seq_to_heads(x, axis_name: str):
    """(b, s/P, h, d) local → (b, s, h/P, d) local: split heads, gather seq."""
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def _heads_to_seq(x, axis_name: str):
    """(b, s, h/P, d) local → (b, s/P, h, d) local: the inverse reshard."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention_shard(q, k, v, axis_name: str = "sp",
                            causal: bool = True):
    """Per-shard body: local arrays are (batch, seq/P, heads, head_dim);
    call inside ``shard_map`` with seq sharded on ``axis_name``.

    k/v may arrive at KV-head width (GQA): the all_to_all then moves
    kv_heads-width bytes and the expansion to query width happens HERE,
    after the reshard — head-group alignment makes this exact: q head j
    uses kv head j // group, and with heads = (kv/P)·group·P per-device
    contiguous q-head range [dev·h/P, (dev+1)·h/P) maps exactly onto kv
    range [dev·kv/P, (dev+1)·kv/P). Requires kv_heads % P == 0 (the caller
    widens before the shard otherwise).

    Differentiable with plain autodiff: ``all_to_all``'s transpose is the
    inverse all_to_all, the expansion's transpose is the query-group sum,
    and the inner attention is the fused custom-VJP op.
    """
    from tpu_task.ml.ops.attention import expand_kv_heads

    qh = _seq_to_heads(q, axis_name)
    kh = expand_kv_heads(_seq_to_heads(k, axis_name), qh.shape[2])
    vh = expand_kv_heads(_seq_to_heads(v, axis_name), qh.shape[2])
    out = dot_product_attention(qh, kh, vh, causal)
    return _heads_to_seq(out, axis_name)


def ulysses_attention(q, k, v, mesh, axis_name: str = "sp",
                      causal: bool = True, batch_axes=None):
    """Global-view all-to-all context-parallel attention.

    q/k/v: (batch, seq, heads, head_dim) with ``heads % sp == 0`` and
    ``seq % sp == 0``; k/v may be KV-head-narrow (GQA) — they cross the
    all_to_all narrow when ``kv_heads % sp == 0`` (group alignment, see
    :func:`ulysses_attention_shard`), else they widen before the shard.
    ``batch_axes`` as in
    :func:`~tpu_task.ml.parallel.ring_attention.zigzag_ring_attention`:
    mesh axis (or tuple) the batch dim is sharded over, so dp groups only
    compute their own slice.
    """
    from tpu_task.ml.ops.attention import expand_kv_heads

    devices = mesh.shape[axis_name]
    heads = q.shape[2]
    if heads % devices:
        raise ValueError(
            f"ulysses needs heads ({heads}) divisible by {axis_name} "
            f"({devices}); use the ring for higher parallel degrees")
    if q.shape[1] % devices:
        raise ValueError(f"sequence ({q.shape[1]}) not divisible by "
                         f"{axis_name} ({devices})")
    kv_heads = k.shape[2]
    if kv_heads != heads and kv_heads % devices:
        # Narrow heads can't split P ways: widen before the shard — the
        # collective saving is forfeited but the math stays exact.
        k = expand_kv_heads(k, heads)
        v = expand_kv_heads(v, heads)
    spec = PartitionSpec(batch_axes, axis_name, None, None)
    fn = _shard_map(
        functools.partial(ulysses_attention_shard, axis_name=axis_name,
                          causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
