"""Logical-axis sharding rules (t5x/maxtext style).

Model code annotates parameters with *logical* axis names; one rules table
maps those to mesh axes. Changing the parallelism layout means changing the
table, not the model.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec

# logical axis -> mesh axis (or None = replicate).
# fsdp shards the "long" parameter axis; tp shards heads/mlp.
DEFAULT_RULES: Dict[str, Optional[object]] = {
    # Activation batch spans every data axis present in the mesh; "ep"
    # counts as one (expert-parallel meshes shard tokens over ep so the
    # dense compute between MoE layers parallelizes too — only the expert
    # weights and the all_to_all dispatch treat ep specially).
    "batch": ("dp", "fsdp", "ep"),
    "seq": None,               # sequence replicated (ring attention uses "sp")
    "vocab": "tp",
    "embed": "fsdp",
    "heads": "tp",
    "head_dim": None,
    "kv": None,
    "mlp": "tp",
    "norm": None,
    "expert": "ep",
}


def logical_to_mesh_axes(
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Dict[str, Optional[object]]] = None,
    mesh=None,
) -> PartitionSpec:
    """Map a tuple of logical axis names to a PartitionSpec.

    Mesh axes not present in ``mesh`` (when given) are dropped to None so the
    same model code runs on meshes without e.g. an ``ep`` axis.
    """
    rules = DEFAULT_RULES if rules is None else rules
    mesh_axis_names = set(mesh.axis_names) if mesh is not None else None

    def resolve(name: Optional[str]):
        if name is None:
            return None
        target = rules.get(name)
        if target is None:
            return None
        if isinstance(target, tuple):
            if mesh_axis_names is not None:
                target = tuple(t for t in target if t in mesh_axis_names)
            return target if target else None
        if mesh_axis_names is not None and target not in mesh_axis_names:
            return None
        return target

    return PartitionSpec(*(resolve(a) for a in logical_axes))


def mesh_batch_axes(mesh) -> Tuple[str, ...]:
    """The mesh axes the logical "batch" dim shards over, normalized to a
    (possibly empty) tuple — the one resolution every train-step builder
    shares so token sharding, activation constraints, and shard_map specs
    cannot disagree."""
    resolved = logical_to_mesh_axes(("batch",), mesh=mesh)[0]
    if resolved is None:
        return ()
    if isinstance(resolved, tuple):
        return resolved
    return (resolved,)


def named_sharding(mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def shard_pytree(tree, pspec_tree, mesh):
    """Place every leaf of ``tree`` per the matching PartitionSpec leaf."""
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        tree,
        pspec_tree,
        is_leaf=lambda x: x is None,
    )


def pspecs_to_shardings(pspec_tree, mesh):
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        pspec_tree,
        is_leaf=lambda x: isinstance(x, PartitionSpec) or x is None,
    )
