"""The repo's ONE partition engine: logical-axis rules, regex-over-path
rules, and the compile seam every sharded program goes through.

Three layers, each feeding the next:

1. **Logical rules** (t5x/MaxText style): model code annotates parameters
   with *logical* axis names (``transformer.param_logical_axes``); the
   :data:`DEFAULT_RULES` table maps those to mesh axes. Changing the
   parallelism layout means changing the table, not the model.
2. **Rule resolution** (:func:`match_partition_rules`): turns a pytree of
   arrays into a pytree of ``PartitionSpec`` — logical-axis annotations
   where the tree carries them, regex-over-"/"-joined-path rules for trees
   that don't (the paged KV pools, ad-hoc state), scalars replicated, and
   a loud error naming any leaf nothing matched. Mesh axes absent from the
   target mesh drop to ``None`` everywhere, so one rules table serves
   every mesh shape.
3. **The compile seam** (:class:`PartitionPlan` + :func:`compile_step`):
   one function that turns (fn, plan) into the compiled program — plain
   ``jit`` when the plan has no mesh, ``jit`` with
   ``in_shardings``/``out_shardings`` derived from the plan's specs when it
   does, or ``shard_map``-then-``jit`` when the plan demands per-shard
   semantics (Titanax-style mode switch). Train and serve both compile
   through here, so they cannot drift on donation/sharding plumbing.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec

# logical axis -> mesh axis (or None = replicate).
# fsdp shards the "long" parameter axis; tp shards heads/mlp.
DEFAULT_RULES: Dict[str, Optional[object]] = {
    # Activation batch spans every data axis present in the mesh; "ep"
    # counts as one (expert-parallel meshes shard tokens over ep so the
    # dense compute between MoE layers parallelizes too — only the expert
    # weights and the all_to_all dispatch treat ep specially).
    "batch": ("dp", "fsdp", "ep"),
    "seq": None,               # sequence replicated (ring attention uses "sp")
    "vocab": "tp",
    "embed": "fsdp",
    "heads": "tp",
    "head_dim": None,
    "kv": None,
    "mlp": "tp",
    "norm": None,
    "expert": "ep",
}


def logical_to_mesh_axes(
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Dict[str, Optional[object]]] = None,
    mesh=None,
) -> PartitionSpec:
    """Map a tuple of logical axis names to a PartitionSpec.

    Mesh axes not present in ``mesh`` (when given) are dropped to None so the
    same model code runs on meshes without e.g. an ``ep`` axis.
    """
    rules = DEFAULT_RULES if rules is None else rules
    mesh_axis_names = set(mesh.axis_names) if mesh is not None else None

    def resolve(name: Optional[str]):
        if name is None:
            return None
        target = rules.get(name)
        if target is None:
            return None
        if isinstance(target, tuple):
            if mesh_axis_names is not None:
                target = tuple(t for t in target if t in mesh_axis_names)
            return target if target else None
        if mesh_axis_names is not None and target not in mesh_axis_names:
            return None
        return target

    return PartitionSpec(*(resolve(a) for a in logical_axes))


def logical_tree_pspecs(axes_tree, mesh=None, rules=None):
    """A whole pytree of logical-axis tuples → pytree of PartitionSpecs —
    the annotated-tree half of rule resolution (``match_partition_rules``
    is the unannotated half; both resolve through the same table)."""
    return jax.tree.map(
        lambda a: logical_to_mesh_axes(a, rules=rules, mesh=mesh),
        axes_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def mesh_axis_size(mesh, name: str) -> int:
    """Size of mesh axis ``name``, 1 when the mesh is None or lacks the
    axis — the one resolution every consumer of an OPTIONAL mesh axis
    shares (the serving engine reads its tp and ep widths through this,
    so a tp-only mesh, an ep-only mesh, and a tp×ep gang all resolve
    consistently)."""
    if mesh is None:
        return 1
    return int(dict(mesh.shape).get(name, 1))


def mesh_batch_axes(mesh) -> Tuple[str, ...]:
    """The mesh axes the logical "batch" dim shards over, normalized to a
    (possibly empty) tuple — the one resolution every train-step builder
    shares so token sharding, activation constraints, and shard_map specs
    cannot disagree."""
    resolved = logical_to_mesh_axes(("batch",), mesh=mesh)[0]
    if resolved is None:
        return ()
    if isinstance(resolved, tuple):
        return resolved
    return (resolved,)


# -- regex-over-path rule resolution ------------------------------------------

def tree_path_str(path) -> str:
    """A tree_util key path as a "/"-joined name (``layers/0/wq``) — the
    format regex partition rules match against."""
    parts: List[str] = []
    for key in path:
        if hasattr(key, "key"):          # DictKey
            parts.append(str(key.key))
        elif hasattr(key, "idx"):        # SequenceKey
            parts.append(str(key.idx))
        elif hasattr(key, "name"):       # GetAttrKey / NamedTuple field
            parts.append(str(key.name))
        else:
            parts.append(str(key))
    return "/".join(parts)


def _leaf_size(leaf) -> int:
    size = 1
    for dim in getattr(leaf, "shape", ()):
        size *= int(dim)
    return size


def match_partition_rules(rules, tree, mesh=None, logical_axes=None,
                          logical_rules=None):
    """Resolve a PartitionSpec for every array leaf of ``tree``.

    Per leaf (its tree path "/"-joined, e.g. ``layers/0/wq`` or ``0/k``),
    resolution order:

    1. scalar leaves (0-d or single-element — optimizer counts, schedule
       state) replicate: ``PartitionSpec()``;
    2. a **logical-axis annotation** — ``logical_axes`` is a matching
       pytree of logical-axis tuples (``transformer.param_logical_axes``
       style) — wins over any regex: annotations sit next to the parameter
       definition and are the model's source of truth;
    3. else the FIRST entry of ``rules`` whose regex ``re.search``-matches
       the path wins. ``rules`` is a sequence of ``(pattern, target)``
       where ``target`` is either a tuple of LOGICAL axis names (resolved
       through the same table as annotations) or a raw ``PartitionSpec``
       (mesh axes used verbatim);
    4. nothing matched → ``ValueError`` naming the offending path, so a
       new parameter cannot silently replicate.

    Mesh axes absent from ``mesh`` drop to None in every case (the
    missing-axis contract of :func:`logical_to_mesh_axes`).
    """
    rules = tuple(rules or ())
    annotations: Dict[str, Any] = {}
    if logical_axes is not None:
        flat, _ = jax.tree_util.tree_flatten_with_path(
            logical_axes,
            is_leaf=lambda x: isinstance(x, tuple) or x is None)
        for path, axes in flat:
            annotations[tree_path_str(path)] = axes

    def resolve(path, leaf):
        name = tree_path_str(path)
        if getattr(leaf, "ndim", None) == 0 or _leaf_size(leaf) == 1:
            return PartitionSpec()
        axes = annotations.get(name)
        if axes is not None:
            return logical_to_mesh_axes(axes, rules=logical_rules, mesh=mesh)
        for pattern, target in rules:
            if re.search(pattern, name):
                if isinstance(target, PartitionSpec):
                    return filter_spec(target, mesh)
                return logical_to_mesh_axes(target, rules=logical_rules,
                                            mesh=mesh)
        raise ValueError(
            f"no partition rule matched param {name!r} "
            f"(shape {tuple(getattr(leaf, 'shape', ()))}); add a regex rule "
            f"or a logical-axis annotation for it")

    return jax.tree_util.tree_map_with_path(resolve, tree)


def filter_spec(spec: PartitionSpec, mesh) -> PartitionSpec:
    """Drop mesh axes absent from ``mesh`` out of a raw PartitionSpec —
    the same missing-axis contract logical resolution has."""
    if mesh is None:
        return spec
    names = set(mesh.axis_names)

    def fix(entry):
        if entry is None:
            return None
        if isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in names)
            return kept if kept else None
        return entry if entry in names else None

    return PartitionSpec(*(fix(e) for e in spec))


# -- spec-tree plumbing (the one home for the is_leaf=PartitionSpec idiom) ----

def _is_spec_leaf(x) -> bool:
    return isinstance(x, PartitionSpec) or x is None


def pspecs_to_shardings(pspec_tree, mesh):
    """PartitionSpec tree → NamedSharding tree (jit in/out_shardings)."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        pspec_tree,
        is_leaf=_is_spec_leaf,
    )


def device_put_tree(tree, pspec_tree, mesh):
    """Place every leaf of ``tree`` per the matching PartitionSpec leaf —
    the one device_put used by train state AND the serving pools."""
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        tree,
        pspec_tree,
        is_leaf=_is_spec_leaf,
    )


def spec_leaves_with_paths(pspec_tree) -> List[Tuple[Tuple[str, ...], PartitionSpec]]:
    """Flatten a spec tree to [(path-key strings, spec)] — the shared
    flatten the optimizer-state suffix matcher (train._opt_specs_like)
    and any other spec-tree consumer use."""
    flat, _ = jax.tree_util.tree_flatten_with_path(
        pspec_tree, is_leaf=lambda x: isinstance(x, PartitionSpec))
    return [(tuple(str(k) for k in path), spec) for path, spec in flat]


def shard_pytree(tree, pspec_tree, mesh):
    """Legacy alias of :func:`device_put_tree` (kept for importers)."""
    return device_put_tree(tree, pspec_tree, mesh)


def named_sharding(mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


# -- the compile seam ---------------------------------------------------------

@dataclass(frozen=True)
class PartitionPlan:
    """Everything :func:`compile_step` needs to compile one program.

    ``in_specs``: a tuple with one PartitionSpec-pytree per positional
    argument (a bare ``PartitionSpec`` is a valid pytree: it pins the whole
    argument); ``out_specs``: same for the result. ``donate``: argnums
    whose buffers the program may consume in place (the KV pools, the train
    state). ``mode``:

    - ``"jit"`` (default): one SPMD program — ``jax.jit`` with
      ``in_shardings``/``out_shardings`` derived from the specs; XLA
      inserts the collectives the shardings imply.
    - ``"shard_map"``: per-shard semantics — the fn body runs once per
      shard with the specs as ``shard_map`` in/out specs (collectives are
      explicit in the body), then the whole map is jitted.

    ``mesh=None`` means single-device: specs are ignored and the fn is
    plainly jitted (with donation), so every call site can build a plan
    unconditionally and let the seam pick.
    """

    mesh: Any = None
    in_specs: Tuple[Any, ...] = ()
    out_specs: Any = None
    donate: Tuple[int, ...] = ()
    mode: str = "jit"
    check_vma: Optional[bool] = field(default=None)

    def __post_init__(self):
        if self.mode not in ("jit", "shard_map"):
            raise ValueError(
                f"unknown PartitionPlan mode {self.mode!r} "
                "(use 'jit' or 'shard_map')")


def compile_step(fn, plan: Optional[PartitionPlan] = None):
    """The one compile seam: (fn, plan) → compiled program.

    See :class:`PartitionPlan` for the mode semantics. Train-step builders
    and the serving engine both compile through here — donation, sharding
    derivation, and the jit/shard_map switch live in exactly one place.
    """
    if plan is None:
        return jax.jit(fn)
    if plan.mesh is None:
        return jax.jit(fn, donate_argnums=plan.donate)
    if plan.mode == "jit":
        return jax.jit(
            fn,
            in_shardings=tuple(
                pspecs_to_shardings(spec, plan.mesh) for spec in plan.in_specs),
            out_shardings=pspecs_to_shardings(plan.out_specs, plan.mesh),
            donate_argnums=plan.donate,
        )
    from tpu_task.ml.parallel.mesh import shard_map

    mapped = shard_map(fn, plan.mesh, in_specs=plan.in_specs,
                       out_specs=plan.out_specs, check_vma=plan.check_vma)
    return jax.jit(mapped, donate_argnums=plan.donate)
