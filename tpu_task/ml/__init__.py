"""TPU-native compute stack: the task library user scripts import.

The reference orchestrates machines and leaves all compute to the user script
(SURVEY.md §2.9: no collective ops, no tensor parallelism anywhere in
/root/reference). For a TPU-native framework the compute stack is
first-class: this package provides the mesh/sharding utilities, a flagship
transformer LM with dp/fsdp/tp shardings, ring attention for sequence
parallelism, pallas TPU kernels, and a checkpoint-to-workdir helper that
makes the orchestrator's continuous data sync (machine-script.sh.tpl:118-124
semantics) meaningful for training jobs.
"""

from tpu_task.ml.checkpoint import (
    AsyncCheckpointer,
    AsyncCheckpointError,
    latest_step,
    restore_checkpoint,
    restore_checkpoint_sharded,
    save_checkpoint,
    save_checkpoint_sharded,
)
from tpu_task.ml.parallel.mesh import (
    balanced_mesh_shape,
    distributed_init_from_env,
    make_mesh,
)
from tpu_task.ml import profiling

__all__ = [
    "AsyncCheckpointer",
    "AsyncCheckpointError",
    "balanced_mesh_shape",
    "profiling",
    "distributed_init_from_env",
    "latest_step",
    "make_mesh",
    "restore_checkpoint",
    "restore_checkpoint_sharded",
    "save_checkpoint",
    "save_checkpoint_sharded",
]
