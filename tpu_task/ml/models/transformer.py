"""Flagship decoder-only transformer LM, pure-functional JAX.

TPU-first design choices: bfloat16 activations with float32 params and
softmax; rotary positions computed inside the traced function (static
shapes); attention through the fused op in ``tpu_task.ml.ops.attention``;
every parameter annotated with *logical* axes so one rules table
(``tpu_task.ml.parallel.sharding``) lays it out over a dp/fsdp/tp mesh.

The reference has no model code at all (SURVEY.md §2.9) — this is the task
library its user scripts would have to bring themselves.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from tpu_task.ml.ops.attention import dot_product_attention
from tpu_task.ml.parallel.sharding import logical_tree_pspecs

Params = Dict[str, Any]


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    d_head: int = 64
    d_ff: int = 1408
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    # Grouped-query attention: k/v projections carry this many heads, each
    # shared by n_heads/n_kv_heads query heads (None = MHA). Shrinks the
    # KV cache — decoding's real memory bound — by the group factor.
    n_kv_heads: Any = None
    # Mixture-of-experts: every ``moe_every``-th layer (layers moe_every-1,
    # 2·moe_every-1, ...) replaces its dense FFN with an ``n_experts``-way
    # MoE FFN (tpu_task.ml.models.moe), expert-sharded over an ``ep`` mesh
    # axis when trained through make_moe_train_step. 0 = all-dense.
    moe_every: int = 0
    n_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    # Weight of the router load-balancing loss added to the LM loss.
    moe_aux_weight: float = 0.01

    @property
    def d_attn(self) -> int:
        return self.n_heads * self.d_head

    def is_moe_layer(self, index: int) -> bool:
        if self.moe_every <= 0:
            return False
        if self.n_experts < 2:
            raise ValueError(f"moe_every={self.moe_every} needs n_experts "
                             f">= 2, got {self.n_experts}")
        return (index + 1) % self.moe_every == 0

    @property
    def moe_cfg(self):
        from tpu_task.ml.models.moe import MoEConfig

        return MoEConfig(
            d_model=self.d_model, d_ff=self.d_ff, n_experts=self.n_experts,
            capacity_factor=self.moe_capacity_factor, top_k=self.moe_top_k)

    @property
    def kv_heads(self) -> int:
        kv = self.n_heads if self.n_kv_heads is None else self.n_kv_heads
        if kv < 1:
            raise ValueError(f"n_kv_heads must be >= 1, got {kv}")
        if self.n_heads % kv:
            raise ValueError(f"n_heads {self.n_heads} not divisible by "
                             f"n_kv_heads {kv}")
        return kv

    @property
    def d_kv(self) -> int:
        return self.kv_heads * self.d_head


# -- init --------------------------------------------------------------------

def _dense(key, shape, scale):
    return jax.random.normal(key, shape, dtype=jnp.float32) * scale


def init(rng, cfg: TransformerConfig) -> Params:
    keys = iter(jax.random.split(rng, 2 + 7 * cfg.n_layers))
    scale = cfg.d_model ** -0.5
    params: Params = {
        "embed": _dense(next(keys), (cfg.vocab_size, cfg.d_model), 1.0),
        "unembed": _dense(next(keys), (cfg.d_model, cfg.vocab_size), scale),
        "final_norm": jnp.ones((cfg.d_model,), jnp.float32),
        "layers": [],
    }
    for i in range(cfg.n_layers):
        layer = {
            "attn_norm": jnp.ones((cfg.d_model,), jnp.float32),
            "wq": _dense(next(keys), (cfg.d_model, cfg.d_attn), scale),
            "wk": _dense(next(keys), (cfg.d_model, cfg.d_kv), scale),
            "wv": _dense(next(keys), (cfg.d_model, cfg.d_kv), scale),
            "wo": _dense(next(keys), (cfg.d_attn, cfg.d_model), scale),
            "mlp_norm": jnp.ones((cfg.d_model,), jnp.float32),
        }
        if cfg.is_moe_layer(i):
            # Same 3-key budget as the dense FFN, so dense layers init
            # bit-identically whether or not other layers are MoE.
            layer["router"] = _dense(
                next(keys), (cfg.d_model, cfg.n_experts), scale)
            layer["w_in"] = _dense(
                next(keys), (cfg.n_experts, cfg.d_model, cfg.d_ff), scale)
            layer["w_out"] = _dense(
                next(keys), (cfg.n_experts, cfg.d_ff, cfg.d_model),
                cfg.d_ff ** -0.5)
        else:
            layer["w_gate"] = _dense(next(keys), (cfg.d_model, cfg.d_ff), scale)
            layer["w_up"] = _dense(next(keys), (cfg.d_model, cfg.d_ff), scale)
            layer["w_down"] = _dense(
                next(keys), (cfg.d_ff, cfg.d_model), cfg.d_ff ** -0.5)
        params["layers"].append(layer)
    return params


def param_logical_axes(cfg: TransformerConfig) -> Params:
    attn = {
        "attn_norm": ("norm",),
        "wq": ("embed", "heads"),
        "wk": ("embed", "heads"),
        "wv": ("embed", "heads"),
        "wo": ("heads", "embed"),
        "mlp_norm": ("norm",),
    }
    dense_ffn = {
        "w_gate": ("embed", "mlp"),
        "w_up": ("embed", "mlp"),
        "w_down": ("mlp", "embed"),
    }
    moe_ffn = {
        "router": ("embed", None),
        "w_in": ("expert", "embed", "mlp"),
        "w_out": ("expert", "mlp", "embed"),
    }
    return {
        "embed": ("vocab", "embed"),
        "unembed": ("embed", "vocab"),
        "final_norm": ("norm",),
        "layers": [
            {**attn, **(moe_ffn if cfg.is_moe_layer(i) else dense_ffn)}
            for i in range(cfg.n_layers)
        ],
    }


def param_pspecs(cfg: TransformerConfig, mesh=None, rules=None) -> Params:
    """PartitionSpecs for every parameter, resolved from the logical-axis
    annotations through the shared partition registry — train-step state
    sharding and the serving engine's weight placement both read THIS."""
    return logical_tree_pspecs(param_logical_axes(cfg), mesh=mesh,
                               rules=rules)


# -- forward -----------------------------------------------------------------

@jax.custom_vjp
def embed_lookup(table, tokens):
    """Embedding gather with a matmul backward.

    The forward is a cheap gather; the backward computes the table gradient
    as a one-hot einsum instead of a scatter-add — a contraction the SPMD
    partitioner reshards efficiently when the table is (vocab=tp, embed=fsdp)
    sharded and the cotangent is batch-sharded (scatter forces an
    involuntary full rematerialization there).
    """
    return table[tokens]


def _embed_fwd(table, tokens):
    # Keep the table in residuals only for its static shape/dtype; it is a
    # live parameter either way, so this costs no extra HBM.
    return table[tokens], (tokens, table)


# GLOBAL one-hot bytes above which the table gradient accumulates over token
# chunks (multi-GB at long sequences if XLA declines to fuse it). The count
# is computed pre-SPMD, so it overestimates per-device bytes by the dp×tp
# shard factor — the default stays high so the single SPMD-friendly einsum
# path is kept whenever memory plausibly allows; tune per deployment via
# TPU_TASK_EMBED_ONEHOT_LIMIT_MB.
_EMBED_ONEHOT_BYTES_LIMIT = int(os.environ.get(
    "TPU_TASK_EMBED_ONEHOT_LIMIT_MB", "2048")) * 1024 * 1024


def _embed_bwd(res, g):
    tokens, table = res
    vocab = table.shape[0]
    flat_tokens = tokens.reshape(-1)
    flat_g = g.reshape(-1, g.shape[-1])
    n_tokens = flat_tokens.shape[0]

    def onehot_grad(toks, gs):
        onehot = jax.nn.one_hot(toks, vocab, dtype=gs.dtype)
        # The scatter-add this replaces was exact, so the matmul must not
        # lose anything the scatter kept. With a bf16 cotangent DEFAULT
        # precision already IS exact: one-hot entries are {0, 1}, so every
        # product is the cotangent value itself, and accumulation runs in
        # f32 via preferred_element_type — while HIGHEST would force the
        # ~6x-slower f32 multi-pass path on a T×V×d-sized einsum (the
        # single biggest avoidable cost of the long-context step). An f32
        # cotangent (CPU tests, f32 configs) keeps HIGHEST: DEFAULT on f32
        # inputs may use split-bf16 passes, which would truncate.
        precision = (jax.lax.Precision.HIGHEST
                     if gs.dtype == jnp.float32
                     else jax.lax.Precision.DEFAULT)
        return jnp.einsum(
            "tv,td->vd", onehot, gs,
            precision=precision,
            preferred_element_type=jnp.float32,
        )

    onehot_bytes = n_tokens * vocab * jnp.dtype(g.dtype).itemsize
    if onehot_bytes <= _EMBED_ONEHOT_BYTES_LIMIT:
        d_table = onehot_grad(flat_tokens, flat_g)
    else:
        # Chunked accumulation: bounds the materialized one-hot to
        # chunk × vocab while keeping the SPMD-friendly contraction form
        # (a scatter-add would force the sharded table to rematerialize).
        chunk = max(256, _EMBED_ONEHOT_BYTES_LIMIT //
                    (vocab * jnp.dtype(g.dtype).itemsize))
        pad = (-n_tokens) % chunk
        toks = jnp.pad(flat_tokens, (0, pad), constant_values=0)
        gs = jnp.pad(flat_g, ((0, pad), (0, 0)))  # zero cotangent: no-op rows
        toks = toks.reshape(-1, chunk)
        gs = gs.reshape(-1, chunk, gs.shape[-1])

        def body(acc, args):
            return acc + onehot_grad(*args), None

        d_table, _ = jax.lax.scan(
            body, jnp.zeros((vocab, flat_g.shape[-1]), jnp.float32),
            (toks, gs))
    return d_table.astype(table.dtype), None


embed_lookup.defvjp(_embed_fwd, _embed_bwd)


def _rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale.astype(x.dtype)


def _rope(x, theta: float, positions=None):
    """Rotary embedding over (batch, seq, heads, head_dim).

    ``positions`` overrides the default 0..seq-1 — KV-cache decoding applies
    rope at absolute offsets through this SAME function, so the train and
    decode paths cannot drift apart. Shape (seq,) rotates every batch row at
    the same offsets; (batch, seq) rotates per row — continuous-batching
    decode steps one token per slot with every slot at its own depth."""
    _, seq, _, d = x.shape
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    if positions is None:
        positions = jnp.arange(seq)
    angles = positions.astype(jnp.float32)[..., None] * freqs
    if angles.ndim == 2:                     # (seq, half): shared offsets
        cos = jnp.cos(angles)[None, :, None, :]
        sin = jnp.sin(angles)[None, :, None, :]
    else:                                    # (batch, seq, half): per-row
        cos = jnp.cos(angles)[:, :, None, :]
        sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def expand_kv(kv, n_heads: int):
    """(b, s, kv_heads, d) → (b, s, n_heads, d); the shared GQA expansion
    rule — see :func:`tpu_task.ml.ops.attention.expand_kv_heads`."""
    from tpu_task.ml.ops.attention import expand_kv_heads

    return expand_kv_heads(kv, n_heads)


def default_moe_fn(cfg: TransformerConfig):
    """Dense-dispatch MoE FFN (single-device exact reference): the layout
    make_moe_train_step's expert-parallel all_to_all path is pinned against."""
    from tpu_task.ml.models import moe

    mcfg = cfg.moe_cfg

    def fn(layer, h):
        return moe.apply_dense(layer, mcfg, h)

    return fn


def _block(x, layer, cfg: TransformerConfig, attn_fn, positions=None,
           moe_fn=None):
    """One transformer block → (x, aux_loss); ``positions`` feeds rope
    absolute offsets — the KV-cache decode path runs THIS function (with
    its own attn_fn closing over the cache), so train and decode share
    every projection, norm, and residual and cannot drift apart.

    ``aux_loss`` is the router load-balancing loss for MoE layers (an f32
    zero for dense layers); ``moe_fn(layer, h) -> (ffn_out, aux)`` lets the
    train step swap the dense dispatch for the ep-sharded all_to_all one."""
    b, s, _ = x.shape
    h = _rmsnorm(x, layer["attn_norm"])
    q = (h @ layer["wq"].astype(cfg.dtype)).reshape(b, s, cfg.n_heads, cfg.d_head)
    k = (h @ layer["wk"].astype(cfg.dtype)).reshape(b, s, cfg.kv_heads, cfg.d_head)
    v = (h @ layer["wv"].astype(cfg.dtype)).reshape(b, s, cfg.kv_heads, cfg.d_head)
    q = _rope(q, cfg.rope_theta, positions)
    k = _rope(k, cfg.rope_theta, positions)
    # attn_fn receives k/v at KV-head width; grouped consumers (the KV
    # cache) keep the narrow layout, everything else expands.
    attn = attn_fn(q, k, v)
    x = x + attn.reshape(b, s, cfg.d_attn) @ layer["wo"].astype(cfg.dtype)

    h = _rmsnorm(x, layer["mlp_norm"])
    if "router" in layer:
        if moe_fn is None:
            moe_fn = default_moe_fn(cfg)
        out, aux = moe_fn(layer, h)
        return x + out.astype(x.dtype), aux.astype(jnp.float32)
    gate = jax.nn.silu(h @ layer["w_gate"].astype(cfg.dtype))
    up = h @ layer["w_up"].astype(cfg.dtype)
    x = x + (gate * up) @ layer["w_down"].astype(cfg.dtype)
    return x, jnp.zeros((), jnp.float32)


def apply(params: Params, cfg: TransformerConfig, tokens, attn_fn=None):
    """tokens: (batch, seq) int32 → logits (batch, seq, vocab) float32."""
    x = apply_features(params, cfg, tokens, attn_fn=attn_fn)
    return (x @ params["unembed"].astype(cfg.dtype)).astype(jnp.float32)


def apply_features(params: Params, cfg: TransformerConfig, tokens,
                   attn_fn=None, activation_spec=None, moe_fn=None):
    """tokens (batch, seq) → final-layer features; see
    :func:`apply_features_with_aux` (this drops the MoE aux loss)."""
    return apply_features_with_aux(
        params, cfg, tokens, attn_fn=attn_fn,
        activation_spec=activation_spec, moe_fn=moe_fn)[0]


def apply_features_with_aux(params: Params, cfg: TransformerConfig, tokens,
                            attn_fn=None, activation_spec=None, moe_fn=None):
    """tokens (batch, seq) → (final-layer features (batch, seq, d_model),
    mean MoE aux loss). Features are BEFORE the unembed projection (the
    fused loss consumes them); the aux mean runs over MoE layers only
    (an f32 zero for all-dense configs).

    ``activation_spec``: optional sharding (e.g. a NamedSharding putting
    seq on the ``sp`` axis) pinned onto the activations right after the
    embedding — sequence-parallel training needs the residual stream
    sharded over seq, which no parameter spec implies (params carry no seq
    axis), so without the constraint XLA may replicate the activations and
    forfeit the memory win."""
    if attn_fn is None:
        attn_fn = lambda q, k, v: dot_product_attention(
            q, expand_kv(k, cfg.n_heads), expand_kv(v, cfg.n_heads), True)
    x = embed_lookup(params["embed"].astype(cfg.dtype), tokens)
    if activation_spec is not None:
        x = jax.lax.with_sharding_constraint(x, activation_spec)
    aux_sum = jnp.zeros((), jnp.float32)
    n_moe = 0
    for i, layer in enumerate(params["layers"]):
        x, aux = _block(x, layer, cfg, attn_fn, moe_fn=moe_fn)
        if "router" in layer:
            aux_sum = aux_sum + aux
            n_moe += 1
    return _rmsnorm(x, params["final_norm"]), aux_sum / max(1, n_moe)


# Vocab-block floor for the fused cross-entropy: each scan step holds one
# (tokens, block) logit tile instead of the full (tokens, vocab) matrix.
XENT_VOCAB_BLOCK = 4096

# Auto-block budget: the largest f32 logit tile one scan step may hold.
# Fewer, larger scan steps are faster (whole-vocab single step beats 4096
# blocks by ~25% on the v5e bench shape: 17.3 vs 22.4 ms fwd+bwd), so the
# block grows until the tile hits this budget and shrinks for long-context
# token counts where the memory bound is the whole point.
XENT_TILE_BYTES = 1 << 30


def _auto_xent_block(n_tokens: int, vocab: int) -> int:
    """Largest 4096-multiple block whose (n_tokens, block) f32 tile fits
    the budget, clamped to [XENT_VOCAB_BLOCK, padded vocab]."""
    budget = int(os.environ.get("TPU_TASK_XENT_TILE_BYTES", XENT_TILE_BYTES))
    block = (budget // (4 * max(1, n_tokens))) // 4096 * 4096
    vocab_ceil = -(-vocab // 4096) * 4096
    return max(XENT_VOCAB_BLOCK, min(block, vocab_ceil))


def _match_vma(init, *refs):
    """Mark ``init`` (a pytree of fresh zeros) as device-varying over every
    mesh axis the reference arrays vary on — scan carries built from
    ``jnp.zeros`` inside ``shard_map`` (the pipeline head runs the fused
    loss there) must match the body outputs' varying axes."""
    from tpu_task.ml.parallel.mesh import pvary, value_vma

    vma = frozenset()
    for r in refs:
        vma = vma | value_vma(r)
    if not vma:
        return init

    return jax.tree.map(lambda x: pvary(x, tuple(vma)), init)


def _pad_vocab(unembed, block):
    """Pad the vocab axis up to a block multiple (pad columns masked to
    -inf in the scan, so they never contribute)."""
    vocab = unembed.shape[1]
    pad = (-vocab) % block
    if pad:
        unembed = jnp.pad(unembed, ((0, 0), (0, pad)))
    return unembed, vocab


def _masked_logits(features, u_block, start, block, vocab):
    """One (T, block) logit tile with pad columns at -inf, f32."""
    z = jnp.dot(features, u_block,
                preferred_element_type=jnp.float32)
    col_valid = (start + jax.lax.iota(jnp.int32, block)) < vocab
    return jnp.where(col_valid[None, :], z, -jnp.inf)


def fused_xent(features, unembed, targets, block: Optional[int] = None,
               token_shards: int = 1):
    """Mean next-token cross-entropy WITHOUT materializing (tokens, vocab)
    logits beyond one tile: the unembed matmul, log-sum-exp, and target
    gather stream over vocab blocks (online logsumexp), and the backward
    recomputes each block's softmax tile — HBM traffic drops from O(T·V)
    f32 tensors to O(T·block) tiles. Any vocab size (padded to a block
    multiple with masked columns). features: (T, d); unembed: (d, V);
    targets: (T,). ``block=None`` auto-sizes to the XENT_TILE_BYTES
    budget — whole-vocab single step at short context (fastest), bounded
    tiles at long context (the memory win).

    ``token_shards``: how many ways the token dim is sharded under SPMD
    (dp×fsdp×sp shard product) — trace-time shapes are GLOBAL, so without
    it the auto block sizes against shard-factor more tokens than any
    device holds and over-shrinks the tile (extra scan steps, results
    unchanged). The train-step builders thread it from the mesh."""
    if block is None:
        block = _auto_xent_block(
            max(1, features.shape[0] // max(1, token_shards)),
            unembed.shape[1])
    return _fused_xent(features, unembed, targets, block)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_xent(features, unembed, targets, block: int):
    lse, target_logit = _xent_forward(features, unembed, targets, block)
    return jnp.mean(lse - target_logit)


def _xent_forward(features, unembed, targets, block):
    n_tokens = features.shape[0]
    unembed, vocab = _pad_vocab(unembed, block)
    blocks = jnp.moveaxis(unembed.reshape(
        unembed.shape[0], unembed.shape[1] // block, block), 1, 0)

    def body(carry, u_block):
        m, l, t_logit, start = carry
        z = _masked_logits(features, u_block, start, block, vocab)
        m_new = jnp.maximum(m, z.max(axis=-1))
        l = l * jnp.exp(m - m_new) + jnp.exp(
            z - m_new[:, None]).sum(axis=-1)
        in_block = (targets >= start) & (targets < start + block)
        local = jnp.clip(targets - start, 0, block - 1)
        t_logit = jnp.where(
            in_block, jnp.take_along_axis(z, local[:, None], axis=1)[:, 0],
            t_logit)
        return (m_new, l, t_logit, start + block), None

    init = _match_vma(
        (jnp.full((n_tokens,), -jnp.inf, jnp.float32),
         jnp.zeros((n_tokens,), jnp.float32),
         jnp.zeros((n_tokens,), jnp.float32),
         jnp.int32(0)),
        features, unembed, targets)
    (m, l, target_logit, _), _ = jax.lax.scan(body, init, blocks)
    lse = m + jnp.log(l)
    return lse, target_logit


def _fused_xent_fwd(features, unembed, targets, block):
    lse, target_logit = _xent_forward(features, unembed, targets, block)
    loss = jnp.mean(lse - target_logit)
    return loss, (features, unembed, targets, lse)


def _fused_xent_bwd(block, res, g):
    features, unembed, targets, lse = res
    n_tokens = features.shape[0]
    padded, vocab = _pad_vocab(unembed, block)
    blocks = jnp.moveaxis(padded.reshape(
        padded.shape[0], padded.shape[1] // block, block), 1, 0)
    scale = g / n_tokens

    # Matmul operand dtype for the two (T, block) x (block|T, d) gradient
    # contractions: on the bf16 train path the OPERANDS go bf16 (one MXU
    # pass instead of the ~4x-slower f32 path — at seq 8k x vocab 32k these
    # two matmuls alone are ~1.1e12 FLOPs/step) while ACCUMULATION stays
    # f32 via preferred_element_type and the f32 carry below. ds entries
    # are softmax probabilities minus a one-hot — bf16's 2^-8 relative
    # rounding on them is far below the gradient noise the monolithic bf16
    # forward already carries. f32 features (CPU tests, f32 configs) keep
    # full f32 operands, so the hermetic exactness pins are untouched.
    operand_dtype = features.dtype

    def body(carry, u_block):
        d_features, start = carry
        z = _masked_logits(features, u_block, start, block, vocab)
        p = jnp.exp(z - lse[:, None])  # softmax tile (pad cols exp(-inf)=0)
        in_block = (targets >= start) & (targets < start + block)
        local = jnp.clip(targets - start, 0, block - 1)
        onehot = (jax.nn.one_hot(local, block, dtype=jnp.float32)
                  * in_block[:, None])
        ds = ((p - onehot) * scale).astype(operand_dtype)  # (T, block)
        # f32 accumulation throughout: a bf16 carry would drift over the
        # vocab/block partial sums (the monolithic path reduces in f32).
        d_features = d_features + jnp.dot(
            ds, u_block.T.astype(operand_dtype),
            preferred_element_type=jnp.float32)
        d_u_block = jnp.dot(features.T.astype(operand_dtype), ds,
                            preferred_element_type=jnp.float32)
        return (d_features, start + block), d_u_block

    init = _match_vma(
        (jnp.zeros(features.shape, jnp.float32), jnp.int32(0)),
        features, unembed, targets, g)
    (d_features, _), d_u_blocks = jax.lax.scan(body, init, blocks)
    d_unembed = jnp.moveaxis(d_u_blocks, 0, 1).reshape(
        padded.shape)[:, :unembed.shape[1]]
    return (d_features.astype(features.dtype),
            d_unembed.astype(unembed.dtype), None)


_fused_xent.defvjp(_fused_xent_fwd, _fused_xent_bwd)


def loss_fn(params: Params, cfg: TransformerConfig, tokens, attn_fn=None,
            fused: bool = True, activation_spec=None, moe_fn=None,
            token_shards: int = 1):
    """Next-token cross-entropy (+ weighted MoE router aux loss when the
    config has MoE layers); tokens (batch, seq).

    ``fused=True`` (default) streams the unembed+softmax over auto-sized
    vocab blocks: at short context the block covers the whole vocab — a
    single scan step, measured FASTER than the monolithic path on the
    flagship bench shape (83.8 vs 85.7 ms fwd+bwd, the bwd recomputes its
    tile instead of saving f32 logits) — and at long context the block
    shrinks to bound logits memory (seq 32k × vocab 32k would be 8 GB f32
    unfused). ``fused=False`` keeps the monolithic reference path the
    hermetic tests compare against."""
    if activation_spec is not None and not fused:
        # The monolithic path would silently drop the constraint, replicate
        # the residual stream over sp, and OOM at exactly the lengths
        # sequence parallelism exists to serve.
        raise ValueError("activation_spec requires the fused loss path")
    targets = tokens[:, 1:]
    features, aux = apply_features_with_aux(
        params, cfg, tokens[:, :-1], attn_fn=attn_fn,
        activation_spec=activation_spec, moe_fn=moe_fn)
    b, s, d = features.shape
    if fused:
        xent = fused_xent(features.reshape(b * s, d),
                          params["unembed"].astype(cfg.dtype),
                          targets.reshape(-1), token_shards=token_shards)
    else:
        logits = (features @ params["unembed"].astype(cfg.dtype)).astype(
            jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        xent = -jnp.take_along_axis(
            logp, targets[..., None], axis=-1)[..., 0].mean()
    return xent + cfg.moe_aux_weight * aux
