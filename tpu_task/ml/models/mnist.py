"""MNIST reference models — the workload of the baseline configs.

BASELINE.md configs 1-2 run "2-epoch MNIST" through the task lifecycle; this
module is the model those task scripts import. Includes a synthetic-data
generator so benchmarks run with zero network egress.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


def init_mlp(rng, d_in: int = 784, d_hidden: int = 256, n_classes: int = 10) -> Dict[str, Any]:
    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (d_in, d_hidden), jnp.float32) * (d_in ** -0.5),
        "b1": jnp.zeros((d_hidden,), jnp.float32),
        "w2": jax.random.normal(k2, (d_hidden, n_classes), jnp.float32) * (d_hidden ** -0.5),
        "b2": jnp.zeros((n_classes,), jnp.float32),
    }


def apply_mlp(params, x):
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def loss_fn(params, x, y):
    logits = apply_mlp(params, x)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]
    return nll.mean()


def accuracy(params, x, y):
    return (apply_mlp(params, x).argmax(-1) == y).mean()


def synthetic_mnist(rng, n: int = 4096) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Linearly-separable-ish synthetic digits: class-dependent mean + noise."""
    k1, k2 = jax.random.split(rng)
    y = jax.random.randint(k1, (n,), 0, 10)
    protos = jax.random.normal(jax.random.PRNGKey(0), (10, 784)) * 2.0
    x = protos[y] + jax.random.normal(k2, (n, 784))
    return x, y
