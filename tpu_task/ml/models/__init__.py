"""Model zoo: flagship transformer LM + MNIST reference models."""

from tpu_task.ml.models.transformer import (
    TransformerConfig,
    apply as transformer_apply,
    init as transformer_init,
    loss_fn as transformer_loss,
    param_pspecs,
)

__all__ = [
    "TransformerConfig",
    "transformer_apply",
    "transformer_init",
    "transformer_loss",
    "param_pspecs",
]
