"""KV-cache autoregressive decoding for the flagship transformer.

Training recomputes attention over the full sequence every step; decoding
must not — each new token attends cached k/v, so the per-token cost is
O(seq) instead of O(seq²). TPU-first shape discipline: the cache is a
fixed-capacity ``max_len`` buffer of static shape (slot j = position j; NOT
a ring — writes past capacity clamp, see :func:`forward_with_cache`), the
decode loop is a ``lax.scan`` (one compilation, no per-token retrace), and
masking is positional arithmetic — no dynamic shapes anywhere, so XLA
compiles one program for the whole generation.

The reference ships no model/inference code at all (SURVEY.md §2.9);
this completes the task library's train → eval → generate triangle.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp

from tpu_task.ml.ops.attention import NEG_INF, gqa_cached_attention
from tpu_task.ml.models.transformer import (
    Params,
    TransformerConfig,
    _block,
    _rmsnorm,
    embed_lookup,
)


def init_cache(cfg: TransformerConfig, batch: int, max_len: int) -> List[dict]:
    """Per-layer k/v caches of static shape (batch, max_len, KV heads,
    d_head) — under grouped-query attention the cache shrinks by the group
    factor, which is the point of GQA at decode time."""
    shape = (batch, max_len, cfg.kv_heads, cfg.d_head)
    return [{"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}
            for _ in range(cfg.n_layers)]


def _cached_attention(q, k_cache, v_cache, q_positions):
    """Dense-cache entry to the shared grouped-query cached-attention core
    (``ml.ops.attention.gqa_cached_attention``) — the paged cache in
    ``ml.serving`` decodes through the SAME core after gathering its block
    pool into this layout, which is what makes paged-vs-dense bit-exactness
    a checkable contract instead of a hope."""
    return gqa_cached_attention(q, k_cache, v_cache, q_positions)


def bounds_guard(ok, msg: str, **fmt):
    """Opt-in traced bounds check (``TPU_TASK_CHECKIFY=1``): the cache's
    overflow contract is only statically checkable when ``start`` is a
    Python int — a TRACED ``start`` that overflows corrupts the cache tail
    silently. Under the env flag, emit a ``checkify.check`` so callers that
    functionalize (``checkify.checkify``; the serving engine's debug mode
    does) get a loud error with the offending values; eager callers raise
    immediately. Off (the default) this is a no-op — plain ``jit`` callers
    never pay for (or trip over) the un-functionalized check. NOTE: with
    the flag ON, every staged caller (including ``generate``'s scan) must
    be run under ``checkify.checkify`` — that is checkify's contract, and
    why the flag is a debug mode, not a default."""
    if os.environ.get("TPU_TASK_CHECKIFY", "") == "1":
        from jax.experimental import checkify

        checkify.check(ok, msg, **fmt)


def _cached_block(x, layer, cfg: TransformerConfig, cache: dict,
                  positions) -> Tuple[Any, dict]:
    """The TRAINING block with a cache-updating attention closure: every
    projection, norm, rope application, and residual is transformer._block
    itself, so the bit-exact train/decode parity the tests pin cannot
    drift — only the attention (against cached k/v) differs."""
    updated: dict = {}

    def attn_fn(q, k, v):
        # k/v arrive at KV-head width and the cache STAYS narrow end to
        # end — _cached_attention groups query heads over the kv heads.
        updated["k"] = jax.lax.dynamic_update_slice(
            cache["k"], k, (0, positions[0], 0, 0))
        updated["v"] = jax.lax.dynamic_update_slice(
            cache["v"], v, (0, positions[0], 0, 0))
        return _cached_attention(q, updated["k"], updated["v"], positions)

    # MoE layers decode through the dense dispatch (single-device exact
    # path); the router aux loss is a training-only term — dropped here.
    x, _aux = _block(x, layer, cfg, attn_fn, positions=positions)
    return x, updated


def forward_with_cache(params: Params, cfg: TransformerConfig, tokens,
                       caches: List[dict], start: int):
    """Run ``tokens`` (batch, s) occupying absolute positions
    [start, start+s) through the model, filling the caches. Returns
    (last-position logits (batch, vocab) float32, updated caches).
    ``start`` may be a traced scalar — shapes stay static.

    HARD CONTRACT: ``start + s`` must not exceed the cache's ``max_len``.
    The buffer is positional, not a ring — ``dynamic_update_slice`` CLAMPS
    writes at capacity, so streaming past it silently corrupts the tail
    slots (rope positions keep advancing while writes stop moving).
    :func:`generate` validates its own bounds; direct callers get a loud
    error here when ``start`` is a concrete Python int, and must enforce
    the bound themselves when it is traced."""
    s = tokens.shape[1]
    max_len = caches[0]["k"].shape[1] if caches else 0
    if isinstance(start, int) and start + s > max_len:
        raise ValueError(
            f"cache overflow: start {start} + tokens {s} > max_len "
            f"{max_len} (the cache is a fixed buffer, not a ring)")
    bounds_guard(start + s <= max_len,
                 "cache overflow: start {start} + tokens {s} > max_len "
                 "{max_len} (the cache is a fixed buffer, not a ring)",
                 start=jnp.asarray(start), s=jnp.asarray(s),
                 max_len=jnp.asarray(max_len))
    positions = start + jnp.arange(s)
    x = embed_lookup(params["embed"].astype(cfg.dtype), tokens)
    new_caches = []
    for layer, cache in zip(params["layers"], caches):
        x, cache = _cached_block(x, layer, cfg, cache, positions)
        new_caches.append(cache)
    x = _rmsnorm(x, params["final_norm"])
    logits = (x[:, -1] @ params["unembed"].astype(cfg.dtype))
    return logits.astype(jnp.float32), new_caches


def _top_p_filter(logits, top_p):
    """Nucleus filtering: keep the smallest probability mass >= top_p,
    everything else to NEG_INF. Static shapes (sort + cumsum), jit-safe.
    ``top_p`` is a scalar, or a (batch,) array for per-row thresholds —
    continuous batching samples every slot with its own request's params in
    one program."""
    top_p = jnp.asarray(top_p, jnp.float32)
    if top_p.ndim:
        top_p = top_p[:, None]
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits.astype(jnp.float32), axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    # Keep every token whose PRECEDING mass is still under top_p — the
    # first token crossing the threshold stays, and the argmax's preceding
    # mass is 0, so at least one token always survives.
    keep = (cumulative - probs) < top_p
    threshold = jnp.min(
        jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits >= threshold, logits, NEG_INF)


def generate(params: Params, cfg: TransformerConfig, prompt,
             max_new_tokens: int, *, temperature: float = 0.0,
             top_p: Optional[float] = None,
             eos_token: Optional[int] = None,
             rng: Optional[jax.Array] = None, max_len: Optional[int] = None):
    """Autoregressive generation. prompt: (batch, prompt_len) int32 →
    (batch, max_new_tokens) int32.

    ``temperature == 0`` is greedy (argmax); otherwise softmax sampling at
    the given temperature (``rng`` required), optionally nucleus-filtered
    to the top ``top_p`` probability mass. ``eos_token``: once a row emits
    it, the row keeps emitting it (static shapes — the scan always runs
    max_new_tokens steps, finished rows just stop changing). One prefill
    pass over the prompt, then a ``lax.scan`` of single-token steps against
    the KV cache — the whole generation is one compiled program."""
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature > 0) needs an rng key")
    if top_p is not None and not 0 < top_p <= 1:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_p is not None and temperature == 0:
        raise ValueError("top_p needs temperature > 0 (greedy ignores it)")
    batch, prompt_len = prompt.shape
    total = (prompt_len + max_new_tokens) if max_len is None else max_len
    if total < prompt_len + max_new_tokens:
        raise ValueError(f"max_len {total} < prompt {prompt_len} + "
                         f"new {max_new_tokens}")

    caches = init_cache(cfg, batch, total)
    logits, caches = forward_with_cache(params, cfg, prompt, caches, 0)

    def pick(logits, key):
        if temperature == 0:
            return jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        # Standard order: temper FIRST, then take the nucleus of the
        # distribution actually being sampled — filtering untempered
        # logits would truncate a flattened (t > 1) distribution far
        # harder than top_p implies.
        logits = logits / temperature
        if top_p is not None:
            logits = _top_p_filter(logits, top_p)
        return jax.random.categorical(key, logits, axis=-1).astype(prompt.dtype)

    keys = (jax.random.split(rng, max_new_tokens) if rng is not None
            else jnp.zeros((max_new_tokens, 2), jnp.uint32))
    first = pick(logits, keys[0])
    done0 = (jnp.zeros((batch,), bool) if eos_token is None
             else first == eos_token)

    def step(carry, key):
        token, caches, position, done = carry
        logits, caches = forward_with_cache(
            params, cfg, token[:, None], caches, position)
        nxt = pick(logits, key)
        if eos_token is not None:
            nxt = jnp.where(done, jnp.asarray(eos_token, nxt.dtype), nxt)
            done = done | (nxt == eos_token)
        return (nxt, caches, position + 1, done), nxt

    # The prefill already produced token 0; scan the remaining n-1 decode
    # steps and emit each step's OWN token — an emit-the-carry shape would
    # pay one whole discarded forward pass per call.
    (_, _, _, _), rest = jax.lax.scan(
        step, (first, caches, jnp.int32(prompt_len), done0), keys[1:])
    return jnp.concatenate(
        [first[:, None], jnp.moveaxis(rest, 0, 1)], axis=1)
