"""Mixture-of-Experts FFN with expert parallelism over the ``ep`` mesh axis.

Experts are sharded one-per-group across ``ep``; tokens are routed top-k
(top-1 = switch-style) with a capacity factor, exchanged via all_to_all
inside ``shard_map``, processed by the local experts, and returned. Router
jitter/aux-loss keep the load balanced. Slots dropped by the capacity limit
contribute ZERO by default (the switch convention — the block's external
residual is the pass-through); set ``dropped_identity=True`` for a
gate-weighted identity in residual-free wirings. The dense path
(``tpu_task.ml.models.transformer``) stays untouched — MoE is an opt-in
block with the same (batch, seq, d_model) contract.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from tpu_task.ml.parallel.mesh import shard_map as _shard_map


@dataclass(frozen=True)
class MoEConfig:
    d_model: int = 64
    d_ff: int = 128
    n_experts: int = 4
    capacity_factor: float = 1.25
    router_noise: float = 0.0
    # Experts consulted per token. top_k=1 keeps switch semantics (gate =
    # winning probability); top_k>1 renormalizes the chosen gates to sum 1
    # (GShard-style).
    top_k: int = 1
    # Dropped-slot policy. False (default): dropped slots contribute ZERO —
    # the switch convention, correct when the block is wired with the
    # standard external residual (x + moe(x)): the residual IS the
    # pass-through, and adding gate*x here would double-count. True:
    # dropped slots contribute a gate-weighted identity — for residual-free
    # wirings where a zero would erase the token's representation.
    dropped_identity: bool = False


def init(rng, cfg: MoEConfig) -> Dict[str, Any]:
    k_router, k_in, k_out = jax.random.split(rng, 3)
    scale_in = cfg.d_model ** -0.5
    return {
        "router": jax.random.normal(k_router, (cfg.d_model, cfg.n_experts),
                                    jnp.float32) * scale_in,
        # Experts stacked on a leading axis — logical axis "expert" → ep.
        "w_in": jax.random.normal(k_in, (cfg.n_experts, cfg.d_model, cfg.d_ff),
                                  jnp.float32) * scale_in,
        "w_out": jax.random.normal(k_out, (cfg.n_experts, cfg.d_ff, cfg.d_model),
                                   jnp.float32) * (cfg.d_ff ** -0.5),
    }


def param_logical_axes() -> Dict[str, Tuple]:
    return {
        "router": ("embed", None),
        "w_in": ("expert", "embed", "mlp"),
        "w_out": ("expert", "mlp", "embed"),
    }


def _route(x, router, cfg: MoEConfig, rng=None):
    """Top-k routing: (expert_index, gate) of shape (tokens, k) + the
    per-expert load statistics (assigned fraction, mean router probability)
    the aux loss is built from. The stats stay separate so the sharded path
    can average them GLOBALLY before taking their product — the aux is
    quadratic in the stats, and a mean of per-shard products would differ
    from the dense reference."""
    logits = x @ router  # (tokens, n_experts)
    if cfg.router_noise > 0 and rng is not None:
        logits = logits + cfg.router_noise * jax.random.normal(
            rng, logits.shape, logits.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate, expert_index = lax.top_k(probs, cfg.top_k)  # (tokens, k) each
    if cfg.top_k > 1:
        gate = gate / jnp.sum(gate, axis=-1, keepdims=True)
    # Load-balancing statistics over all k assignments (switch/GShard).
    assigned = jnp.mean(
        jax.nn.one_hot(expert_index, cfg.n_experts).sum(axis=1), axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    return expert_index, gate, (assigned, density_proxy)


def _aux_from_stats(stats, cfg: MoEConfig):
    assigned, density_proxy = stats
    return cfg.n_experts * jnp.sum(assigned * density_proxy) / cfg.top_k


def apply_dense(params, cfg: MoEConfig, x, rng=None):
    """Single-device reference: dispatch via one-hot matmuls (no a2a, no
    capacity limit — the exact result the sharded path approaches as
    capacity grows)."""
    b, s, d = x.shape
    tokens = x.reshape(b * s, d)
    expert_index, gate, stats = _route(tokens, params["router"], cfg, rng)
    aux_loss = _aux_from_stats(stats, cfg)
    # top_k experts per token are DISTINCT, so the k one-hots are disjoint:
    # one summed dispatch matrix feeds a single expert pass, and the
    # gate-weighted combine separates the slots again.
    one_hot = jax.nn.one_hot(expert_index, cfg.n_experts,
                             dtype=x.dtype)                 # (t, k, e)
    dispatch = one_hot.sum(axis=1)                          # (t, e) ∈ {0,1}
    weights = jnp.einsum("tke,tk->te", one_hot,
                         gate.astype(x.dtype))              # gate per expert
    # (experts, tokens, d): every expert sees its tokens, zeros elsewhere.
    dispatched = jnp.einsum("te,td->etd", dispatch, tokens)
    hidden = jax.nn.silu(
        jnp.einsum("etd,edf->etf", dispatched, params["w_in"]))
    out = jnp.einsum("etf,efd->etd", hidden, params["w_out"])
    combined = jnp.einsum("etd,te->td", out, weights)
    return combined.reshape(b, s, d), aux_loss


def apply_sharded(params, cfg: MoEConfig, x, mesh, axis_name: str = "ep",
                  rng=None, batch_axes=None, tp_axis=None, capacity=None):
    """Expert-parallel forward: tokens sharded over ep, experts one group
    each, all_to_all token exchange both ways.

    ``batch_axes``: mesh axes the token batch dim shards over (default:
    just ``axis_name``). Pass e.g. ``("dp", "ep")`` to compose expert
    parallelism with data parallelism in one mesh — the all_to_all stays
    inside each dp group (experts replicate over dp, shard over ep).

    ``tp_axis``: tensor-parallel axis the expert FFN's HIDDEN (d_ff) dim
    additionally shards over — the tp×ep composition serving gangs run:
    each device holds ``n_experts/ep`` experts × ``d_ff/tp`` of their
    hidden width (exactly the layout the partition registry's
    ``("expert", "embed", "mlp")`` annotation places), the local ``w_out``
    contraction is a partial sum over its f-shard, and one ``psum`` over
    ``tp_axis`` completes it before tokens return through the ep
    all_to_all. Tokens replicate over tp (the ep exchange stays inside
    each tp group). None (default) keeps the training-path layout
    byte-identical.

    ``capacity``: explicit per-expert capacity-buffer depth, overriding
    the ``capacity_factor`` formula. The serving dispatch passes its
    (static) local token count here, making the dispatch DROPLESS by
    construction — no masked garbage row can ever evict a real token's
    slot, which is what keeps the ep path's greedy streams identical to
    the dense-dispatch reference."""
    if batch_axes is None:
        batch_axes = (axis_name,)
    n_shards = mesh.shape[axis_name]
    if cfg.n_experts % n_shards:
        raise ValueError(f"n_experts {cfg.n_experts} not divisible by "
                         f"ep={n_shards}")
    if tp_axis is not None and cfg.d_ff % mesh.shape[tp_axis]:
        raise ValueError(f"d_ff {cfg.d_ff} not divisible by "
                         f"{tp_axis}={mesh.shape[tp_axis]}")
    experts_per_shard = cfg.n_experts // n_shards

    def shard_fn(router, w_in, w_out, x_local):
        b, s, d = x_local.shape
        tokens = x_local.reshape(b * s, d)
        n_tokens = tokens.shape[0]
        # Decorrelate router jitter across shards: each shard's tokens are
        # distinct, so identical noise would defeat the jitter's purpose.
        # Fold in EVERY batch axis index — under dp×ep composition two
        # shards with the same ep index still hold different tokens.
        shard_rng = rng
        if shard_rng is not None:
            for ax in batch_axes:
                shard_rng = jax.random.fold_in(shard_rng, lax.axis_index(ax))
        expert_index, gate, stats = _route(tokens, router, cfg, shard_rng)
        cap = capacity if capacity is not None else max(
            1, int(cfg.capacity_factor * n_tokens * cfg.top_k
                   / cfg.n_experts))

        # Flatten the (tokens, k) assignments slot-major so primary-slot
        # assignments win capacity over secondary ones.
        flat_expert = expert_index.T.reshape(-1)   # (k * n_tokens,)
        flat_gate = gate.T.reshape(-1)
        flat_tokens = jnp.tile(tokens, (cfg.top_k, 1))

        # Position of each assignment within its expert's capacity buffer:
        # 0-based arrival order among assignments routed to the same expert.
        one_hot = jax.nn.one_hot(flat_expert, cfg.n_experts, dtype=jnp.int32)
        position = jnp.sum(jnp.cumsum(one_hot, axis=0) * one_hot, axis=-1) - 1
        keep = position < cap

        # Dispatch buffer: (n_experts, capacity, d).
        buffer = jnp.zeros((cfg.n_experts, cap, d), x_local.dtype)
        safe_pos = jnp.where(keep, position, 0)
        buffer = buffer.at[flat_expert, safe_pos].add(
            flat_tokens * keep[:, None].astype(tokens.dtype))

        # all_to_all: (n_experts, cap, d) → exchange expert groups so each
        # shard holds its experts' tokens from EVERY shard:
        # (experts_per_shard * n_shards_tokens, cap, d).
        grouped = buffer.reshape(n_shards, experts_per_shard, cap, d)
        exchanged = lax.all_to_all(grouped, axis_name, split_axis=0,
                                   concat_axis=0, tiled=False)
        # exchanged: (n_shards, experts_per_shard, capacity, d) where leading
        # axis is source shard.
        hidden = jax.nn.silu(jnp.einsum("xecd,edf->xecf", exchanged, w_in))
        out = jnp.einsum("xecf,efd->xecd", hidden, w_out)
        if tp_axis is not None:
            # Local f-shard contraction above is a partial sum; complete
            # it across tp BEFORE tokens return through the ep exchange
            # (the psum also makes the output tp-invariant, matching the
            # tokens-replicated-over-tp out spec).
            out = lax.psum(out, tp_axis)
        # Return tokens to their source shards.
        returned = lax.all_to_all(out, axis_name, split_axis=0,
                                  concat_axis=0, tiled=False)
        returned = returned.reshape(cfg.n_experts, cap, d)

        delivered = returned[flat_expert, safe_pos]
        if cfg.dropped_identity:
            slot_out = jnp.where(keep[:, None], delivered, flat_tokens)
        else:  # switch convention: the external residual is the pass-through
            slot_out = delivered * keep[:, None].astype(tokens.dtype)
        combined = jnp.sum(
            (slot_out * flat_gate[:, None].astype(tokens.dtype)).reshape(
                cfg.top_k, n_tokens, d),
            axis=0)
        # Average the load STATISTICS over every token-sharding axis first,
        # then take their product: equal-sized shards make the global means
        # exact, so the aux equals the dense single-device one (a mean of
        # per-shard aux products would not — the aux is quadratic in the
        # stats). Also makes the scalar mesh-invariant (out_specs demands
        # it).
        for ax in dict.fromkeys((*batch_axes, axis_name)):
            stats = jax.tree.map(lambda s: lax.pmean(s, ax), stats)
        aux = _aux_from_stats(stats, cfg)
        return combined.reshape(b, s, d), aux

    token_spec = PartitionSpec(batch_axes, None, None)  # batch over dp×ep
    # Experts sharded on ep; with a tp axis the hidden (d_ff) dim of the
    # expert weights additionally shards over tp — the registry's
    # ("expert", "embed", "mlp") layout, consumed in place.
    w_in_spec = PartitionSpec(axis_name, None, tp_axis)
    w_out_spec = PartitionSpec(axis_name, tp_axis, None)
    fn = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(PartitionSpec(None, None), w_in_spec, w_out_spec,
                  token_spec),
        out_specs=(token_spec, PartitionSpec()),
    )
    return fn(params["router"], params["w_in"], params["w_out"], x)
