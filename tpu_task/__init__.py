"""tpu-task: TPU-native full-lifecycle orchestration of ephemeral ML tasks.

A from-scratch rebuild of the capabilities of terraform-provider-iterative
(see SURVEY.md), targeting Cloud TPU as a first-class citizen, plus a JAX/Pallas
compute stack (models, parallelism, kernels) for the task scripts it runs.
"""

__version__ = "0.1.0"
