"""SLO plane: declarative objectives, multi-window error-budget burn
rates, and durable breach alerts.

PR 11 built the measurement substrate (mergeable registry snapshots);
this module *interprets* it. An :class:`SloObjective` names a metric and
what "good" means — a latency histogram with a threshold (good = sample
at or under the threshold) or an availability counter pair (good =
total − bad) — and an :class:`SloClass` bundles objectives with the
Google-SRE-workbook multi-window burn-rate policy: alert only when BOTH
a fast window (catches cliff-edge regressions in minutes) and a slow
window (filters one-bucket blips) burn error budget faster than their
thresholds.

The math is deliberately exact and unit-pinnable. Registry snapshots are
cumulative, so a window is a DELTA between the snapshot nearest the
window start and the newest one; histogram deltas subtract bucket-wise
(the same monotone grid :meth:`Histogram.merge` adds), counter deltas
subtract values. With budget ``1 − target``::

    error_rate(window)  = bad_delta / (good_delta + bad_delta)
    burn_rate(window)   = error_rate / budget

A burn rate of 1.0 spends exactly the error budget over the SLO period;
14.4 (the workbook's fast default) exhausts a 30-day budget in 2 days.
``tests/test_operations.py`` pins a synthetic histogram to a known burn
rate on both windows.

Breaches are DURABLE: :func:`write_alert` lands one JSON record per
breach occurrence under ``obs/alerts/`` of any storage ``Backend`` — the
same per-occurrence-key contract as the PR 3 governor events (the key
embeds the breach start stamp, so re-evaluating an ongoing breach
overwrites its own record instead of growing the store). The scheduler
tick and ``ServeFleet.flush_obs`` are the two evaluation points;
``tpu-task obs alerts`` and ``obs watch`` read the records back.

Plain Python on the host, like everything in ``obs/`` — this module
never imports jax, storage, or serving code.
"""

from __future__ import annotations

import json
import re
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ALERT_PREFIX",
    "Alert",
    "BurnWindow",
    "ObjectiveStatus",
    "SloClass",
    "SloEvaluator",
    "SloObjective",
    "hist_good_bad",
    "read_alerts",
    "write_alert",
]

ALERT_PREFIX = "obs/alerts/"


@dataclass(frozen=True)
class BurnWindow:
    """One evaluation window: seconds of history + the burn-rate level
    above which it votes to alert."""

    window_s: float
    max_burn: float


#: The SRE-workbook page-tier defaults: 5 min at 14.4× + 1 h at 6×.
FAST_BURN = BurnWindow(300.0, 14.4)
SLOW_BURN = BurnWindow(3600.0, 6.0)


@dataclass(frozen=True)
class SloObjective:
    """What "good" means for one metric.

    Two kinds, discriminated by ``threshold_s``:

    * **latency** (``threshold_s`` set): ``metric`` names a histogram;
      an event is good when its sample is at or under the threshold.
      The threshold resolves at bucket resolution — a bucket is good iff
      its upper bound is ≤ the threshold — so thresholds should sit on
      or near a bucket boundary (~33% grid at the default 8/decade).
    * **availability** (``threshold_s`` None): ``metric`` names the
      bad-event counter and ``total_metric`` the total-event counter;
      good = total − bad.

    ``metric`` may end in ``.*``: the objective expands to one instance
    per matching snapshot key (the per-tenant/per-service fan-out —
    ``sched.queue_latency_s.*`` evaluates every tenant separately).
    """

    name: str
    metric: str
    target: float                          # good fraction, e.g. 0.99
    threshold_s: Optional[float] = None
    total_metric: Optional[str] = None

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {self.target}")
        if self.threshold_s is None and self.total_metric is None:
            raise ValueError(
                f"objective {self.name!r} needs threshold_s (latency over "
                "a histogram) or total_metric (availability over counters)")

    @property
    def budget(self) -> float:
        return 1.0 - self.target


@dataclass(frozen=True)
class SloClass:
    """A service/tenant class: objectives + the multi-window policy."""

    name: str
    objectives: Tuple[SloObjective, ...]
    fast: BurnWindow = FAST_BURN
    slow: BurnWindow = SLOW_BURN

    def __post_init__(self):
        # Accept any sequence; store the tuple the frozen dataclass needs.
        object.__setattr__(self, "objectives", tuple(self.objectives))


# -- good/bad extraction -------------------------------------------------------


def hist_good_bad(entry: dict, threshold_s: float) -> Tuple[float, float]:
    """(good, bad) event counts of one histogram SNAPSHOT at a latency
    threshold, at bucket resolution: bucket ``i`` is good iff its upper
    bound ``lo·growth^i`` (``lo`` for the underflow bucket) is ≤ the
    threshold; the overflow bucket is always bad. One-ulp tolerance so a
    threshold ON a boundary counts that boundary's bucket as good."""
    lo = entry["lo"]
    growth = 10.0 ** (1.0 / entry["per_decade"])
    n = entry["n"]
    limit = threshold_s * (1.0 + 1e-9)
    good = bad = 0.0
    for index, count in entry.get("counts", {}).items():
        i = int(index)
        if i >= n - 1:                    # overflow: no finite upper bound
            bad += count
        elif (lo if i == 0 else lo * growth ** i) <= limit:
            good += count
        else:
            bad += count
    return good, bad


def _hist_delta(new: dict, old: Optional[dict]) -> dict:
    """Bucket-wise ``new − old`` (snapshots are cumulative). A negative
    bucket means the source restarted its registry — clamp to the new
    snapshot's count (the conservative reading: everything since the
    restart is inside the window)."""
    if old is None or old.get("type") != "histogram":
        return new
    out = dict(new)
    old_counts = old.get("counts", {})
    counts = {}
    for index, count in new.get("counts", {}).items():
        delta = count - old_counts.get(index, 0)
        counts[index] = count if delta < 0 else delta
    out["counts"] = {i: c for i, c in counts.items() if c}
    out["count"] = sum(counts.values())
    return out


def _counter_delta(new: dict, old: Optional[dict]) -> float:
    value = float(new.get("value", 0.0))
    if old is None or old.get("type") != new.get("type"):
        return value
    delta = value - float(old.get("value", 0.0))
    return value if delta < 0 else delta


# -- evaluation ----------------------------------------------------------------


@dataclass
class ObjectiveStatus:
    """One objective instance's current reading."""

    slo: str
    objective: str
    metric: str
    target: float
    attainment: float                     # cumulative good fraction
    burn_fast: float
    burn_slow: float
    breached: bool

    def to_json(self) -> dict:
        return {
            "slo": self.slo, "objective": self.objective,
            "metric": self.metric, "target": self.target,
            "attainment": round(self.attainment, 6),
            "burn_fast": round(self.burn_fast, 4),
            "burn_slow": round(self.burn_slow, 4),
            "breached": self.breached,
        }


@dataclass
class Alert:
    """A durable breach record. ``started_at`` is stable across
    re-evaluations of one ongoing breach — it keys the durable record,
    so persisting an ongoing alert is idempotent."""

    slo: str
    objective: str
    metric: str
    target: float
    burn_fast: float
    burn_slow: float
    attainment: float
    started_at: float
    at: float
    windows: Dict[str, float] = field(default_factory=dict)

    def key(self) -> str:
        metric = re.sub(r"[^A-Za-z0-9_.-]", "_", self.metric)
        return (f"{ALERT_PREFIX}{self.slo}-{self.objective}-{metric}"
                f"-{int(self.started_at * 1000):013d}.json")

    def to_json(self) -> dict:
        return {
            "slo": self.slo, "objective": self.objective,
            "metric": self.metric, "target": self.target,
            "burn_fast": round(self.burn_fast, 4),
            "burn_slow": round(self.burn_slow, 4),
            "attainment": round(self.attainment, 6),
            "started_at": self.started_at, "at": self.at,
            "windows": self.windows,
        }

    @classmethod
    def from_json(cls, record: dict) -> "Alert":
        return cls(slo=record["slo"], objective=record["objective"],
                   metric=record["metric"], target=record["target"],
                   burn_fast=record["burn_fast"],
                   burn_slow=record["burn_slow"],
                   attainment=record.get("attainment", 0.0),
                   started_at=record["started_at"], at=record["at"],
                   windows=dict(record.get("windows", {})))


class SloEvaluator:
    """Window the cumulative registry snapshots and evaluate burn rates.

    Callers :meth:`observe` a (merged) snapshot whenever they have a
    fresh one — the scheduler every tick, the fleet every obs flush —
    and :meth:`evaluate` reads burn rates off the retained ring. The
    clock is injectable (the scheduler runs on a virtual clock in tests
    and soaks); timestamps only ever come from it."""

    def __init__(self, slos: Sequence[SloClass],
                 clock: Callable[[], float] = time.monotonic,
                 max_samples: int = 512):
        self.slos = list(slos)
        self.clock = clock
        self._ring: List[Tuple[float, dict]] = []
        self._max_samples = max_samples
        horizon = max((max(slo.fast.window_s, slo.slow.window_s)
                       for slo in self.slos), default=0.0)
        self._horizon = 2.0 * horizon
        #: (slo, objective, metric) -> breach start stamp; keys stable
        #: while a breach is ongoing (the alert-record idempotency).
        self._breach_started: Dict[tuple, float] = {}

    # -- snapshot ring --------------------------------------------------------
    def observe(self, snapshot: dict, now: Optional[float] = None) -> None:
        now = self.clock() if now is None else now
        self._ring.append((now, snapshot))
        cutoff = now - self._horizon
        while len(self._ring) > 2 and (self._ring[1][0] <= cutoff
                                       or len(self._ring) > self._max_samples):
            # Keep at least the newest baseline OUTSIDE the horizon so
            # the slow window always has a subtrahend.
            self._ring.pop(0)

    def _baseline(self, now: float, window_s: float) -> Optional[dict]:
        """The newest snapshot at or before the window start (falling
        back to the oldest retained — a shorter-than-window history
        reads as "since the beginning")."""
        chosen = None
        for stamp, snapshot in self._ring:
            if stamp <= now - window_s:
                chosen = snapshot
            else:
                break
        if chosen is None and self._ring:
            chosen = self._ring[0][1]
        return chosen

    # -- math -----------------------------------------------------------------
    @staticmethod
    def _good_bad(objective: SloObjective, metric: str, snapshot: dict,
                  baseline: Optional[dict]) -> Tuple[float, float]:
        entry = snapshot.get(metric)
        if entry is None:
            return 0.0, 0.0
        base_entry = (baseline or {}).get(metric)
        if objective.threshold_s is not None:
            if entry.get("type") != "histogram":
                return 0.0, 0.0
            return hist_good_bad(_hist_delta(entry, base_entry),
                                 objective.threshold_s)
        total_entry = snapshot.get(objective.total_metric)
        if total_entry is None:
            return 0.0, 0.0
        bad = _counter_delta(entry, base_entry)
        total = _counter_delta(total_entry,
                               (baseline or {}).get(objective.total_metric))
        return max(0.0, total - bad), min(bad, total)

    def _burn(self, objective: SloObjective, metric: str, snapshot: dict,
              baseline: Optional[dict]) -> float:
        good, bad = self._good_bad(objective, metric, snapshot, baseline)
        total = good + bad
        if total <= 0:
            return 0.0
        return (bad / total) / objective.budget

    def _instances(self, objective: SloObjective,
                   snapshot: dict) -> List[str]:
        if not objective.metric.endswith(".*"):
            return [objective.metric]
        prefix = objective.metric[:-1]    # keep the trailing dot
        return sorted(name for name in snapshot
                      if name.startswith(prefix))

    # -- the evaluation pass ---------------------------------------------------
    def evaluate(self, now: Optional[float] = None
                 ) -> Tuple[List[ObjectiveStatus], List[Alert]]:
        """Burn rates for every objective instance over the retained
        ring. Returns (statuses, alerts): ``alerts`` carries one record
        per CURRENTLY-breached instance (stable ``started_at`` while the
        breach persists — persist them all, the durable key dedups)."""
        now = self.clock() if now is None else now
        if not self._ring:
            return [], []
        snapshot = self._ring[-1][1]
        statuses: List[ObjectiveStatus] = []
        alerts: List[Alert] = []
        for slo in self.slos:
            fast_base = self._baseline(now, slo.fast.window_s)
            slow_base = self._baseline(now, slo.slow.window_s)
            for objective in slo.objectives:
                for metric in self._instances(objective, snapshot):
                    burn_fast = self._burn(objective, metric, snapshot,
                                           fast_base)
                    burn_slow = self._burn(objective, metric, snapshot,
                                           slow_base)
                    good, bad = self._good_bad(objective, metric,
                                               snapshot, None)
                    attainment = good / (good + bad) if good + bad else 1.0
                    breached = (burn_fast > slo.fast.max_burn
                                and burn_slow > slo.slow.max_burn)
                    statuses.append(ObjectiveStatus(
                        slo=slo.name, objective=objective.name,
                        metric=metric, target=objective.target,
                        attainment=attainment, burn_fast=burn_fast,
                        burn_slow=burn_slow, breached=breached))
                    key = (slo.name, objective.name, metric)
                    if breached:
                        started = self._breach_started.setdefault(key, now)
                        alerts.append(Alert(
                            slo=slo.name, objective=objective.name,
                            metric=metric, target=objective.target,
                            burn_fast=burn_fast, burn_slow=burn_slow,
                            attainment=attainment, started_at=started,
                            at=now,
                            windows={"fast_s": slo.fast.window_s,
                                     "slow_s": slo.slow.window_s}))
                    else:
                        self._breach_started.pop(key, None)
        return statuses, alerts


# -- durable alert records -----------------------------------------------------


def write_alert(backend, alert: Alert) -> str:
    """One JSON record per breach occurrence under ``obs/alerts/`` —
    the durable event plane (same Backend seam as the PR 3 governor
    events). The key embeds the breach start, so re-persisting an
    ongoing breach overwrites its own record (idempotent)."""
    key = alert.key()
    backend.write(key, json.dumps(alert.to_json()).encode())
    return key


def read_alerts(backend, prefix: str = ALERT_PREFIX) -> List[Alert]:
    """Every durable alert, newest last. Unreadable records are skipped
    — a torn write must never take the viewer down."""
    alerts: List[Alert] = []
    for key in sorted(backend.list(prefix)):
        if not key.endswith(".json"):
            continue
        try:
            alerts.append(Alert.from_json(json.loads(backend.read(key))))
        except (ValueError, KeyError, OSError):
            continue
    alerts.sort(key=lambda alert: (alert.started_at, alert.slo,
                                   alert.objective, alert.metric))
    return alerts
