"""Goodput, MFU, and dispatch-overhead accounting for the serving engine.

Three questions the raw tok/s number cannot answer, each an always-on
gauge on the PR 11 registry (so they export, merge, and render like
every other metric):

* **Where does wall time go?** The engine splits every step's wall into
  *in-program* time (inside the fused jitted programs — dispatch +
  device compute) and *host-gap* time (everything else: admission,
  retire bookkeeping, numpy staging, Python). ``goodput.host_gap_frac``
  is the direct measurement of ROADMAP item 4's "the step loop re-enters
  Python per token" claim — the number the multi-token micro-step work
  must drive down. Caveat (synchronous loop): time is measured around
  the program CALL, so a backend with fully async dispatch attributes
  device time that completes after the call returns to the host gap; an
  engine that reads tokens back every step blocks through the readback
  and the split is faithful. The OVERLAPPED loop (``ServingConfig.
  overlap``) splits three ways instead: program time is the dispatch
  call plus the consume edge's blocked wait (device demonstrably busy),
  host work done while a program was in flight is *overlapped*
  (``goodput.overlapped_host_s`` — the device has queued work under it,
  so it is covered, not a gap), and only host time in steps with NO
  program in flight — the drain tail, the first step's pre-dispatch
  sliver, a flush that emptied the pipeline — charges the host gap.
  KV tier migration (PR 17) is attributed the same way: ``_demote_pass``
  stages device→host copies inside the covered window and forces them
  at the consume edge, so demote traffic lands in ``overlapped_host_s``
  / program time, never the gap — offload at batch 32 keeps
  ``host_gap_frac`` ~0 (the `make bench-tier` pin).
  ``host_gap_frac`` under overlap therefore measures device idle the
  host could have prevented, which the double-buffered loop drives to
  ~zero by construction; the wall-clock win it buys is reported
  separately (``bench.py goodput --async``) because a one-core CPU
  host time-slices "device" and host onto the same core — attribution
  says what a real accelerator would hide, wall says what this host
  actually hid.
* **How much work was wasted?** Tokens are the unit: recompute
  preemptions roll back emitted tokens, rejected speculative proposals
  were scored and discarded, re-dispatched prefixes are re-ingested
  context another engine already produced. ``goodput.ratio`` =
  useful / (useful + wasted) token-work.
* **How close to the hardware?** A static per-step FLOP cost model over
  the ``ml/serving/model.py`` shapes (2 FLOPs per matmul parameter per
  token + the position-dependent attention term) accumulates model
  FLOPs; ``goodput.mfu`` divides by busy wall × peak FLOP/s. Peak comes
  from the device kind (the public TPU spec sheets) or
  ``TPU_TASK_PEAK_FLOPS``; off-TPU there is no meaningful peak, so a
  documented nominal 1e12 makes the gauge a relative utilization number
  (trend, not absolute). :func:`decode_step_cost_analysis_flops`
  cross-checks the static model against
  ``jax.jit(...).lower().cost_analysis()`` where the backend provides
  one.

The meter is created only when the engine has an ``obs`` handle — the
``obs=None`` zero-overhead contract is untouched — and costs two
``perf_counter`` calls per program dispatch plus a few vectorized numpy
ops per step.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

__all__ = [
    "GoodputMeter",
    "NOMINAL_PEAK_FLOPS",
    "PEAK_FLOPS_BY_KIND",
    "decode_step_cost_analysis_flops",
    "flops_for_positions",
    "matmul_params",
    "peak_flops_per_s",
    "token_flops",
]

#: Peak dense bf16 FLOP/s per chip by device kind (public spec sheets) —
#: the same table bench.py's train-step MFU uses.
PEAK_FLOPS_BY_KIND = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

#: Off-TPU fallback: no public peak exists for an arbitrary host CPU, so
#: the MFU gauge runs on a nominal 1 TFLOP/s — a RELATIVE utilization
#: number (comparable run-to-run on one host, not across hardware).
NOMINAL_PEAK_FLOPS = 1e12


def peak_flops_per_s() -> float:
    """Peak FLOP/s of the attached accelerator: ``TPU_TASK_PEAK_FLOPS``
    env override first, then the device-kind table, then the documented
    nominal fallback."""
    env = os.environ.get("TPU_TASK_PEAK_FLOPS", "")
    if env:
        return float(env)
    try:
        import jax

        kind = jax.devices()[0].device_kind
        for prefix, peak in PEAK_FLOPS_BY_KIND.items():
            if kind.startswith(prefix):
                return peak
    except Exception:
        pass
    return NOMINAL_PEAK_FLOPS


def matmul_params(cfg) -> int:
    """Matmul parameter count of one forward pass (embedding lookup is a
    gather, not a matmul; the unembed projection is). MoE layers count
    ``moe_top_k`` experts' FFN weights — the per-token compute, not the
    parameter storage."""
    attn = (cfg.d_model * cfg.d_attn          # wq
            + 2 * cfg.d_model * cfg.kv_heads * cfg.d_head   # wk, wv
            + cfg.d_attn * cfg.d_model)       # wo
    dense_ff = 3 * cfg.d_model * cfg.d_ff     # w_gate, w_up, w_down
    total = cfg.d_model * cfg.vocab_size      # unembed
    for i in range(cfg.n_layers):
        total += attn
        if cfg.is_moe_layer(i):
            total += (cfg.d_model * cfg.n_experts          # router
                      + cfg.moe_top_k * 2 * cfg.d_model * cfg.d_ff)
        else:
            total += dense_ff
    return total


def token_flops(cfg, kv_len: int) -> float:
    """Forward FLOPs to process ONE token position attending ``kv_len``
    cache entries: 2 FLOPs per matmul parameter + the attention scores
    and value-gather matmuls (2 · 2 · n_heads · d_head · kv_len per
    layer). The PaLM-appendix forward convention, attention unhalved —
    decode attends the full (non-causal-split) cache."""
    return (2.0 * matmul_params(cfg)
            + 4.0 * cfg.n_layers * cfg.d_attn * kv_len)


def flops_for_positions(cfg, positions) -> float:
    """Vectorized :func:`token_flops` over an array of absolute
    positions (a token at position p attends p + 1 entries — itself
    included, the scatter-then-attend order)."""
    pos = np.asarray(positions, np.float64).reshape(-1)
    if pos.size == 0:
        return 0.0
    return (pos.size * 2.0 * matmul_params(cfg)
            + 4.0 * cfg.n_layers * cfg.d_attn * float(np.sum(pos + 1.0)))


def decode_step_cost_analysis_flops(cfg, scfg, mesh=None) -> Optional[float]:
    """XLA's own FLOP count for one fused greedy decode step (via
    ``jax.jit(...).lower().cost_analysis()``) — the cross-check that
    keeps the static model honest where the backend provides one.
    ``mesh``: lower the SHARDED program instead (MoE configs get the ep
    all_to_all dispatch threaded exactly as the engine compiles it; the
    returned count is then the per-shard partition's). Returns None when
    the backend exposes no cost analysis (or cannot analyze the sharded
    program)."""
    try:
        import jax
        import jax.numpy as jnp

        from tpu_task.ml.models import transformer
        from tpu_task.ml.serving.cache import init_pools
        from tpu_task.ml.serving.model import (
            greedy_decode_step,
            serving_moe_fn,
        )

        params = transformer.init(jax.random.PRNGKey(0), cfg)
        pools = init_pools(cfg, scfg)
        mfn = serving_moe_fn(cfg, mesh)
        n, m = scfg.slots, scfg.max_blocks_per_slot
        lowered = jax.jit(
            lambda p, t, pos, tab, act, pl: greedy_decode_step(
                p, cfg, t, pos, tab, act, pl, mesh=mesh,
                moe_fn=mfn)).lower(
            params, jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32),
            jnp.zeros((n, m), jnp.int32), jnp.ones((n,), bool), pools)
        analysis = lowered.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        flops = analysis.get("flops")
        return float(flops) if flops else None
    except Exception:
        return None


class GoodputMeter:
    """Per-engine accumulator behind the ``goodput.*`` registry names.

    The engine calls :meth:`program` around every fused-program dispatch,
    :meth:`work` with the positions each program processed,
    :meth:`begin_step`/:meth:`end_step` around each scheduler iteration,
    and the token-accounting methods at commit/waste sites. Everything
    exports through the registry (counters sum in the fleet merge,
    gauges are instantaneous), so ``/metrics``, ``obs top``, and
    ``obs watch`` see it like any other metric."""

    def __init__(self, cfg, registry, peak_flops: Optional[float] = None):
        self.cfg = cfg
        self.peak_flops = float(peak_flops if peak_flops is not None
                                else peak_flops_per_s())
        self._base_flops = 2.0 * matmul_params(cfg)
        self._attn_flops = 4.0 * cfg.n_layers * cfg.d_attn
        self.reset()
        for stat in ("program_s", "host_s", "overlapped_host_s",
                     "dispatches", "model_flops",
                     "tokens_emitted", "tokens_preempted",
                     "tokens_spec_rejected", "tokens_reingested"):
            registry.counter_fn(f"goodput.{stat}",
                                lambda self=self, stat=stat:
                                float(getattr(self, stat)))
        registry.gauge_fn("goodput.ratio", lambda: self.ratio)
        registry.gauge_fn("goodput.mfu", lambda: self.mfu)
        registry.gauge_fn("goodput.host_gap_frac",
                          lambda: self.host_gap_frac)
        registry.gauge_fn("goodput.dispatches_per_token",
                          lambda: self.dispatches_per_token)
        registry.gauge_fn("goodput.peak_flops", lambda: self.peak_flops)

    def reset(self) -> None:
        """Zero the accumulators (benches reset after compile warmup so
        compile seconds don't read as host gap)."""
        self.program_s = 0.0
        self.host_s = 0.0
        self.overlapped_host_s = 0.0
        self.dispatches = 0
        self.model_flops = 0.0
        self.tokens_emitted = 0
        self.tokens_preempted = 0
        self.tokens_spec_rejected = 0
        self.tokens_reingested = 0
        self._prog_mark = 0.0
        self._step_wait: Optional[float] = None

    # -- time accounting -------------------------------------------------------
    def program(self, dt: float) -> None:
        """One fused-program dispatch took ``dt`` seconds (call through
        readback in the synchronous loop; call only — enqueue cost — in
        the overlapped loop, whose device time lands via
        :meth:`consume_wait`. See the module docstring's caveat)."""
        self.program_s += dt
        self.dispatches += 1

    def begin_step(self) -> None:
        self._prog_mark = self.program_s
        self._step_wait = None

    def end_step(self, wall_s: float) -> None:
        """Close one scheduler iteration: whatever the step's wall spent
        outside its program dispatches is host gap."""
        self.host_s += max(0.0, wall_s - (self.program_s - self._prog_mark))

    def consume_wait(self, dt: float) -> None:
        """Overlapped loop only: the consume edge blocked ``dt`` seconds
        waiting on the in-flight program — device-busy time, charged as
        program time (without bumping the dispatch count)."""
        self.program_s += dt
        self._step_wait = dt

    def end_step_overlapped(self, wall_s: float, covered: bool) -> None:
        """Close one OVERLAPPED scheduler iteration. ``covered`` is the
        engine's statement that a program was in flight across this
        step's host work (the previous program was still unconsumed, or
        a new one was dispatched before the sweep) — host time under a
        live program is overlapped, not a gap: the device has work
        queued regardless of what the host does next. A step with no
        program in flight (the drain tail, the first step's pre-dispatch
        sliver, a flush that emptied the pipeline) charges its full gap
        to ``host_s`` — the device really could idle under it."""
        gap = max(0.0, wall_s - (self.program_s - self._prog_mark))
        if covered:
            self.overlapped_host_s += gap
        else:
            self.host_s += gap

    # -- work / token accounting -----------------------------------------------
    def work(self, positions) -> None:
        """Charge the static FLOP model for token positions a program
        processed (TARGET-model programs; draft-model work counts as
        program time but not model FLOPs — MFU stays the target's)."""
        pos = np.asarray(positions, np.float64).reshape(-1)
        if pos.size:
            self.work_counts(pos.size, float(pos.sum()))

    def work_counts(self, count: int, pos_sum: float) -> None:
        """The hot-path form: ``count`` tokens whose positions sum to
        ``pos_sum`` (token at position p attends p + 1 entries, so the
        attention term is ``pos_sum + count``). The engine calls this
        every step with sums over arrays it already holds — no fancy
        indexing, no temporaries (the naive form cost ~4% of a toy
        step's wall; this one is arithmetic)."""
        if count:
            self.model_flops += (count * self._base_flops
                                 + self._attn_flops * (pos_sum + count))

    def work_span(self, n: int) -> None:
        """A whole prompt at positions [0, n): Σ(p+1) = n(n+1)/2 in
        closed form (the bucketed-prefill charge)."""
        if n:
            self.model_flops += (n * self._base_flops
                                 + self._attn_flops * n * (n + 1) / 2.0)

    def emitted(self, n: int = 1) -> None:
        self.tokens_emitted += n

    def wasted_preempt(self, n: int) -> None:
        """A recompute preemption rolled back ``n`` committed tokens."""
        self.tokens_preempted += max(0, n)

    def wasted_spec(self, n: int) -> None:
        """``n`` draft proposals were scored by the target and rejected."""
        self.tokens_spec_rejected += max(0, n)

    def wasted_reingest(self, n: int) -> None:
        """``n`` already-emitted tokens re-ingested as context (a
        re-dispatched/resumed prefix another engine already produced)."""
        self.tokens_reingested += max(0, n)

    # -- gauges ----------------------------------------------------------------
    @property
    def busy_s(self) -> float:
        # Overlapped host time is wall the device spent executing under
        # the sweep — part of the busy denominator (zero in sync mode,
        # so the synchronous gauges are unchanged).
        return self.program_s + self.host_s + self.overlapped_host_s

    @property
    def host_gap_frac(self) -> float:
        busy = self.busy_s
        return self.host_s / busy if busy > 0 else 0.0

    @property
    def ratio(self) -> float:
        """Useful tokens over total token-work. Preempted tokens were
        emitted and thrown away (they re-emit on recompute, so they
        subtract from the numerator AND stay in the denominator)."""
        useful = max(0, self.tokens_emitted - self.tokens_preempted)
        total = (self.tokens_emitted + self.tokens_spec_rejected
                 + self.tokens_reingested)
        return useful / total if total > 0 else 1.0

    @property
    def mfu(self) -> float:
        busy = self.busy_s
        if busy <= 0 or self.peak_flops <= 0:
            return 0.0
        return self.model_flops / busy / self.peak_flops

    @property
    def dispatches_per_token(self) -> float:
        return self.dispatches / max(1, self.tokens_emitted)

    def snapshot(self) -> dict:
        """The ``stats()["goodput"]`` convenience view (everything here
        also rides the registry under ``goodput.*``)."""
        return {
            "ratio": round(self.ratio, 6),
            # Full precision: a toy model's MFU against a TFLOP/s-scale
            # peak sits far below 1e-6 and must not round to a lying 0.
            "mfu": self.mfu,
            "host_gap_frac": round(self.host_gap_frac, 6),
            "in_program_frac": round(1.0 - self.host_gap_frac, 6),
            "program_s": round(self.program_s, 6),
            "host_s": round(self.host_s, 6),
            # Host work done under an in-flight program (overlap mode) —
            # covered by device execution, so not part of the gap.
            "overlapped_host_s": round(self.overlapped_host_s, 6),
            "dispatches": self.dispatches,
            "dispatches_per_token": round(self.dispatches_per_token, 4),
            "model_flops": self.model_flops,
            "peak_flops": self.peak_flops,
            "tokens": {
                "emitted": self.tokens_emitted,
                "preempted": self.tokens_preempted,
                "spec_rejected": self.tokens_spec_rejected,
                "reingested": self.tokens_reingested,
            },
        }
