"""Request-scoped distributed tracing: spans in a bounded in-process ring.

A trace is minted once per fleet request at ``Router.submit`` and its
context (trace id + parent span id) rides one HTTP header
(:data:`TRACE_HEADER`) through the pooled transport into the replica and
down into the engine's per-slot state — so one trace id names the whole
life of a request across every process that touched it: submit →
dispatch → queue → prefill → first token → decode → [preempt → drain →
export → re-dispatch on a sibling, linked as a child span of the SAME
trace] → finish.

Spans are plain dataclass records. Finished spans land in a bounded
``deque`` ring (drop-oldest — tracing must never become the memory leak
it exists to find) and leave the process through
:class:`tpu_task.obs.export.SpanExporter` (the storage ``Backend`` seam,
``obs/spans/``) or a replica's ``/obs`` endpoint. Span timestamps are
wall-clock (``time.time``) on purpose: they must be comparable across
the processes one waterfall spans; durations inside one process are as
good as monotonic at these (≥ ms) scales.

The zero-overhead contract lives one level up: layers take an optional
``obs`` handle and skip every call here when it is ``None`` — no tracer
object, no timestamps, no ring. This module never imports jax, storage,
or serving code.
"""

from __future__ import annotations

import collections
import threading
import time
import uuid
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

__all__ = ["TRACE_HEADER", "Span", "TraceContext", "Tracer"]

#: The one propagation header: ``<trace_id>:<parent span_id>``.
TRACE_HEADER = "X-Tpu-Task-Trace"


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass(frozen=True)
class TraceContext:
    """What crosses a process boundary: which trace, and which span the
    receiver's spans are children of."""

    trace_id: str
    span_id: str

    def to_header(self) -> str:
        return f"{self.trace_id}:{self.span_id}"

    @classmethod
    def mint(cls) -> "TraceContext":
        """A fresh trace with a virtual root span id — for components
        that receive no upstream context but must keep all their spans
        for one request in ONE trace (an engine driven directly, a
        replica client that sends no header). The root id never gets a
        span record; renderers treat its children as orphan roots."""
        return cls(trace_id=_new_id(), span_id=_new_id())

    @classmethod
    def from_header(cls, value: Optional[str]) -> Optional["TraceContext"]:
        if not value or ":" not in value:
            return None
        trace_id, _, span_id = value.partition(":")
        if not trace_id or not span_id:
            return None
        return cls(trace_id=trace_id, span_id=span_id)


@dataclass
class Span:
    """One timed operation. ``status`` is ``ok`` for the happy path;
    interruptions record what happened instead of finishing
    (``error`` / ``preempted`` / ``exported`` / ``redispatched``)."""

    trace_id: str
    span_id: str
    name: str
    start: float
    end: Optional[float] = None
    parent_id: Optional[str] = None
    status: str = "ok"
    source: str = ""
    attrs: Dict[str, object] = field(default_factory=dict)

    @property
    def ctx(self) -> TraceContext:
        """This span as a parent context for children (local or remote)."""
        return TraceContext(trace_id=self.trace_id, span_id=self.span_id)

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None else self.start) - self.start

    def to_json(self) -> dict:
        return {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "start": self.start, "end": self.end, "status": self.status,
            "source": self.source, "attrs": self.attrs,
        }

    @classmethod
    def from_json(cls, record: dict) -> "Span":
        return cls(trace_id=record["trace_id"], span_id=record["span_id"],
                   parent_id=record.get("parent_id"), name=record["name"],
                   start=record["start"], end=record.get("end"),
                   status=record.get("status", "ok"),
                   source=record.get("source", ""),
                   attrs=dict(record.get("attrs") or {}))


Parent = Union[Span, TraceContext, None]


class Tracer:
    """Mint, finish, and ring-buffer spans for one component.

    Thread-safe: HTTP handler threads, the step loop, and the router all
    append to the same ring. ``capacity`` bounds memory (drop-oldest)."""

    def __init__(self, source: str = "", capacity: int = 4096,
                 clock=time.time):
        self.source = source
        self.clock = clock
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self.dropped = 0

    # -- span lifecycle --------------------------------------------------------
    def start(self, name: str, parent: Parent = None, **attrs) -> Span:
        """Open a span. ``parent=None`` mints a NEW trace (the router's
        root); a :class:`Span`/:class:`TraceContext` parent keeps the
        trace id and links the hierarchy."""
        if parent is None:
            trace_id, parent_id = _new_id(), None
        else:
            ctx = parent.ctx if isinstance(parent, Span) else parent
            trace_id, parent_id = ctx.trace_id, ctx.span_id
        return Span(trace_id=trace_id, span_id=_new_id(),
                    parent_id=parent_id, name=name, start=self.clock(),
                    source=self.source, attrs=dict(attrs))

    def end(self, span: Span, status: str = "ok", **attrs) -> Span:
        span.end = self.clock()
        span.status = status
        if attrs:
            span.attrs.update(attrs)
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(span)
        return span

    def event(self, name: str, parent: Parent = None, status: str = "ok",
              **attrs) -> Span:
        """A zero-duration span — lifecycle transitions, faults."""
        return self.end(self.start(name, parent=parent, **attrs),
                        status=status)

    def error(self, name: str, error: BaseException, parent: Parent = None,
              **attrs) -> Span:
        """A structured error event: exception type + message as span
        attrs, ``status="error"`` — what replaces a bare
        ``traceback.print_exc()`` nobody syncs."""
        return self.event(name, parent=parent, status="error",
                          exc_type=type(error).__name__,
                          error=str(error) or repr(error), **attrs)

    @contextmanager
    def span(self, name: str, parent: Parent = None, **attrs):
        record = self.start(name, parent=parent, **attrs)
        try:
            yield record
        except BaseException as exc:
            self.end(record, status="error", exc_type=type(exc).__name__,
                     error=str(exc) or repr(exc))
            raise
        else:
            self.end(record)

    # -- ring access -----------------------------------------------------------
    def finished(self) -> List[Span]:
        with self._lock:
            return list(self._ring)

    def drain(self) -> List[Span]:
        """Finished spans, cleared — the exporter's read-once path."""
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
            return out
