"""Durable metrics registry: counters, gauges, and mergeable histograms.

One :class:`MetricsRegistry` per process component (engine, replica,
router, scheduler) is the single export path for every number the layer
publishes: each metric has ONE name, ONE type, and serializes through
:meth:`MetricsRegistry.snapshot` — what ``/stats`` serves, what the fleet
flushes under ``obs/metrics/``, and what ``tpu-task obs top`` renders.

Histograms are fixed-bucket streaming histograms over DETERMINISTIC
log-spaced bucket boundaries (``lo · growth^i``): every process derives
the identical bucket grid from the same ``(lo, hi, per_decade)`` knobs,
so replica histograms merge across processes by plain bucket-wise add —
no sample lists shipped, no t-digest dependencies. Quantiles log-
interpolate inside the winning bucket and clamp to the observed
[min, max], so they agree with an exact percentile of the raw samples to
within one bucket (the tier-1 pin `tests/test_obs.py` holds bench.py to
exactly that contract).

Everything here is plain Python on the host — safe anywhere except
inside a traced program (record at dispatch boundaries, never in jit).
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, List, Optional

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "merge_snapshots",
]


class Counter:
    """Monotonic counter. Thread-safe: registries are shared between
    HTTP handler threads and step loops, and ``+=`` is not atomic."""

    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def snapshot(self) -> dict:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-write-wins instantaneous value (a plain store is atomic
    under the GIL — no lock needed)."""

    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self.value}


class Histogram:
    """Streaming histogram over deterministic log-spaced buckets.

    Bucket ``i`` (1 ≤ i ≤ n) covers ``(lo·growth^(i-1), lo·growth^i]``;
    bucket 0 is the underflow catch-all (x ≤ lo) and bucket n+1 the
    overflow. Defaults cover 1 µs .. 10 ks at 8 buckets/decade (~33%
    relative resolution) — wide enough for every latency this repo
    measures, fine enough that "within one bucket" is a usable error bar.
    """

    kind = "histogram"

    def __init__(self, name: str = "", lo: float = 1e-6, hi: float = 1e4,
                 per_decade: int = 8):
        if lo <= 0 or hi <= lo or per_decade < 1:
            raise ValueError(
                f"bad histogram grid lo={lo} hi={hi} per_decade={per_decade}")
        self.name = name
        self.lo = float(lo)
        self.per_decade = int(per_decade)
        self.growth = 10.0 ** (1.0 / per_decade)
        self._inv_log_growth = 1.0 / math.log(self.growth)
        n = int(math.ceil(math.log10(hi / lo) * per_decade))
        self.counts = [0] * (n + 2)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        # observe/merge/snapshot run from handler threads AND step loops
        # on the same shared registry; the multi-field update must be
        # atomic or a mid-observe snapshot serializes count inconsistent
        # with the buckets (breaking quantile/merge math downstream).
        # RLock: snapshot() calls quantile() under the same lock.
        self._lock = threading.RLock()

    # -- recording -------------------------------------------------------------
    def _index(self, x: float) -> int:
        if x <= self.lo:
            return 0
        i = 1 + int(math.floor(math.log(x / self.lo) * self._inv_log_growth
                               # one-ulp guard: exact bucket boundaries must
                               # land in the bucket they close, not the next
                               - 1e-9))
        return min(i, len(self.counts) - 1)

    def observe(self, x: float) -> None:
        x = float(x)
        index = self._index(x)
        with self._lock:
            self.counts[index] += 1
            self.count += 1
            self.sum += x
            self.min = x if self.min is None else min(self.min, x)
            self.max = x if self.max is None else max(self.max, x)

    # -- reading ---------------------------------------------------------------
    def bucket_bounds(self, i: int) -> tuple:
        if i == 0:
            return (0.0, self.lo)
        return (self.lo * self.growth ** (i - 1), self.lo * self.growth ** i)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """q ∈ [0, 1]; log-interpolated inside the winning bucket and
        clamped to the observed [min, max] — agrees with an exact
        percentile of the raw samples to within one bucket."""
        with self._lock:
            if self.count == 0:
                return 0.0
            target = max(1.0, q * self.count)
            cum = 0
            for i, c in enumerate(self.counts):
                cum += c
                if c and cum >= target:
                    lo, hi = self.bucket_bounds(i)
                    frac = (target - (cum - c)) / c
                    value = hi if lo <= 0 else lo * (hi / lo) ** frac
                    return max(self.min, min(self.max, value))
            return self.max  # pragma: no cover (count > 0 lands above)

    # -- merge / serialization -------------------------------------------------
    def merge(self, other: "Histogram") -> "Histogram":
        """Bucket-wise add (the cross-replica aggregation path). Grids
        must match — they do by construction when both sides used the
        same knobs."""
        if (self.lo, self.per_decade, len(self.counts)) != \
                (other.lo, other.per_decade, len(other.counts)):
            raise ValueError(
                f"histogram grids differ: {self.name!r} vs {other.name!r}")
        with self._lock:
            for i, c in enumerate(other.counts):
                self.counts[i] += c
            self.count += other.count
            self.sum += other.sum
            for bound in ("min", "max"):
                theirs = getattr(other, bound)
                ours = getattr(self, bound)
                if theirs is not None:
                    pick = theirs if ours is None else \
                        (min if bound == "min" else max)(ours, theirs)
                    setattr(self, bound, pick)
        return self

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "type": "histogram",
                "lo": self.lo,
                "per_decade": self.per_decade,
                "n": len(self.counts),
                # sparse: latency histograms touch a handful of buckets
                "counts": {str(i): c
                           for i, c in enumerate(self.counts) if c},
                "count": self.count,
                "sum": self.sum,
                "min": self.min,
                "max": self.max,
                "p50": self.quantile(0.50),
                "p99": self.quantile(0.99),
            }

    @classmethod
    def from_snapshot(cls, snap: dict, name: str = "") -> "Histogram":
        hist = cls(name, lo=snap["lo"],
                   hi=snap["lo"] * 10.0 ** ((snap["n"] - 2)
                                            / snap["per_decade"]),
                   per_decade=snap["per_decade"])
        # hi reconstruction can be one bucket short under float log round-
        # trip; size the array from the snapshot, which is authoritative.
        hist.counts = [0] * snap["n"]
        for i, c in snap["counts"].items():
            hist.counts[int(i)] = c
        hist.count = snap["count"]
        hist.sum = snap["sum"]
        hist.min = snap["min"]
        hist.max = snap["max"]
        return hist


class MetricsRegistry:
    """Create-or-get typed metrics under unique names, plus lazy gauges
    (``gauge_fn``) that snapshot existing plain-attribute counters without
    rewriting their mutation sites."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        #: name -> (kind, fn): lazily-evaluated metrics over existing
        #: plain attributes. Kind matters at MERGE time: "counter" sums
        #: across sources (monotonic per-process totals), "gauge" keeps
        #: the last writer (instantaneous values).
        self._lazy_fns: Dict[str, tuple] = {}

    def _get(self, name: str, cls, **kwargs):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = self._metrics[name] = cls(name, **kwargs)
            elif not isinstance(metric, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(metric).__name__}, not {cls.__name__}")
            return metric

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, **kwargs) -> Histogram:
        return self._get(name, Histogram, **kwargs)

    def gauge_fn(self, name: str, fn: Callable[[], float]) -> None:
        """Register a lazily-evaluated gauge (instantaneous value —
        last-write-wins on merge) — the bridge that puts existing plain
        attributes on the one export path without changing how they are
        written."""
        with self._lock:
            self._lazy_fns[name] = ("gauge", fn)

    def counter_fn(self, name: str, fn: Callable[[], float]) -> None:
        """Like :meth:`gauge_fn` but exported as a COUNTER: monotonic
        per-process totals (``engine.steps``, ``router.redispatches``)
        must SUM across sources in the fleet merge, not keep whichever
        replica's snapshot sorted last."""
        with self._lock:
            self._lazy_fns[name] = ("counter", fn)

    def snapshot(self) -> dict:
        out = {}
        with self._lock:
            metrics = dict(self._metrics)
            lazy_fns = dict(self._lazy_fns)
        for name, metric in sorted(metrics.items()):
            out[name] = metric.snapshot()
        for name, (kind, fn) in sorted(lazy_fns.items()):
            try:
                out[name] = {"type": kind, "value": fn()}
            except Exception:
                pass  # a dead closure must never break the export path
        return out


def merge_snapshots(snapshots: List[dict]) -> dict:
    """Fleet-wide aggregation of per-process registry snapshots:
    counters add, histograms merge bucket-wise, gauges keep the last
    writer (they are instantaneous by definition). A TYPE CONFLICT
    (two sources registered one name as different kinds — a version
    skew across a rolling fleet) keeps the first writer deterministically
    instead of corrupting the merge or taking the export path down."""
    merged: dict = {}
    for snap in snapshots:
        for name, entry in snap.items():
            kind = entry.get("type")
            have = merged.get(name)
            if have is None:
                merged[name] = dict(entry)
            elif have.get("type") != kind:
                continue                  # type conflict: first writer wins
            elif kind == "counter":
                have["value"] += entry["value"]
            elif kind == "histogram":
                hist = Histogram.from_snapshot(have, name).merge(
                    Histogram.from_snapshot(entry, name))
                merged[name] = hist.snapshot()
            else:
                merged[name] = dict(entry)
    return merged
