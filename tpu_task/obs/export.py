"""Durable span/metric export and the human-facing renderers.

Export rides the storage ``Backend`` seam — the SAME durability plane
checkpoints, drain files, and scheduler state already use (a replica
writes ``obs/`` into its working directory and the agent's delta sync
ships it; an in-process fleet writes straight into the scheduler's
backend). Zero new transport.

Two output formats:

* :func:`chrome_trace` — the Chrome trace-event JSON Perfetto and
  ``chrome://tracing`` load directly (``ph="X"`` complete events, µs
  timestamps, span attrs as ``args``).
* :func:`render_waterfall` — the terminal view behind
  ``tpu-task obs trace <id>``: one line per span, parent-indented, with
  a proportional timeline bar.
"""

from __future__ import annotations

import itertools
import json
import re
import uuid
from typing import Dict, Iterable, List, Optional

from tpu_task.obs.metrics import merge_snapshots
from tpu_task.obs.trace import Span

__all__ = [
    "SPAN_PREFIX",
    "METRICS_PREFIX",
    "SpanExporter",
    "chrome_trace",
    "export_metrics",
    "prometheus_text",
    "read_metrics",
    "read_spans",
    "render_waterfall",
]

SPAN_PREFIX = "obs/spans/"
METRICS_PREFIX = "obs/metrics/"


class SpanExporter:
    """Append-only span batches under ``obs/spans/`` of any Backend.

    Keys are ``<source>-<run>-<seq>.json`` — ``run`` is per-exporter
    random so a restarted process never overwrites its predecessor's
    batches, ``seq`` keeps one process's batches ordered."""

    def __init__(self, backend, prefix: str = SPAN_PREFIX):
        self._backend = backend
        self._prefix = prefix
        self._run = uuid.uuid4().hex[:8]
        self._seq = itertools.count()

    def export(self, spans: List[Span], source: str = "") -> Optional[str]:
        if not spans:
            return None
        key = (f"{self._prefix}{source or spans[0].source or 'spans'}"
               f"-{self._run}-{next(self._seq):06d}.json")
        self._backend.write(
            key, json.dumps([span.to_json() for span in spans]).encode())
        return key


def read_spans(backend, prefix: str = SPAN_PREFIX) -> List[Span]:
    """Every exported span under ``prefix``, start-ordered. Unreadable
    batches are skipped — a torn write must not take the viewer down."""
    spans: List[Span] = []
    for key in sorted(backend.list(prefix)):
        if not key.endswith(".json"):
            continue
        try:
            spans.extend(Span.from_json(record)
                         for record in json.loads(backend.read(key)))
        except (ValueError, KeyError, OSError):
            continue
    spans.sort(key=lambda span: (span.start, span.span_id))
    return spans


def export_metrics(backend, snapshot: dict, source: str,
                   prefix: str = METRICS_PREFIX) -> str:
    """One registry snapshot per source, last-write-wins — snapshots are
    cumulative, so overwrite IS the correct merge within a source."""
    key = f"{prefix}{source}.json"
    backend.write(key, json.dumps(snapshot).encode())
    return key


def read_metrics(backend, prefix: str = METRICS_PREFIX) -> dict:
    """All sources' snapshots merged (counters add, histograms
    bucket-wise) — the fleet-wide view ``tpu-task obs top`` renders."""
    snapshots = []
    for key in sorted(backend.list(prefix)):
        if not key.endswith(".json"):
            continue
        try:
            snapshots.append(json.loads(backend.read(key)))
        except (ValueError, OSError):
            continue
    return merge_snapshots(snapshots)


def _prom_name(name: str, prefix: str) -> str:
    """Registry name → a legal Prometheus metric name
    (``[a-zA-Z_:][a-zA-Z0-9_:]*``): dots and every other illegal
    character become underscores."""
    out = prefix + re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not re.match(r"[a-zA-Z_:]", out):
        out = "_" + out
    return out


def _prom_num(value) -> str:
    if value is None:
        return "NaN"
    value = float(value)
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return format(value, ".10g")


def prometheus_text(snapshot: dict, prefix: str = "tpu_task_") -> str:
    """One registry (or fleet-merged) snapshot in Prometheus text
    exposition format — what a replica's ``GET /metrics`` serves and any
    standard scraper ingests.

    Counters and gauges map directly; histograms emit the standard
    cumulative ``_bucket{le="..."}`` series (one line per bucket
    boundary where the cumulative count changes, plus the mandatory
    ``le="+Inf"``), ``_sum``, and ``_count``. Bucket boundaries come
    from the deterministic log grid, so a fleet of replicas scrapes
    onto identical ``le`` label sets."""
    lines: List[str] = []
    for name, entry in sorted(snapshot.items()):
        kind = entry.get("type")
        pname = _prom_name(name, prefix)
        if kind in ("counter", "gauge"):
            lines.append(f"# TYPE {pname} {kind}")
            lines.append(f"{pname} {_prom_num(entry.get('value', 0.0))}")
        elif kind == "histogram":
            lines.append(f"# TYPE {pname} histogram")
            lo, per_decade = entry["lo"], entry["per_decade"]
            growth = 10.0 ** (1.0 / per_decade)
            counts = {int(i): c for i, c in entry.get("counts", {}).items()}
            cum = 0
            for i in range(entry["n"] - 1):   # overflow folds into +Inf
                bucket = counts.get(i, 0)
                if not bucket:
                    continue
                cum += bucket
                upper = lo if i == 0 else lo * growth ** i
                lines.append(
                    f'{pname}_bucket{{le="{_prom_num(upper)}"}} {cum}')
            lines.append(f'{pname}_bucket{{le="+Inf"}} {entry["count"]}')
            lines.append(f"{pname}_sum {_prom_num(entry.get('sum', 0.0))}")
            lines.append(f"{pname}_count {entry['count']}")
    return "\n".join(lines) + "\n" if lines else "# no metrics\n"


def chrome_trace(spans: Iterable[Span]) -> dict:
    """Chrome trace-event JSON (Perfetto / chrome://tracing / speedscope).

    Complete events (``ph="X"``) with µs timestamps; the process lane is
    the trace, the thread lane the emitting component, so one request's
    waterfall reads top-to-bottom across router → replica → engine."""
    events = []
    for span in spans:
        events.append({
            "name": span.name,
            "cat": span.status,
            "ph": "X",
            "ts": span.start * 1e6,
            "dur": max(span.duration, 0.0) * 1e6,
            "pid": span.trace_id,
            "tid": span.source or "-",
            "args": {**span.attrs, "span_id": span.span_id,
                     "parent_id": span.parent_id, "status": span.status},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def render_waterfall(spans: List[Span], width: int = 40) -> str:
    """One trace as an aligned text waterfall: parent-indented span tree
    (orphan parents — e.g. a hard-killed replica whose open spans died
    with it — root at depth 0) over a proportional timeline."""
    if not spans:
        return "(no spans)"
    by_id: Dict[str, Span] = {span.span_id: span for span in spans}

    def depth(span: Span) -> int:
        d, seen = 0, set()
        while span.parent_id in by_id and span.span_id not in seen:
            seen.add(span.span_id)
            span = by_id[span.parent_id]
            d += 1
        return d

    t0 = min(span.start for span in spans)
    t1 = max(span.end if span.end is not None else span.start
             for span in spans)
    total = max(t1 - t0, 1e-9)
    # Stable display order: parents before children, then by start time.
    ordered = sorted(spans, key=lambda span: (span.start, depth(span),
                                              span.span_id))
    label_w = max(len("  " * depth(span) + span.name) for span in ordered)
    lines = [f"trace {spans[0].trace_id}  "
             f"({len(spans)} spans, {total * 1e3:.1f} ms)"]
    for span in ordered:
        off = int((span.start - t0) / total * width)
        bar_w = max(1, int(max(span.duration, 0.0) / total * width))
        bar = " " * off + "▇" * min(bar_w, width - off)
        label = ("  " * depth(span) + span.name).ljust(label_w)
        extras = " ".join(
            f"{key}={value}" for key, value in sorted(span.attrs.items())
            if key in ("rid", "fid", "replica", "token_start", "token_end",
                       "exc_type", "error", "tenant", "task_id"))
        status = "" if span.status == "ok" else f" [{span.status}]"
        lines.append(
            f"{label}  |{bar.ljust(width)}| "
            f"{span.duration * 1e3:8.2f} ms  {span.source or '-'}"
            f"{status}{('  ' + extras) if extras else ''}")
    return "\n".join(lines)
