"""One observability plane for the whole stack (router → kernel hosts).

Two halves, threaded through every layer behind one tiny handle
(:class:`Obs` = tracer + metrics registry):

* **Distributed tracing** (``trace``): a trace minted at
  ``Router.submit`` rides the :data:`TRACE_HEADER` HTTP header into the
  replica and down into the engine's per-request state; spans land in a
  bounded ring and export durably through the storage ``Backend`` seam
  (``obs/spans/``), renderable as a terminal waterfall or Chrome-trace/
  Perfetto JSON.
* **Metrics registry** (``metrics``): counters, gauges, and
  deterministic log-bucketed histograms (mergeable across replicas by
  bucket-wise add) behind one :class:`MetricsRegistry` per component —
  the single name/type/export path for every number the layer publishes.

Overhead contract: layers accept ``obs=None`` and skip every recording
call when unset — the zero-overhead path. With obs on, recording is
host-side only (dispatch boundaries, never inside traced programs):
one ``perf_counter`` pair + histogram bump per fused step, one span per
request phase. ``bench.py obs`` holds the engine to ≤ 5% tok/s overhead.
"""

from dataclasses import dataclass

from tpu_task.obs.export import (
    METRICS_PREFIX,
    SPAN_PREFIX,
    SpanExporter,
    chrome_trace,
    export_metrics,
    read_metrics,
    read_spans,
    render_waterfall,
)
from tpu_task.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    merge_snapshots,
)
from tpu_task.obs.trace import TRACE_HEADER, Span, TraceContext, Tracer

__all__ = [
    "METRICS_PREFIX",
    "SPAN_PREFIX",
    "TRACE_HEADER",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Obs",
    "Span",
    "SpanExporter",
    "TraceContext",
    "Tracer",
    "chrome_trace",
    "export_metrics",
    "merge_snapshots",
    "read_metrics",
    "read_spans",
    "render_waterfall",
]


@dataclass
class Obs:
    """The handle a component threads through: one tracer (its spans) +
    one registry (its numbers). ``None`` everywhere means obs off —
    layers guard every recording site on it."""

    tracer: Tracer
    metrics: MetricsRegistry

    @classmethod
    def create(cls, source: str = "", capacity: int = 4096) -> "Obs":
        return cls(tracer=Tracer(source=source, capacity=capacity),
                   metrics=MetricsRegistry())
