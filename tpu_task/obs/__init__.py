"""One observability plane for the whole stack (router → kernel hosts).

Two halves, threaded through every layer behind one tiny handle
(:class:`Obs` = tracer + metrics registry):

* **Distributed tracing** (``trace``): a trace minted at
  ``Router.submit`` rides the :data:`TRACE_HEADER` HTTP header into the
  replica and down into the engine's per-request state; spans land in a
  bounded ring and export durably through the storage ``Backend`` seam
  (``obs/spans/``), renderable as a terminal waterfall or Chrome-trace/
  Perfetto JSON.
* **Metrics registry** (``metrics``): counters, gauges, and
  deterministic log-bucketed histograms (mergeable across replicas by
  bucket-wise add) behind one :class:`MetricsRegistry` per component —
  the single name/type/export path for every number the layer publishes.

On top of the substrate sits the OPERATIONS plane: declarative SLOs with
multi-window error-budget burn-rate alerting (``slo`` — durable breach
records under ``obs/alerts/``), goodput/MFU/dispatch-overhead accounting
(``goodput``), Prometheus text exposition (:func:`prometheus_text` — a
replica's ``GET /metrics``), and the ``tpu-task obs watch``/``alerts``
terminal views.

Overhead contract: layers accept ``obs=None`` and skip every recording
call when unset — the zero-overhead path. With obs on, recording is
host-side only (dispatch boundaries, never inside traced programs):
one ``perf_counter`` pair + histogram bump per fused step, one span per
request phase. ``bench.py obs`` holds the engine to ≤ 5% tok/s overhead.
"""

from dataclasses import dataclass

from tpu_task.obs.export import (
    METRICS_PREFIX,
    SPAN_PREFIX,
    SpanExporter,
    chrome_trace,
    export_metrics,
    prometheus_text,
    read_metrics,
    read_spans,
    render_waterfall,
)
from tpu_task.obs.goodput import GoodputMeter
from tpu_task.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    merge_snapshots,
)
from tpu_task.obs.sla import (
    DEFAULT_CLASS,
    SLA_HEADER,
    SLO_CLASSES,
    DegradeLadder,
    class_rank,
    format_sla_header,
    parse_sla_header,
)
from tpu_task.obs.slo import (
    ALERT_PREFIX,
    Alert,
    BurnWindow,
    SloClass,
    SloEvaluator,
    SloObjective,
    read_alerts,
    write_alert,
)
from tpu_task.obs.trace import TRACE_HEADER, Span, TraceContext, Tracer

__all__ = [
    "ALERT_PREFIX",
    "DEFAULT_CLASS",
    "METRICS_PREFIX",
    "SLA_HEADER",
    "SLO_CLASSES",
    "SPAN_PREFIX",
    "TRACE_HEADER",
    "Alert",
    "BurnWindow",
    "Counter",
    "DegradeLadder",
    "Gauge",
    "GoodputMeter",
    "Histogram",
    "MetricsRegistry",
    "Obs",
    "SloClass",
    "SloEvaluator",
    "SloObjective",
    "Span",
    "SpanExporter",
    "TraceContext",
    "Tracer",
    "chrome_trace",
    "class_rank",
    "export_metrics",
    "format_sla_header",
    "merge_snapshots",
    "parse_sla_header",
    "prometheus_text",
    "read_alerts",
    "read_metrics",
    "read_spans",
    "render_waterfall",
    "write_alert",
]


@dataclass
class Obs:
    """The handle a component threads through: one tracer (its spans) +
    one registry (its numbers). ``None`` everywhere means obs off —
    layers guard every recording site on it."""

    tracer: Tracer
    metrics: MetricsRegistry

    @classmethod
    def create(cls, source: str = "", capacity: int = 4096) -> "Obs":
        obs = cls(tracer=Tracer(source=source, capacity=capacity),
                  metrics=MetricsRegistry())
        # The tracer's drop-oldest ring is silent on its own — surface
        # overflow on the one export path so `obs top`/`obs watch` can
        # warn that waterfalls may be missing their oldest spans.
        obs.metrics.counter_fn(
            "obs.spans_dropped",
            lambda tracer=obs.tracer: float(tracer.dropped))
        return obs
