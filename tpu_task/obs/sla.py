"""SLA actuation primitives: SLO classes, deadlines/slack, and the
degrade ladder (the actuation half of the PR 12 measurement plane).

The measurement plane (``obs/slo.py``) answers "is the error budget on
fire"; this module is the shared vocabulary every layer ACTS with:

* **SLO classes** — ``premium`` > ``standard`` > ``best_effort``, a
  total protection order. :func:`class_rank` is the number everything
  keys on: victim selection prefers the LOWEST rank, the degrade ladder
  reaches the HIGHEST rank last.
* **Deadlines and slack** — a request carries an absolute deadline on
  its owner's clock; ``slack = deadline - now`` is the one quantity
  admission ordering (EDF), shed gates, and victim selection consume.
  Deadlines cross process boundaries as REMAINING milliseconds (the
  :data:`SLA_HEADER` dispatch header, next to the PR 11 trace header)
  because two processes share no clock.
* **The degrade ladder** (:class:`DegradeLadder`) — graceful brownout
  under overload, driven by the burn-rate evaluator's live alert state:
  each escalation applies to the least-protected class first, and a
  class's response escalates clamp → de-speculate → shed. Premium can
  never be shed by the ladder (the ladder caps below its shed rung);
  only an individually unmeetable deadline sheds a premium request.

Pure host Python, no jax/storage imports — the router, the serving
engine, and the gang scheduler all import this without layering
violations (same rule as the rest of ``tpu_task.obs``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

__all__ = [
    "DEFAULT_CLASS",
    "DegradeLadder",
    "MAX_RUNG",
    "RUNG_CLAMP",
    "RUNG_NOSPEC",
    "RUNG_SHED",
    "SLA_HEADER",
    "SLO_CLASSES",
    "class_rank",
    "format_sla_header",
    "parse_sla_header",
]

#: Dispatch-header twin of TRACE_HEADER: ``<class>;<remaining_ms>`` (the
#: ms part omitted for deadline-less requests). Remaining — not absolute
#: — because router and replica share no clock.
SLA_HEADER = "X-Tpu-Task-Sla"

#: Protection order, most protected first.
SLO_CLASSES = ("premium", "standard", "best_effort")

DEFAULT_CLASS = "standard"

_RANK = {"premium": 2, "standard": 1, "best_effort": 0}


def class_rank(slo_class: Optional[str]) -> int:
    """Protection rank: premium 2, standard 1, best_effort 0. Unknown
    class names rank as standard — a typo must not silently make a
    request first against the wall."""
    return _RANK.get(slo_class or DEFAULT_CLASS, _RANK[DEFAULT_CLASS])


def format_sla_header(slo_class: str,
                      remaining_ms: Optional[float] = None) -> str:
    if remaining_ms is None:
        return str(slo_class)
    return f"{slo_class};{remaining_ms:.1f}"


def parse_sla_header(value: Optional[str]) \
        -> Tuple[str, Optional[float]]:
    """``(slo_class, remaining_ms)`` — permissive: absent/garbled
    headers degrade to (standard, no deadline), never to a 4xx (the SLA
    plane is advisory metadata on top of a correct request)."""
    if not value:
        return DEFAULT_CLASS, None
    name, _, ms = value.partition(";")
    name = name.strip() or DEFAULT_CLASS
    if not ms.strip():
        return name, None
    try:
        return name, max(0.0, float(ms))
    except ValueError:
        return name, None


# -- the degrade ladder --------------------------------------------------------

#: A class's response escalates through these rungs of its EFFECTIVE
#: rung (``ladder.rung - class_rank``): first shorten answers, then stop
#: paying for speculation, and only then refuse work.
RUNG_CLAMP = 1      # clamp max_new_tokens
RUNG_NOSPEC = 2     # disable speculative decoding
RUNG_SHED = 3       # shed (structured terminal + Retry-After)

#: Ladder ceiling: best_effort (rank 0) reaches RUNG_SHED at ladder rung
#: 3 and standard at 4; premium (rank 2) tops out at RUNG_NOSPEC — the
#: ladder can brownout premium, never black it out.
MAX_RUNG = RUNG_SHED + 1


@dataclass
class DegradeLadder:
    """Alert-driven brownout state machine (deterministic, clockless:
    one :meth:`observe` per SLO evaluation beat).

    Escalates one rung after ``escalate_after`` consecutive alerting
    evaluations, de-escalates one rung after ``clear_after`` consecutive
    clear ones — asymmetric on purpose: entering brownout should be
    prompt, leaving it should be convinced. Per-class actuation comes
    from :meth:`plan`: the effective rung subtracts the class's
    protection rank, so best_effort walks every rung before standard
    starts and premium is always two rungs behind the front."""

    clamp_max_new: int = 16
    escalate_after: int = 1
    clear_after: int = 2
    rung: int = 0
    transitions: List[str] = field(default_factory=list, repr=False)
    _firing: int = field(default=0, repr=False)
    _clear: int = field(default=0, repr=False)

    def observe(self, alerting: bool) -> int:
        """One evaluation beat: ``alerting`` is the burn-rate
        evaluator's live state (any alert firing). Returns the rung."""
        if alerting:
            self._firing += 1
            self._clear = 0
            if self._firing >= self.escalate_after and self.rung < MAX_RUNG:
                self._firing = 0
                self.rung += 1
                self.transitions.append(f"up:{self.rung}")
        else:
            self._clear += 1
            self._firing = 0
            if self._clear >= self.clear_after and self.rung > 0:
                self._clear = 0
                self.rung -= 1
                self.transitions.append(f"down:{self.rung}")
        return self.rung

    def effective_rung(self, slo_class: str) -> int:
        return max(0, self.rung - class_rank(slo_class))

    def plan(self, slo_class: str, max_new_tokens: int) -> dict:
        """What the ladder does to ONE request of this class right now:
        ``{shed, no_spec, max_new}`` (``max_new`` already clamped;
        clamping never raises a request's own budget)."""
        rung = self.effective_rung(slo_class)
        return {
            "shed": rung >= RUNG_SHED,
            "no_spec": rung >= RUNG_NOSPEC,
            "max_new": min(max_new_tokens, self.clamp_max_new)
            if rung >= RUNG_CLAMP else max_new_tokens,
        }
