"""Hermetic worker agent: emulates a TPU-VM worker as a local subprocess.

This is the "subprocess VM" of the hermetic end-to-end slice (SURVEY.md §7):
it reproduces the on-VM agent's observable behavior — restore workdir from
the bucket, run the task script under supervision with a hard timeout, sync
logs every log-period and data every data-period, write the exit status JSON
report, final-sync, and (on worker 0) touch the self-destruct marker — using
a local directory as the bucket, so the full lifecycle is testable with zero
cloud credentials, exactly what the reference never had (SURVEY.md §4).

Behavioral contract mirrored from
/root/reference/task/common/machine/machine-script.sh.tpl:
  * status report JSON: {"result", "code", "status"} (tpl:51)
  * report blob names: reports/task-{machine}, reports/status-{machine} (tpl:110)
  * data restore before start (tpl:89); mtime-gated data sync loop (tpl:118-124)
  * timeout → result "timeout", no exit code (tpl:56 RuntimeMaxSec semantics)

Run: python -m tpu_task.machine.local_agent --remote DIR --directory DIR \
         --script FILE [--timeout EPOCH] [--machine-id ID] ...
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
import uuid
from datetime import datetime, timezone

from tpu_task.storage import sync as storage_sync
from tpu_task.storage import transfer as storage_transfer


def _iso_now() -> str:
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


class Agent:
    def __init__(self, remote: str, directory: str, script_path: str,
                 machine_id: str, timeout_epoch: float,
                 log_period: float, data_period: float, worker_id: int = 0):
        self.remote = remote
        self.directory = directory
        self.script_path = script_path
        self.machine_id = machine_id
        self.timeout_epoch = timeout_epoch
        self.log_period = log_period
        self.data_period = data_period
        self.worker_id = worker_id
        self.log_lines: list[str] = []
        self._log_lock = threading.Lock()
        self._done = threading.Event()

    # -- sync loops ----------------------------------------------------------
    def _reports_dir(self) -> str:
        return os.path.join(self.remote, "reports")

    def _write_report(self, prefix: str, content: str) -> None:
        os.makedirs(self._reports_dir(), exist_ok=True)
        path = os.path.join(self._reports_dir(), f"{prefix}-{self.machine_id}")
        with open(path, "w") as handle:
            handle.write(content)

    def _sync_logs(self) -> None:
        with self._log_lock:
            content = "".join(self.log_lines)
        self._write_report("task", content)

    def _log_loop(self) -> None:
        last = None
        while not self._done.wait(self.log_period):
            with self._log_lock:
                current = len(self.log_lines)
            if current != last:
                last = current
                self._sync_logs()

    def _data_loop(self) -> None:
        if self.worker_id != 0:
            return
        last_epoch = None
        while not self._done.wait(self.data_period):
            epoch = self._data_epoch()
            if epoch != last_epoch:
                last_epoch = epoch
                try:
                    storage_sync(self.directory, os.path.join(self.remote, "data"))
                except Exception as error:  # keep looping like the shell loop
                    self._append_log(f"data sync error: {error}\n")

    def _data_epoch(self) -> float:
        newest = 0.0
        for dirpath, _dirnames, filenames in os.walk(self.directory):
            for name in filenames:
                try:
                    newest = max(newest, os.path.getmtime(os.path.join(dirpath, name)))
                except OSError:
                    pass
        return newest

    def _append_log(self, line: str) -> None:
        with self._log_lock:
            self.log_lines.append(f"{_iso_now()} {line}")

    # -- lifecycle -----------------------------------------------------------
    def run(self) -> int:
        os.makedirs(self.directory, exist_ok=True)
        data_remote = os.path.join(self.remote, "data")
        if os.path.isdir(data_remote):
            storage_transfer(data_remote, self.directory)

        env = dict(os.environ)
        # The agent itself runs with accelerator bootstrap hooks scrubbed
        # (it must not grab a TPU); the user task gets them back.
        from tpu_task.backends.local.control_plane import restore_accelerator_env

        restore_accelerator_env(env)
        env["TPU_WORKER_ID"] = str(self.worker_id)
        env["TPU_TASK_MACHINE_IDENTITY"] = self.machine_id
        if env.get("TPU_TASK_CLOUD_PROVIDER") == "k8s":
            # Mirror the rank under the k8s-native name so scripts written
            # for real indexed Jobs (resource_job.go:135-140) run unchanged
            # on the hermetic plane.
            env["JOB_COMPLETION_INDEX"] = str(self.worker_id)

        remaining = None
        if self.timeout_epoch > 0:
            remaining = self.timeout_epoch - time.time()
            if remaining < 1:
                remaining = 1

        process = subprocess.Popen(
            ["/bin/bash", self.script_path],
            cwd=self.directory, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True,
        )

        threads = [
            threading.Thread(target=self._log_loop, daemon=True),
            threading.Thread(target=self._data_loop, daemon=True),
        ]
        for thread in threads:
            thread.start()

        reader = threading.Thread(target=self._read_output, args=(process,), daemon=True)
        reader.start()

        timed_out = False
        try:
            process.wait(timeout=remaining)
        except subprocess.TimeoutExpired:
            timed_out = True
            try:
                os.killpg(process.pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
            try:
                process.wait(timeout=10)
            except subprocess.TimeoutExpired:
                os.killpg(process.pid, signal.SIGKILL)
                process.wait()

        reader.join(timeout=5)
        self._done.set()
        for thread in threads:
            thread.join(timeout=5)

        # Status report (tpl:51): timeout has result "timeout" and no code.
        if timed_out:
            report = {"result": "timeout", "code": "", "status": ""}
        else:
            code = process.returncode
            report = {
                "result": "exit-code" if code else "success",
                "code": str(code),
                "status": str(code),
            }
        # Final data sync BEFORE the status report: the report is what makes
        # clients observe a terminal status, and delete→pull may follow it
        # immediately — data uploaded after it would be lost to the pull.
        if self.worker_id == 0:
            try:
                storage_sync(self.directory, data_remote)
            except Exception as error:
                self._append_log(f"final data sync error: {error}\n")
        self._sync_logs()
        self._write_report("status", json.dumps(report))
        if self.worker_id == 0:
            # Self-destruct signal: the control plane scales the group to zero
            # when it sees this marker (the hermetic `leo stop` equivalent).
            with open(os.path.join(self.remote, "shutdown"), "w") as handle:
                handle.write(self.machine_id)
        return process.returncode or 0

    def _read_output(self, process: subprocess.Popen) -> None:
        assert process.stdout is not None
        for raw in process.stdout:
            self._append_log(raw.decode(errors="replace"))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--remote", required=True, help="bucket directory")
    parser.add_argument("--directory", required=True, help="task working directory")
    parser.add_argument("--script", required=True, help="task script path")
    parser.add_argument("--machine-id", default="")
    parser.add_argument("--timeout", type=float, default=0.0, help="absolute epoch")
    parser.add_argument("--log-period", type=float, default=5.0)
    parser.add_argument("--data-period", type=float, default=10.0)
    parser.add_argument("--worker-id", type=int, default=0)
    args = parser.parse_args(argv)

    machine_id = args.machine_id or f"{uuid.uuid4()}-worker{args.worker_id}"
    agent = Agent(
        remote=args.remote, directory=args.directory, script_path=args.script,
        machine_id=machine_id, timeout_epoch=args.timeout,
        log_period=args.log_period, data_period=args.data_period,
        worker_id=args.worker_id,
    )
    return agent.run()


if __name__ == "__main__":
    sys.exit(main())
