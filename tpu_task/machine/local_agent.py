"""Hermetic worker agent: emulates a TPU-VM worker as a local subprocess.

This is the "subprocess VM" of the hermetic end-to-end slice (SURVEY.md §7):
it reproduces the on-VM agent's observable behavior — restore workdir from
the bucket, run the task script under supervision with a hard timeout, sync
logs every log-period and data every data-period, write the exit status JSON
report, final-sync, and (on worker 0) touch the self-destruct marker — using
a local directory as the bucket, so the full lifecycle is testable with zero
cloud credentials, exactly what the reference never had (SURVEY.md §4).

Behavioral contract mirrored from
/root/reference/task/common/machine/machine-script.sh.tpl:
  * status report JSON: {"result", "code", "status"} (tpl:51)
  * report blob names: reports/task-{machine}, reports/status-{machine} (tpl:110)
  * data restore before start (tpl:89); mtime-gated data sync loop (tpl:118-124)
  * timeout → result "timeout", no exit code (tpl:56 RuntimeMaxSec semantics)

Run: python -m tpu_task.machine.local_agent --remote DIR --directory DIR \
         --script FILE [--timeout EPOCH] [--machine-id ID] ...
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
import uuid
from datetime import datetime, timezone

from tpu_task.storage import sync as storage_sync
from tpu_task.storage import transfer as storage_transfer


def _iso_now() -> str:
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def _shard_sync_rules(worker_id: int) -> list:
    """Per-worker shard filter rules, same as tpu-worker-script.sh.tpl:
    worker 0 mirrors everything but other workers' checkpoint shard files
    (its sync must never delete shards only worker N uploaded); worker N
    mirrors ONLY its own shard files."""
    if worker_id == 0:
        return ["+ **ckpt-*.shard-0.*", "- **ckpt-*.shard-*"]
    return [f"+ **ckpt-*.shard-{worker_id}.*", "- **"]


class Agent:
    def __init__(self, remote: str, directory: str, script_path: str,
                 machine_id: str, timeout_epoch: float,
                 log_period: float, data_period: float, worker_id: int = 0,
                 checkpoint_dir: str = "checkpoints",
                 heartbeat_period: float = 30.0, node_name: str = ""):
        self.remote = remote
        self.directory = directory
        self.script_path = script_path
        self.machine_id = machine_id
        self.timeout_epoch = timeout_epoch
        self.log_period = log_period
        self.data_period = data_period
        self.worker_id = worker_id
        self.checkpoint_dir = checkpoint_dir
        self.heartbeat_period = heartbeat_period
        self.node_name = node_name
        self.log_lines: list[str] = []
        self._log_lock = threading.Lock()
        self._log_synced = 0  # bytes of log already durable in the blob
        self._done = threading.Event()
        # SIGTERM = preemption notice: stop the child, final-sync, report.
        self._preempted = threading.Event()

    # -- sync loops ----------------------------------------------------------
    def _reports_dir(self) -> str:
        return os.path.join(self.remote, "reports")

    def _write_report(self, prefix: str, content: str) -> None:
        os.makedirs(self._reports_dir(), exist_ok=True)
        path = os.path.join(self._reports_dir(), f"{prefix}-{self.machine_id}")
        with open(path, "w") as handle:
            handle.write(content)

    def _sync_logs(self) -> None:
        """Ship the task log blob. The log only ever grows, so when the
        durable blob still holds exactly the prefix we last shipped, only
        the delta is appended — a tick's upload cost is O(new output), not
        O(log so far) (the reader side tails the same way via ranged
        reads). Any size mismatch (fresh blob, out-of-band rewrite) falls
        back to a full rewrite."""
        with self._log_lock:
            content = "".join(self.log_lines)
        data = content.encode()
        os.makedirs(self._reports_dir(), exist_ok=True)
        path = os.path.join(self._reports_dir(), f"task-{self.machine_id}")
        try:
            durable = os.path.getsize(path)
        except OSError:
            durable = -1
        if durable == self._log_synced and 0 <= durable <= len(data):
            if durable < len(data):
                with open(path, "ab") as handle:
                    handle.write(data[durable:])
        else:
            with open(path, "wb") as handle:
                handle.write(data)
        self._log_synced = len(data)

    def _log_loop(self) -> None:
        last = None
        while not self._done.wait(self.log_period):
            with self._log_lock:
                current = len(self.log_lines)
            if current != last:
                try:
                    self._sync_logs()
                except Exception as error:  # transient like _data_loop: one
                    # failed tick must not kill log streaming for the run
                    self._append_log(f"log sync error: {error}\n")
                    continue  # `last` unchanged → retried next tick
                last = current

    # -- liveness heartbeats ---------------------------------------------------
    def _write_heartbeat(self, final: bool = False) -> None:
        """``reports/heartbeat-{machine}``: the liveness contract. The
        orchestrator's reconciler treats a stale heartbeat on an ACTIVE
        slice as preemption-equivalent; ``final`` marks a clean agent exit
        so a finished worker is never mistaken for a hung one."""
        self._write_report("heartbeat", json.dumps({
            "time": _iso_now(),
            "machine": self.machine_id,
            "worker": self.worker_id,
            "node": self.node_name,
            "final": final,
        }))

    def _heartbeat_loop(self) -> None:
        while not self._done.wait(self.heartbeat_period):
            try:
                self._write_heartbeat()
            except Exception as error:  # flaky bucket ≠ dead worker
                self._append_log(f"heartbeat error: {error}\n")

    def _data_loop(self) -> None:
        last_epoch = None
        while not self._done.wait(self.data_period):
            epoch = self._data_epoch()
            if epoch != last_epoch:
                last_epoch = epoch
                try:
                    self._sync_data(epoch)
                except Exception as error:  # keep looping like the shell loop
                    self._append_log(f"data sync error: {error}\n")

    def _sync_data(self, epoch: float = None) -> None:
        """One data tick. Worker 0 mirrors the whole workdir; workers N≠0
        mirror only their own checkpoint shard files (the multi-host sharded
        contract — tpu-worker-script.sh.tpl:143-150). Checkpoint-priority:
        worker 0 syncs the checkpoint directory FIRST, so checkpoints become
        durable before the rest of the workdir streams, and the size+mtime
        diff skips files an AsyncCheckpointer direct-upload already pushed
        (it preserves source mtimes) instead of re-uploading them."""
        data_remote = os.path.join(self.remote, "data")
        rules = _shard_sync_rules(self.worker_id)
        if self.worker_id != 0:
            # ``epoch`` is the loop's already-computed shard mtime scan —
            # don't re-walk the workdir for the same answer; only the
            # final-sync call path (no epoch) scans here.
            if epoch is None:
                epoch = self._shard_epoch()
            if epoch > 0.0:
                storage_sync(self.directory, data_remote, exclude=rules)
            return
        ckpt_local = os.path.join(self.directory, self.checkpoint_dir)
        if os.path.isdir(ckpt_local):
            storage_sync(
                ckpt_local,
                os.path.join(data_remote, self.checkpoint_dir),
                exclude=rules)
        storage_sync(self.directory, data_remote, exclude=rules)

    def _data_epoch(self) -> float:
        if self.worker_id != 0:
            return self._shard_epoch()
        newest = 0.0
        for dirpath, _dirnames, filenames in os.walk(self.directory):
            for name in filenames:
                try:
                    newest = max(newest, os.path.getmtime(os.path.join(dirpath, name)))
                except OSError:
                    pass
        return newest

    def _shard_epoch(self) -> float:
        """Newest mtime among THIS worker's checkpoint shard files (0.0 when
        none exist — workers N≠0 sync nothing else, so no shards means no
        sync and no spurious ``data/`` creation in the bucket)."""
        import fnmatch

        pattern = f"ckpt-*.shard-{self.worker_id}.*"
        newest = 0.0
        for dirpath, _dirnames, filenames in os.walk(self.directory):
            for name in filenames:
                if not fnmatch.fnmatch(name, pattern):
                    continue
                try:
                    newest = max(newest, os.path.getmtime(os.path.join(dirpath, name)))
                except OSError:
                    pass
        return newest

    def _append_log(self, line: str) -> None:
        with self._log_lock:
            self.log_lines.append(f"{_iso_now()} {line}")

    # -- lifecycle -----------------------------------------------------------
    def run(self) -> int:
        os.makedirs(self.directory, exist_ok=True)
        data_remote = os.path.join(self.remote, "data")
        if os.path.isdir(data_remote):
            storage_transfer(data_remote, self.directory)

        env = dict(os.environ)
        # The agent itself runs with accelerator bootstrap hooks scrubbed
        # (it must not grab a TPU); the user task gets them back.
        from tpu_task.backends.local.control_plane import restore_accelerator_env

        restore_accelerator_env(env)
        env["TPU_WORKER_ID"] = str(self.worker_id)
        env["TPU_TASK_MACHINE_IDENTITY"] = self.machine_id
        # The bucket prefix the workdir mirrors to: lets user scripts stream
        # checkpoints straight into the bucket off the sync tick
        # (AsyncCheckpointer(upload_remote="auto")) instead of waiting for
        # the next data-period sweep.
        env["TPU_TASK_DATA_REMOTE"] = data_remote
        if self.node_name:
            # Stable per-slice identity (survives requeues): scripts key
            # per-slice state (checkpoints) on it in multi-slice tasks.
            env["TPU_TASK_NODE"] = self.node_name
        if env.get("TPU_TASK_CLOUD_PROVIDER") == "k8s":
            # Mirror the rank under the k8s-native name so scripts written
            # for real indexed Jobs (resource_job.go:135-140) run unchanged
            # on the hermetic plane.
            env["JOB_COMPLETION_INDEX"] = str(self.worker_id)

        remaining = None
        if self.timeout_epoch > 0:
            remaining = self.timeout_epoch - time.time()
            if remaining < 1:
                remaining = 1

        process = subprocess.Popen(
            ["/bin/bash", self.script_path],
            cwd=self.directory, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True,
        )

        self._install_preemption_handler(process)
        try:
            self._write_heartbeat()  # liveness baseline before the first tick
        except Exception as error:
            self._append_log(f"heartbeat error: {error}\n")

        threads = [
            threading.Thread(target=self._log_loop, daemon=True),
            threading.Thread(target=self._data_loop, daemon=True),
            threading.Thread(target=self._heartbeat_loop, daemon=True),
        ]
        for thread in threads:
            thread.start()

        reader = threading.Thread(target=self._read_output, args=(process,), daemon=True)
        reader.start()

        timed_out = False
        try:
            process.wait(timeout=remaining)
        except subprocess.TimeoutExpired:
            timed_out = True
            try:
                os.killpg(process.pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
            try:
                process.wait(timeout=10)
            except subprocess.TimeoutExpired:
                os.killpg(process.pid, signal.SIGKILL)
                process.wait()

        reader.join(timeout=5)
        self._done.set()
        for thread in threads:
            thread.join(timeout=5)

        # Status report (tpl:51): timeout has result "timeout" and no code.
        # A preempted worker reports result "preempted" (no code): status
        # folding counts neither success nor failure — the reconciler owns
        # the slice's fate, and the report preserves the last state.
        if self._preempted.is_set():
            report = {"result": "preempted", "code": "", "status": ""}
        elif timed_out:
            report = {"result": "timeout", "code": "", "status": ""}
        else:
            code = process.returncode
            report = {
                "result": "exit-code" if code else "success",
                "code": str(code),
                "status": str(code),
            }
        # Final data sync BEFORE the status report: the report is what makes
        # clients observe a terminal status, and delete→pull may follow it
        # immediately — data uploaded after it would be lost to the pull.
        # All workers run it: worker 0 mirrors the workdir, workers N≠0 ship
        # their own checkpoint shards (no-op when they wrote none).
        try:
            self._sync_data()
        except Exception as error:
            self._append_log(f"final data sync error: {error}\n")
        self._sync_logs()
        self._write_report("status", json.dumps(report))
        try:
            # Final heartbeat: a cleanly-exited (or preempted-with-grace)
            # worker must never read as hung to the liveness reconciler.
            self._write_heartbeat(final=True)
        except Exception as error:
            self._append_log(f"heartbeat error: {error}\n")
        if self.worker_id == 0 and not self._preempted.is_set():
            # Self-destruct signal: the control plane scales the group to zero
            # when it sees this marker (the hermetic `leo stop` equivalent).
            # NOT on preemption — a preempted slice must be requeued, not
            # torn down.
            with open(os.path.join(self.remote, "shutdown"), "w") as handle:
                handle.write(self.machine_id)
        return process.returncode or 0

    def _install_preemption_handler(self, process: subprocess.Popen) -> None:
        """SIGTERM = preemption notice (the shape every cloud's reclaim
        warning takes): stop the child with the same TERM→grace→KILL ladder
        the timeout path uses, then let the normal terminal path run its
        final data/log sync and status report, so a preempted worker's last
        state still lands in the bucket."""

        def on_sigterm(_signum, _frame):
            if process.poll() is not None:
                # The task already finished — the terminal path is running
                # and must report the child's REAL result; a teardown
                # notice arriving now is not a preemption of the task.
                return
            if self._preempted.is_set():
                return
            self._preempted.set()
            self._append_log("preemption notice (SIGTERM): stopping task\n")
            try:
                os.killpg(process.pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
            grace = float(os.environ.get("TPU_TASK_PREEMPT_GRACE", "10"))

            def escalate():
                if process.poll() is None:
                    try:
                        os.killpg(process.pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass

            timer = threading.Timer(grace, escalate)
            timer.daemon = True
            timer.start()

        try:
            signal.signal(signal.SIGTERM, on_sigterm)
        except ValueError:
            pass  # not the main thread (in-process test harness)

    def _read_output(self, process: subprocess.Popen) -> None:
        assert process.stdout is not None
        for raw in process.stdout:
            self._append_log(raw.decode(errors="replace"))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--remote", required=True, help="bucket directory")
    parser.add_argument("--directory", required=True, help="task working directory")
    parser.add_argument("--script", required=True, help="task script path")
    parser.add_argument("--machine-id", default="")
    parser.add_argument("--timeout", type=float, default=0.0, help="absolute epoch")
    parser.add_argument("--log-period", type=float, default=5.0)
    parser.add_argument("--data-period", type=float, default=10.0)
    parser.add_argument("--worker-id", type=int, default=0)
    parser.add_argument("--checkpoint-dir", default="checkpoints",
                        help="workdir-relative checkpoint directory that gets"
                             " priority (first) in each data sync tick")
    parser.add_argument("--heartbeat-period", type=float, default=30.0,
                        help="liveness heartbeat write period (seconds)")
    parser.add_argument("--node-name", default="",
                        help="stable slice identity (queued-resource name); "
                             "exported to the task as TPU_TASK_NODE and "
                             "stamped into heartbeats")
    args = parser.parse_args(argv)

    machine_id = args.machine_id or f"{uuid.uuid4()}-worker{args.worker_id}"
    agent = Agent(
        remote=args.remote, directory=args.directory, script_path=args.script,
        machine_id=machine_id, timeout_epoch=args.timeout,
        log_period=args.log_period, data_period=args.data_period,
        worker_id=args.worker_id, checkpoint_dir=args.checkpoint_dir,
        heartbeat_period=args.heartbeat_period, node_name=args.node_name,
    )
    return agent.run()


if __name__ == "__main__":
    sys.exit(main())
