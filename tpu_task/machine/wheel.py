"""Agent wheel: build once, stage into the task bucket, install on workers.

The reference ships `leo` as a static Go binary the bootstrap downloads
(machine-script.sh.tpl:59-87); the tpu-task equivalent is a pure-Python wheel
built from this checkout, staged under ``agent/`` in the task's bucket, and
installed by the worker bootstrap with a metadata-server token — so a real
TPU-VM bootstrap never depends on the package existing on a package index.
"""

from __future__ import annotations

import glob
import logging
import os
import subprocess
import sys
from typing import Optional

logger = logging.getLogger("tpu_task")

AGENT_PREFIX = "agent"  # bucket subdirectory for the staged wheel


def _repo_root() -> Optional[str]:
    """The checkout containing pyproject.toml, if we're running from one."""
    package_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(package_dir)
    if os.path.exists(os.path.join(root, "pyproject.toml")):
        return root
    return None


def _cache_dir() -> str:
    return os.path.join(os.path.expanduser("~/.tpu-task"), "wheels")


def _newest_source_mtime(root: str) -> float:
    newest = os.path.getmtime(os.path.join(root, "pyproject.toml"))
    for dirpath, dirnames, filenames in os.walk(os.path.join(root, "tpu_task")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if name.endswith((".py", ".tpl", ".cpp")):
                try:
                    newest = max(newest,
                                 os.path.getmtime(os.path.join(dirpath, name)))
                except OSError:
                    pass
    return newest


def ensure_wheel() -> Optional[str]:
    """Build (or reuse) the tpu-task wheel; None when not buildable here
    (e.g. running from an installed package — the bootstrap then falls back
    to the package index). The cache is invalidated against source mtimes so
    agent fixes actually reach workers instead of staging a stale build."""
    root = _repo_root()
    cached = sorted(glob.glob(os.path.join(_cache_dir(), "tpu_task-*.whl")))
    if cached and (root is None
                   or os.path.getmtime(cached[-1]) >= _newest_source_mtime(root)):
        return cached[-1]
    if root is None:
        return None
    for stale in cached:
        try:
            os.remove(stale)
        except OSError:
            pass
    os.makedirs(_cache_dir(), exist_ok=True)
    try:
        subprocess.run(
            [sys.executable, "-m", "pip", "wheel", "--no-deps",
             "--no-build-isolation", "--quiet", "-w", _cache_dir(), root],
            check=True, capture_output=True, text=True, timeout=300)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as error:
        output = getattr(error, "stderr", "") or str(error)
        logger.warning("agent wheel build failed (%s); workers will fall "
                       "back to the package index", output.strip()[-200:])
        return None
    built = sorted(glob.glob(os.path.join(_cache_dir(), "tpu_task-*.whl")))
    return built[-1] if built else None


def stage_wheel(remote: str) -> str:
    """Upload the agent wheel to ``{remote}/agent/``; returns the staged
    object's authenticated media URL ('' if unavailable)."""
    import posixpath
    import urllib.parse

    from tpu_task.storage.backends import BACKEND_GCS, open_backend

    wheel = ensure_wheel()
    if wheel is None:
        return ""
    basename = os.path.basename(wheel)
    backend, conn = open_backend(remote)
    key = posixpath.join(AGENT_PREFIX, basename)
    backend.write_from_file(key, wheel)
    if conn.backend != BACKEND_GCS:
        return ""  # local/fake remotes don't run the real bootstrap
    object_name = posixpath.join(conn.path.strip("/"), key) \
        if conn.path.strip("/") else key
    return (f"https://storage.googleapis.com/storage/v1/b/{conn.container}/o/"
            f"{urllib.parse.quote(object_name, safe='')}?alt=media")
