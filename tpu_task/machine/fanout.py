"""Multi-host fan-out: run a command on every TPU-VM worker of a slice.

The reference only ever SSHes into a single machine (task/common/ssh/
connection.go:10 — one-shot exec); a TPU slice is 1..N worker hosts that all
need bootstrap, debugging, and log collection. This module executes a command
on all workers concurrently (thread pool; the work is network-bound) and
returns per-worker results.

Transports:

* ``SSHTransport`` — the real path: the system ``ssh`` binary with the
  task's deterministic private key. Host-key checking is disabled, the same
  documented trade-off as the reference (connection.go:22-23 FIXME).
* ``LocalTransport`` — hermetic path: "workers" are local directories (the
  fake control plane's per-worker workdirs); exec is a local subprocess.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
import threading
import weakref
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import List, Optional, Protocol, Sequence


@dataclass
class ExecResult:
    worker_id: int
    address: str
    returncode: int
    stdout: str
    stderr: str

    @property
    def ok(self) -> bool:
        return self.returncode == 0


class Transport(Protocol):
    def run(self, address: str, command: str, timeout: float) -> tuple: ...


def _unlink_quietly(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


class SSHTransport:
    """Remote exec over the system ssh binary with an in-memory private key.

    The key is materialized to a 0600 temp file ONCE per transport instance
    and reused across the whole fan-out (a 32-worker slice does 1 key write,
    not 32), removed on :meth:`close` or garbage collection."""

    def __init__(self, private_key_pem: str, username: str = "ubuntu",
                 connect_timeout: int = 10):
        self.private_key_pem = private_key_pem
        self.username = username
        self.connect_timeout = connect_timeout
        self._key_path: Optional[str] = None
        self._key_lock = threading.Lock()
        self._finalizer = None

    def _ensure_key(self) -> str:
        """Write the key file on first use; thread-safe — fan_out calls
        ``run`` from a pool, and all workers must share one file."""
        with self._key_lock:
            if self._key_path is None or not os.path.exists(self._key_path):
                fd, key_path = tempfile.mkstemp(prefix="tpu-task-key-")
                with os.fdopen(fd, "w") as handle:  # mkstemp opens 0600
                    handle.write(self.private_key_pem)
                self._key_path = key_path
                self._finalizer = weakref.finalize(
                    self, _unlink_quietly, key_path)
            return self._key_path

    def close(self) -> None:
        """Remove the materialized key file (idempotent; a later ``run``
        re-materializes it)."""
        with self._key_lock:
            if self._finalizer is not None:
                self._finalizer()
                self._finalizer = None
            self._key_path = None

    def run(self, address: str, command: str, timeout: float) -> tuple:
        proc = subprocess.run(
            [
                "ssh",
                "-i", self._ensure_key(),
                "-o", "StrictHostKeyChecking=no",
                "-o", "UserKnownHostsFile=/dev/null",
                "-o", f"ConnectTimeout={self.connect_timeout}",
                "-o", "BatchMode=yes",
                f"{self.username}@{address}",
                command,
            ],
            capture_output=True, text=True, timeout=timeout,
        )
        return proc.returncode, proc.stdout, proc.stderr


class LocalTransport:
    """Hermetic exec: the address is a working directory on this machine."""

    def __init__(self, env: Optional[dict] = None):
        self.env = env

    def run(self, address: str, command: str, timeout: float) -> tuple:
        env = dict(os.environ)
        if self.env:
            env.update(self.env)
        proc = subprocess.run(
            ["/bin/bash", "-c", command],
            cwd=address, capture_output=True, text=True, timeout=timeout,
            env=env,
        )
        return proc.returncode, proc.stdout, proc.stderr


def fan_out(
    addresses: Sequence[str],
    command: str,
    transport: Transport,
    timeout: float = 60.0,
    max_parallel: int = 32,
) -> List[ExecResult]:
    """Run ``command`` on every worker concurrently; results by worker index."""

    def one(item) -> ExecResult:
        index, address = item
        try:
            returncode, stdout, stderr = transport.run(address, command, timeout)
        except subprocess.TimeoutExpired:
            return ExecResult(index, address, 124, "", f"timeout after {timeout}s")
        except OSError as error:
            return ExecResult(index, address, 255, "", str(error))
        return ExecResult(index, address, returncode, stdout, stderr)

    if not addresses:
        return []
    with ThreadPoolExecutor(max_workers=min(max_parallel, len(addresses))) as pool:
        return list(pool.map(one, enumerate(addresses)))
