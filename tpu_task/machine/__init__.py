from tpu_task.machine.script import render_script

__all__ = ["render_script"]
