"""Worker bootstrap-script renderer.

Parity with /root/reference/task/common/machine/script.go:22-60: embed the
user task script (base64), environment variables, credentials exports, and an
absolute timeout epoch into the worker bootstrap template. The template itself
is the TPU-VM replacement for the reference's cloud-init payload (see
templates/tpu-worker-script.sh.tpl).
"""

from __future__ import annotations

import base64
import os
import shlex
from datetime import datetime
from typing import Dict, Optional

from tpu_task.common.values import Variables

_TEMPLATE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "templates", "tpu-worker-script.sh.tpl"
)


def render_script(
    script: str,
    credentials: Dict[str, str],
    variables: Variables,
    timeout: Optional[datetime],
    agent_wheel_url: str = "",
) -> str:
    """Render the worker bootstrap script (machine.Script equivalent).

    ``agent_wheel_url`` is the staged agent wheel's authenticated media URL
    (empty: the bootstrap falls back to the package index)."""
    timeout_string = "infinity" if timeout is None else str(int(timeout.timestamp()))

    environment = ""
    for name, value in variables.enrich().items():
        escaped = value.replace('"', '\\"')
        environment += f'{name}="{escaped}"\n'

    export_credentials = ""
    for name, value in credentials.items():
        export_credentials += "export " + shlex.quote(f"{name}={value}") + "\n"

    with open(_TEMPLATE_PATH) as handle:
        template = handle.read()

    return (
        template
        .replace("@TASK_SCRIPT@", base64.b64encode(script.encode()).decode())
        .replace("@VARIABLES@", base64.b64encode(environment.encode()).decode())
        .replace("@CREDENTIALS@", base64.b64encode(export_credentials.encode()).decode())
        .replace("@TIMEOUT@", timeout_string)
        .replace("@AGENT_WHEEL_URL@", agent_wheel_url)
    )
