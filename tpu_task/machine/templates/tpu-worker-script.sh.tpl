#!/bin/bash
# tpu-task worker bootstrap — runs as the startup script on every TPU-VM
# worker of a slice. Semantics mirror the reference on-VM agent
# (/root/reference/task/common/machine/machine-script.sh.tpl): install the
# task as a supervised systemd unit with a hard runtime countdown, restore
# the workdir from the bucket, stream logs/status back, self-destruct at
# exit — with TPU-first replacements: jax[tpu] instead of NVIDIA drivers,
# the TPU metadata server for worker identity, and the tpu-task data-plane
# CLI instead of rclone.

sudo mkdir --parents /opt/task/directory
chmod u=rwx,g=rwx,o=rwx /opt/task/directory

base64 --decode << END | sudo tee /usr/bin/tpu-task-script > /dev/null
@TASK_SCRIPT@
END
chmod u=rwx,g=rx,o=rx /usr/bin/tpu-task-script

sudo tee /usr/bin/tpu-task-shutdown << 'END' > /dev/null
#!/bin/bash
# Grace period, then wait for in-flight transfers to drain before the
# self-destruct call scales this slice to zero.
sleep 20; while pgrep -f "tpu-task storage" > /dev/null; do sleep 1; done
source /opt/task/credentials
if test "${TPU_WORKER_ID:-0}" != "0"; then exit 0; fi
(systemctl is-system-running | grep stopping) || tpu-task stop --cloud="$TPU_TASK_CLOUD_PROVIDER" --region="$TPU_TASK_CLOUD_REGION" "$TPU_TASK_IDENTIFIER"
END
chmod u=rwx,g=rx,o=rx /usr/bin/tpu-task-shutdown

base64 --decode << END | sudo tee /opt/task/variables > /dev/null
@VARIABLES@
END
base64 --decode << END | sudo tee /opt/task/credentials > /dev/null
@CREDENTIALS@
END
chmod u=rw,g=,o= /opt/task/variables
chmod u=rw,g=,o= /opt/task/credentials

source /opt/task/credentials

# TPU worker identity from the metadata server: rank + slice topology, so the
# user script can call jax.distributed.initialize() without any extra wiring.
TPU_METADATA="http://metadata.google.internal/computeMetadata/v1/instance/attributes"
export TPU_WORKER_ID="$(curl --silent --header 'Metadata-Flavor: Google' $TPU_METADATA/agent-worker-number || echo 0)"
export TPU_WORKER_HOSTNAMES="$(curl --silent --header 'Metadata-Flavor: Google' $TPU_METADATA/worker-network-endpoints | tr ',' '\n' | cut -d: -f3 | paste -sd, - || true)"
# Stable slice identity (the queued-resource name; survives requeues):
# stamped into liveness heartbeats and exported to the task script.
export TPU_TASK_NODE="$(curl --silent --header 'Metadata-Flavor: Google' $TPU_METADATA/tpu-task-node || echo unknown)"
export TPU_TASK_MACHINE_IDENTITY="$(uuidgen)-worker$TPU_WORKER_ID"
# jax.distributed contract (tpu_task.ml.parallel.mesh.distributed_init_from_env):
# rank, world size, and coordinator = worker 0's endpoint.
export TPU_TASK_WORKER_ID="$TPU_WORKER_ID"
TPU_TASK_NUM_WORKERS="$(echo "$TPU_WORKER_HOSTNAMES" | tr ',' '\n' | grep -c .)"
test "$TPU_TASK_NUM_WORKERS" -ge 1 2> /dev/null || TPU_TASK_NUM_WORKERS=1
export TPU_TASK_NUM_WORKERS
export TPU_TASK_COORDINATOR="$(echo "$TPU_WORKER_HOSTNAMES" | cut -d, -f1):8476"
{
  echo "export TPU_WORKER_ID=$TPU_WORKER_ID"
  echo "export TPU_WORKER_HOSTNAMES=$TPU_WORKER_HOSTNAMES"
  echo "export TPU_TASK_NODE=$TPU_TASK_NODE"
  echo "export TPU_TASK_MACHINE_IDENTITY=$TPU_TASK_MACHINE_IDENTITY"
  echo "export TPU_TASK_WORKER_ID=$TPU_TASK_WORKER_ID"
  echo "export TPU_TASK_NUM_WORKERS=$TPU_TASK_NUM_WORKERS"
  echo "export TPU_TASK_COORDINATOR=$TPU_TASK_COORDINATOR"
} | sudo tee --append /opt/task/credentials > /dev/null

TPU_TASK_LOG_DIRECTORY="$(mktemp --directory)"
TPU_TASK_DATA_DIRECTORY="/opt/task/directory"

TPU_TASK_START_COMMAND="/bin/bash -lc 'exec /usr/bin/tpu-task-script'"
TPU_TASK_REMAINING_RUN_TIME=$((@TIMEOUT@-$(date +%s)))
if (( TPU_TASK_REMAINING_RUN_TIME < 1 )); then
  TPU_TASK_START_COMMAND="/bin/bash -c 'sleep infinity'"
  TPU_TASK_REMAINING_RUN_TIME=1
fi

sudo tee /etc/systemd/system/tpu-task.service > /dev/null <<END
[Unit]
  After=default.target
[Service]
  Type=simple
  ExecStart=-$TPU_TASK_START_COMMAND
  ExecStop=/bin/bash -c 'source /opt/task/credentials; if test "\$TPU_WORKER_ID" = "0"; then tpu-task storage sync "$TPU_TASK_DATA_DIRECTORY" "\$TPU_TASK_REMOTE/data" --exclude "+ **ckpt-*.shard-0.*" --exclude "- **ckpt-*.shard-*"; else tpu-task storage sync "$TPU_TASK_DATA_DIRECTORY" "\$TPU_TASK_REMOTE/data" --exclude "+ **ckpt-*.shard-\$TPU_WORKER_ID.*" --exclude "- **"; fi; systemctl is-system-running | grep stopping || echo "{\\\\"result\\\\": \\\\"\$SERVICE_RESULT\\\\", \\\\"code\\\\": \\\\"\$EXIT_STATUS\\\\", \\\\"status\\\\": \\\\"\$EXIT_CODE\\\\"}" > "$TPU_TASK_LOG_DIRECTORY/status-$TPU_TASK_MACHINE_IDENTITY" && tpu-task storage copy "$TPU_TASK_LOG_DIRECTORY" "\$TPU_TASK_REMOTE/reports"'
  ExecStopPost=/usr/bin/tpu-task-shutdown
  Environment=HOME=/root
  EnvironmentFile=/opt/task/variables
  WorkingDirectory=/opt/task/directory
  RuntimeMaxSec=$TPU_TASK_REMAINING_RUN_TIME
[Install]
  WantedBy=default.target
END

# Install the tpu-task agent (data plane + self-destruct CLI) and JAX for TPU.
# The orchestrator stages the wheel in the task bucket at create time; fetch
# it with a metadata-server token (no package index required), falling back
# to the index only when no wheel was staged.
TPU_TASK_AGENT_WHEEL_URL="@AGENT_WHEEL_URL@"
if ! command -v tpu-task 2>&1 > /dev/null && test -n "$TPU_TASK_AGENT_WHEEL_URL"; then
  TPU_TASK_GCS_TOKEN="$(curl -s -H 'Metadata-Flavor: Google' 'http://metadata.google.internal/computeMetadata/v1/instance/service-accounts/default/token' | python3 -c 'import sys, json; print(json.load(sys.stdin)["access_token"])')"
  curl -sf -H "Authorization: Bearer $TPU_TASK_GCS_TOKEN" -o /tmp/tpu-task-agent.whl "$TPU_TASK_AGENT_WHEEL_URL" \
    && python3 -m pip install --quiet /tmp/tpu-task-agent.whl
fi
if ! command -v tpu-task 2>&1 > /dev/null; then
  python3 -m pip install --quiet tpu-task || pip install --quiet tpu-task
fi
if ! python3 -c 'import jax' 2> /dev/null; then
  python3 -m pip install --quiet 'jax[tpu]' --find-links https://storage.googleapis.com/jax-releases/libtpu_releases.html
fi

# Restore the workdir from the bucket: a respawned (preempted) worker resumes
# from the last synced checkpoint.
tpu-task storage copy "$TPU_TASK_REMOTE/data" /opt/task/directory

sudo systemctl daemon-reload
sudo systemctl enable tpu-task.service --now
sudo systemctl disable --now apt-daily.timer 2> /dev/null || true

# Log stream: journald task unit → reports/task-{machine}, every 5 s on change.
# The liveness heartbeat rides the same loop: its payload changes every tick,
# so the hash check below guarantees a sync (and thus a fresh
# reports/heartbeat-{machine} in the bucket) each period — the staleness
# contract the orchestrator's reconciler watches (TPU_TASK_HEARTBEAT_STALE_AFTER).
while sleep 5; do
  printf '{"time": "%s", "machine": "%s", "worker": %s, "node": "%s", "final": false}' \
    "$(date --utc +%Y-%m-%dT%H:%M:%SZ)" "$TPU_TASK_MACHINE_IDENTITY" \
    "${TPU_WORKER_ID:-0}" "$TPU_TASK_NODE" \
    > "$TPU_TASK_LOG_DIRECTORY/heartbeat-$TPU_TASK_MACHINE_IDENTITY"
  test -n "$TPU_TASK_MACHINE_LOGS" && journalctl > "$TPU_TASK_LOG_DIRECTORY/machine-$TPU_TASK_MACHINE_IDENTITY"
  journalctl --all --no-hostname --output=short-iso --quiet --unit=tpu-task --utc | sed 's/^\([0-9-]*\)T\([0-9:]*\)+0000 \S*: \(.*\)/\1T\2Z \3/g' > "$TPU_TASK_LOG_DIRECTORY/task-$TPU_TASK_MACHINE_IDENTITY"
  NEW_TPU_TASK_LOG_HASH="$(md5sum "$TPU_TASK_LOG_DIRECTORY"/*)"
  if test "$NEW_TPU_TASK_LOG_HASH" != "$TPU_TASK_LOG_HASH"; then
    TPU_TASK_LOG_HASH="$NEW_TPU_TASK_LOG_HASH"
    tpu-task storage sync "$TPU_TASK_LOG_DIRECTORY" "$TPU_TASK_REMOTE/reports"
  fi
done &

# Data/checkpoint stream: workdir → bucket, every 10 s when mtimes change.
# Worker 0 mirrors the whole workdir; every other worker copies ONLY its own
# checkpoint shard files (ckpt-*.shard-$TPU_WORKER_ID.* — written by
# tpu_task.ml.save_checkpoint_sharded), so multi-host sharded state reaches
# the bucket without concurrent mirrors deleting each other's uploads.
if test "${TPU_WORKER_ID:-0}" = "0"; then
  while sleep 10; do
    NEW_TPU_TASK_DATA_EPOCH="$(find "$TPU_TASK_DATA_DIRECTORY" -printf "%T@\n" | sort | tail -1)"
    if test "$NEW_TPU_TASK_DATA_EPOCH" != "$TPU_TASK_DATA_EPOCH"; then
      TPU_TASK_DATA_EPOCH="$NEW_TPU_TASK_DATA_EPOCH"
      # Other workers' shard files exist only in the bucket — exclude them
      # from the mirror so worker 0's sync can't delete them.
      tpu-task storage sync "$TPU_TASK_DATA_DIRECTORY" "$TPU_TASK_REMOTE/data" \
        --exclude "+ **ckpt-*.shard-0.*" --exclude "- **ckpt-*.shard-*"
    fi
  done &
else
  while sleep 10; do
    NEW_TPU_TASK_DATA_EPOCH="$(find "$TPU_TASK_DATA_DIRECTORY" -name "ckpt-*.shard-$TPU_WORKER_ID.*" -printf "%T@\n" | sort | tail -1)"
    if test "$NEW_TPU_TASK_DATA_EPOCH" != "$TPU_TASK_DATA_EPOCH"; then
      TPU_TASK_DATA_EPOCH="$NEW_TPU_TASK_DATA_EPOCH"
      # sync (not copy), scoped to this worker's shards: stale shard files
      # pruned locally must also leave the bucket, or respawn restores drag
      # an ever-growing pile onto every worker.
      tpu-task storage sync "$TPU_TASK_DATA_DIRECTORY" "$TPU_TASK_REMOTE/data" \
        --exclude "+ **ckpt-*.shard-$TPU_WORKER_ID.*" --exclude "- **"
    fi
  done &
fi
