"""Repo lint: the two serving-path invariants a refactor silently breaks.

Run via ``make lint`` (and in tier-1 through ``tests/test_repo_lint.py``).

1. **No ``jnp.concatenate`` in serving token paths.** Under an outer jit
   on the jax 0.4.x CPU backend, a concatenate whose result feeds a
   ``shard_map``'s token slicing miscompiles (PR 15: wrong collective
   layout — silently wrong tokens, no error). The serving token paths
   therefore build packed rows with ``jnp.pad`` / ``.at[:n].set``
   static-slice writes instead. A genuinely safe use (host-side, or
   provably outside any shard_map token path) opts out with a
   ``lint: allow-concatenate`` comment on the same line.

2. **No blocking reads inside the overlapped dispatch region.** The
   engine code between the ``lint: begin-overlap-dispatch`` and
   ``lint: end-overlap-dispatch`` markers runs while the previous
   program is still executing on the device; a ``block_until_ready`` /
   ``jax.device_get`` / ``np.asarray``-of-a-device-value there
   re-serializes the loop the async engine exists to kill — the consume
   edge (outside the markers) is the ONE sanctioned blocking point.

3. **No blocking reads inside the tier-migrate staging region.** Same
   discipline, second region: the demote path between
   ``lint: begin-tier-migrate`` and ``lint: end-tier-migrate`` stages
   pool slices toward the host tier WHILE a program is in flight; the
   bytes may only be forced at the consume edge
   (``_finalize_demotions``). A synchronous ``jax.device_get`` /
   ``np.asarray`` on a pool array there would silently turn every
   demotion into a step-loop stall.

All checks are textual by design: they gate idioms, not semantics, so
they stay O(file read) and dependency-free.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path
from typing import List, Tuple

REPO = Path(__file__).resolve().parents[2]

#: Modules whose jnp arrays are (or feed) token paths under shard_map —
#: the serving model/engine plus the MoE dispatch they call into.
TOKEN_PATH_GLOBS = (
    "tpu_task/ml/serving/*.py",
    "tpu_task/ml/models/moe.py",
)

ALLOW_CONCAT = "lint: allow-concatenate"
BEGIN_OVERLAP = "lint: begin-overlap-dispatch"
END_OVERLAP = "lint: end-overlap-dispatch"
BEGIN_TIER = "lint: begin-tier-migrate"
END_TIER = "lint: end-tier-migrate"
OVERLAP_FILE = "tpu_task/ml/serving/engine.py"

_CONCAT_RE = re.compile(r"\bjnp\.concatenate\s*\(")
#: Blocking-read idioms: forcing a device value waits for every program
#: enqueued before it. `np.asarray(` is matched with a lookbehind so the
#: host-side `jnp.asarray(` staging calls (cheap, non-blocking on host
#: inputs) never trip it.
_BLOCKING_RES = (
    re.compile(r"block_until_ready"),
    re.compile(r"\bjax\.device_get\s*\("),
    re.compile(r"(?<![\w.])np\.asarray\s*\("),
)


def lint_concatenate_text(text: str, path: str) -> List[str]:
    """Findings for rule 1 on one file's text."""
    findings = []
    for ln, line in enumerate(text.splitlines(), 1):
        if _CONCAT_RE.search(line) and ALLOW_CONCAT not in line:
            findings.append(
                f"{path}:{ln}: jnp.concatenate in a serving token path "
                f"(jax 0.4.x CPU SPMD miscompile under shard_map — use "
                f"jnp.pad or .at[:n].set packing, or annotate "
                f"'# {ALLOW_CONCAT}' if provably safe)")
    return findings


def _lint_region_text(text: str, path: str, begin_marker: str,
                      end_marker: str, what: str,
                      region: str) -> List[str]:
    """Shared no-blocking-reads region check. A missing begin marker is
    itself a finding — deleting the markers must not silently disable
    the check."""
    findings = []
    lines = text.splitlines()
    spans: List[Tuple[int, int]] = []
    begin = None
    for ln, line in enumerate(lines, 1):
        if begin_marker in line:
            begin = ln
        elif end_marker in line and begin is not None:
            spans.append((begin, ln))
            begin = None
    if not spans:
        return [f"{path}: {what} lint markers "
                f"('{begin_marker}' ... '{end_marker}') not found — "
                f"the no-blocking region must stay marked"]
    if begin is not None:
        findings.append(f"{path}:{begin}: unterminated '{begin_marker}'")
    for lo, hi in spans:
        for ln in range(lo, hi + 1):
            stripped = lines[ln - 1].lstrip()
            if stripped.startswith("#"):
                continue
            for rx in _BLOCKING_RES:
                if rx.search(lines[ln - 1]):
                    findings.append(
                        f"{path}:{ln}: blocking device read "
                        f"('{rx.pattern}') inside the {region} "
                        f"region — only the consume edge may "
                        f"block")
    return findings


def lint_overlap_text(text: str, path: str) -> List[str]:
    """Findings for rule 2 (the overlapped dispatch region) on the
    engine file's text."""
    return _lint_region_text(
        text, path, BEGIN_OVERLAP, END_OVERLAP,
        "overlap-dispatch", "overlapped dispatch")


def lint_tier_text(text: str, path: str) -> List[str]:
    """Findings for rule 3 (the demote/promote staging region) on the
    engine file's text: tier migration must stage non-blocking — a
    synchronous device read there stalls the step loop the host tier
    was built to keep busy."""
    return _lint_region_text(
        text, path, BEGIN_TIER, END_TIER,
        "tier-migrate", "tier-migrate staging")


def run(repo: Path = REPO) -> List[str]:
    findings = []
    for glob in TOKEN_PATH_GLOBS:
        for path in sorted(repo.glob(glob)):
            rel = path.relative_to(repo).as_posix()
            findings += lint_concatenate_text(
                path.read_text(encoding="utf-8"), rel)
    engine = repo / OVERLAP_FILE
    if engine.exists():
        text = engine.read_text(encoding="utf-8")
        findings += lint_overlap_text(text, OVERLAP_FILE)
        findings += lint_tier_text(text, OVERLAP_FILE)
    else:
        findings.append(f"{OVERLAP_FILE}: missing (overlap lint target)")
    return findings


def main(argv=None) -> int:
    findings = run()
    for f in findings:
        print(f)
    if findings:
        print(f"repo_lint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("repo_lint: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
