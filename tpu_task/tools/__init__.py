"""Repo-internal developer tooling (lint, audits) — not shipped behavior."""
