from tpu_task.backends.tpu.accelerators import (
    Accelerator,
    InvalidAcceleratorError,
    parse_accelerator,
)
from tpu_task.backends.tpu.api import (
    FakeTpuControlPlane,
    NodeInfo,
    QueuedResourceInfo,
    QueuedResourceSpec,
    RestTpuClient,
)
from tpu_task.backends.tpu.task import TPUTask, list_tpu_tasks, resolve_zone

__all__ = [
    "Accelerator", "InvalidAcceleratorError", "parse_accelerator",
    "FakeTpuControlPlane", "NodeInfo", "QueuedResourceInfo",
    "QueuedResourceSpec", "RestTpuClient",
    "TPUTask", "list_tpu_tasks", "resolve_zone",
]
