"""Cloud TPU control-plane client: interface, REST implementation, fake.

The QueuedResource/Node API replaces the reference's InstanceTemplate +
ManagedInstanceGroup pair (/root/reference/task/gcp/resources/
resource_instance_template.go, resource_instance_group_manager.go): a
QueuedResource is the request for TPU capacity (queued until granted — the
spot/stockout realities the MIG hides), and the Node is the granted slice of
one or more TPU-VM workers.

Two implementations:

* ``RestTpuClient`` — the real ``tpu.googleapis.com/v2`` surface (urllib,
  token auth via service account or metadata server). Only touched on real
  clouds.
* ``FakeTpuControlPlane`` — a deterministic, file-backed state machine with
  the same observable behavior (states, queueing, preemption, stockouts),
  optionally *executing* node workers as local agent subprocesses so the
  whole TPU path runs hermetically. This is the fake control-plane layer the
  reference lacks (SURVEY.md §4) — preemption/requeue logic is unit-testable.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol

from tpu_task.common.errors import ResourceAlreadyExistsError, ResourceNotFoundError

# -- data model ---------------------------------------------------------------

# QueuedResource states (subset of the real API's).
QR_WAITING = "WAITING_FOR_RESOURCES"
QR_PROVISIONING = "PROVISIONING"
QR_ACTIVE = "ACTIVE"
QR_SUSPENDING = "SUSPENDING"
QR_SUSPENDED = "SUSPENDED"
QR_FAILED = "FAILED"

# Node states.
NODE_CREATING = "CREATING"
NODE_READY = "READY"
NODE_PREEMPTED = "PREEMPTED"
NODE_DELETING = "DELETING"


@dataclass
class QueuedResourceSpec:
    node_id: str
    accelerator_type: str
    runtime_version: str
    startup_script: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    spot: bool = False
    service_account: str = ""
    network: str = "default"
    zone: str = ""
    # networkConfig from the task's Firewall model: a spec whose ingress
    # allows nothing gets no external IP (gcp/task.go:72-128 equivalent for
    # a slice). tags carries the task identifier so tag-scoped firewall
    # rules can bind to the node's workers.
    enable_external_ips: bool = True
    tags: List[str] = field(default_factory=list)


@dataclass
class NodeInfo:
    name: str
    state: str
    accelerator_type: str
    endpoints: List[str] = field(default_factory=list)  # one per worker host
    worker_count: int = 1
    health: str = ""


@dataclass
class QueuedResourceInfo:
    name: str
    state: str
    spec: QueuedResourceSpec
    node_name: str = ""
    events: List[dict] = field(default_factory=list)


class TpuClient(Protocol):
    def create_queued_resource(self, name: str, spec: QueuedResourceSpec) -> None: ...

    def get_queued_resource(self, name: str) -> QueuedResourceInfo: ...

    def delete_queued_resource(self, name: str, force: bool = True) -> None: ...

    def list_queued_resources(self) -> List[str]: ...

    def get_node(self, name: str) -> NodeInfo: ...

    def delete_node(self, name: str) -> None: ...


# -- fake control plane -------------------------------------------------------

class FakeTpuControlPlane:
    """File-backed deterministic QueuedResource/Node state machine.

    State transitions advance on observation (each ``get_*`` call is one
    tick), so tests are fully deterministic without wall-clock dependence:

      QR:  WAITING_FOR_RESOURCES → PROVISIONING → ACTIVE
      Node: CREATING → READY (workers spawn if execution is enabled)

    Knobs:
      * ``capacity``: concurrent chips available; requests beyond it stay
        WAITING (stockout behavior spot capacity really has).
      * ``preempt(name)``: node → PREEMPTED, QR → SUSPENDED (what a real
        spot reclaim looks like through the API).
      * ``run_workers``: execute each node worker as a local-agent
        subprocess with TPU_WORKER_ID/TPU_WORKER_HOSTNAMES set.
    """

    def __init__(self, root: Optional[str] = None, capacity_chips: int = 4096,
                 run_workers: bool = True, ticks_to_provision: int = 1,
                 ticks_to_active: int = 1):
        self.root = root or os.environ.get(
            "TPU_TASK_FAKE_TPU_ROOT",
            os.path.join(os.path.expanduser("~/.tpu-task"), "fake-tpu"))
        self.capacity_chips = capacity_chips
        self.run_workers = run_workers
        self.ticks_to_provision = ticks_to_provision
        self.ticks_to_active = ticks_to_active
        os.makedirs(self.root, exist_ok=True)

    # -- persistence ----------------------------------------------------------
    def _qr_path(self, name: str) -> str:
        return os.path.join(self.root, "queued_resources", name + ".json")

    def _node_path(self, name: str) -> str:
        return os.path.join(self.root, "nodes", name + ".json")

    def _load(self, path: str) -> dict:
        if not os.path.exists(path):
            raise ResourceNotFoundError(path)
        with open(path) as handle:
            return json.load(handle)

    def _store(self, path: str, payload: dict) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as handle:
            json.dump(payload, handle, indent=2)
        os.replace(tmp, path)

    # -- queued resources -----------------------------------------------------
    def create_queued_resource(self, name: str, spec: QueuedResourceSpec) -> None:
        path = self._qr_path(name)
        if os.path.exists(path):
            return  # AlreadyExists → idempotent no-op
        self._store(path, {
            "name": name,
            "state": QR_WAITING,
            "ticks": 0,
            "spec": spec.__dict__,
            "node_name": spec.node_id,
            "events": [self._event("CREATE", f"queued resource {name} accepted")],
        })

    def get_queued_resource(self, name: str) -> QueuedResourceInfo:
        payload = self._load(self._qr_path(name))
        payload = self._tick_qr(payload)
        spec = QueuedResourceSpec(**payload["spec"])
        return QueuedResourceInfo(
            name=payload["name"], state=payload["state"], spec=spec,
            node_name=payload.get("node_name", ""), events=payload.get("events", []),
        )

    def delete_queued_resource(self, name: str, force: bool = True) -> None:
        path = self._qr_path(name)
        if not os.path.exists(path):
            raise ResourceNotFoundError(name)
        payload = self._load(path)
        node_name = payload.get("node_name", "")
        if node_name and os.path.exists(self._node_path(node_name)):
            if not force:
                raise RuntimeError("queued resource has an active node; use force")
            self._reap_node(node_name)
        os.remove(path)

    def _reap_node(self, name: str) -> None:
        """Tear a node down on the reclaim/requeue path, honoring the
        graceful-preemption grace: ``preempt_node(graceful=True)`` forgot
        the agent pids precisely so they could final-sync after SIGTERM —
        rmtree'ing their exec directory here would revoke that grace on
        the filesystem side (the drain export a serve replica writes, the
        last checkpoint sync of a batch task). A gracefully-reclaimed
        node therefore loses its record but keeps its "disk" until the
        re-granted incarnation (same name) overlays it; every other path
        keeps full fresh-disk deletion."""
        payload = self._load(self._node_path(name))
        if payload.get("graceful_reclaim"):
            os.remove(self._node_path(name))
            return
        self.delete_node(name)

    def list_queued_resources(self) -> List[str]:
        directory = os.path.join(self.root, "queued_resources")
        if not os.path.isdir(directory):
            return []
        return sorted(name[:-5] for name in os.listdir(directory) if name.endswith(".json"))

    def _tick_qr(self, payload: dict) -> dict:
        payload["ticks"] = payload.get("ticks", 0) + 1
        state = payload["state"]
        spec = payload["spec"]
        if state == QR_WAITING:
            if self._chips_in_use() + self._spec_chips(spec) <= self.capacity_chips:
                if payload["ticks"] >= self.ticks_to_provision:
                    payload["state"] = QR_PROVISIONING
                    payload["ticks"] = 0
                    payload["events"].append(self._event(
                        "PROVISION", "capacity granted; provisioning node"))
        elif state == QR_PROVISIONING:
            if payload["ticks"] >= self.ticks_to_active:
                payload["state"] = QR_ACTIVE
                payload["ticks"] = 0
                payload["events"].append(self._event("ACTIVE", "node provisioned"))
                self._create_node(payload)
        elif state == QR_ACTIVE:
            node_path = self._node_path(payload["node_name"])
            if os.path.exists(node_path):
                node = self._load(node_path)
                if node["state"] == NODE_PREEMPTED:
                    payload["state"] = QR_SUSPENDED
                    payload["events"].append(self._event(
                        "SUSPEND", "node preempted; queued resource suspended"))
        self._store(self._qr_path(payload["name"]), payload)
        return payload

    def _spec_chips(self, spec: dict) -> int:
        from tpu_task.backends.tpu.accelerators import parse_accelerator

        return parse_accelerator(spec["accelerator_type"]).chips

    def _chips_in_use(self) -> int:
        total = 0
        for name in self.list_nodes():
            node = self._load(self._node_path(name))
            if node["state"] in (NODE_CREATING, NODE_READY):
                total += self._spec_chips({"accelerator_type": node["accelerator_type"]})
        # PROVISIONING queued resources hold capacity before their node
        # materializes; without this, several WAITING requests could all pass
        # the capacity check and overcommit the plane.
        directory = os.path.join(self.root, "queued_resources")
        if os.path.isdir(directory):
            for entry in os.listdir(directory):
                if not entry.endswith(".json"):
                    continue
                payload = self._load(os.path.join(directory, entry))
                if payload["state"] == QR_PROVISIONING:
                    total += self._spec_chips(payload["spec"])
        return total

    @staticmethod
    def _event(code: str, description: str) -> dict:
        from datetime import datetime, timezone

        return {"time": datetime.now(timezone.utc).isoformat(),
                "code": code, "description": description}

    # -- nodes ----------------------------------------------------------------
    def _create_node(self, qr_payload: dict) -> None:
        from tpu_task.backends.tpu.accelerators import parse_accelerator

        spec = qr_payload["spec"]
        name = qr_payload["node_name"]
        accelerator = parse_accelerator(spec["accelerator_type"])
        workers = []
        for index in range(accelerator.workers):
            workers.append({
                "index": index,
                "endpoint": f"10.130.0.{index + 1}",
                "pid": 0,
                "machine_id": f"{uuid.uuid4().hex[:12]}-worker{index}",
            })
        node = {
            "name": name,
            "state": NODE_READY,
            "accelerator_type": spec["accelerator_type"],
            "spot": spec.get("spot", False),
            "workers": workers,
            "metadata": spec.get("metadata", {}),
            "startup_script": spec.get("startup_script", ""),
        }
        self._store(self._node_path(name), node)
        if self.run_workers:
            self._spawn_workers(node)
            # _spawn_workers filled in worker PIDs; persist them so
            # preempt/delete can actually kill the agent processes.
            self._store(self._node_path(name), node)

    def _spawn_workers(self, node: dict) -> None:
        """Execute the node's workers as local agents (hermetic execution).

        The fake control plane understands the same metadata contract the
        real bootstrap uses: ``metadata["tpu-task-remote"]`` (bucket),
        ``metadata["tpu-task-script-b64"]`` (task script), and sync periods.
        """
        import base64

        metadata = node.get("metadata", {})
        remote = metadata.get("tpu-task-remote", "")
        script_b64 = metadata.get("tpu-task-script-b64", "")
        if not remote or not script_b64:
            return
        node_dir = os.path.join(self.root, "node-exec", node["name"])
        os.makedirs(node_dir, exist_ok=True)
        script_path = os.path.join(node_dir, "task.sh")
        with open(script_path, "w") as handle:
            handle.write(base64.b64decode(script_b64).decode())
        hostnames = ",".join(worker["endpoint"] for worker in node["workers"])
        for worker in node["workers"]:
            workdir = os.path.join(node_dir, f"worker{worker['index']}")
            os.makedirs(workdir, exist_ok=True)
            env = dict(os.environ)
            for key, value in metadata.items():
                if key.startswith("tpu-task-env-"):
                    env[key[len("tpu-task-env-"):]] = value
            from tpu_task.backends.local.control_plane import scrub_accelerator_env

            scrub_accelerator_env(env)
            env["TPU_WORKER_HOSTNAMES"] = hostnames
            # jax.distributed contract, mirroring the real bootstrap template.
            env["TPU_WORKER_ID"] = str(worker["index"])
            env["TPU_TASK_WORKER_ID"] = str(worker["index"])
            env["TPU_TASK_NUM_WORKERS"] = str(len(node["workers"]))
            env["TPU_TASK_COORDINATOR"] = node["workers"][0]["endpoint"] + ":8476"
            env["PYTHONPATH"] = os.pathsep.join(filter(None, [
                os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))),
                env.get("PYTHONPATH", "")]))
            agent_log = open(os.path.join(node_dir, f"worker{worker['index']}.agent.log"), "ab")
            try:
                process = subprocess.Popen(
                    [sys.executable, "-m", "tpu_task.machine.local_agent",
                     "--remote", remote,
                     "--directory", workdir,
                     "--script", script_path,
                     "--machine-id", worker["machine_id"],
                     "--timeout", metadata.get("tpu-task-timeout", "0"),
                     "--log-period", metadata.get("tpu-task-log-period", "5"),
                     "--data-period", metadata.get("tpu-task-data-period", "10"),
                     "--heartbeat-period",
                     metadata.get("tpu-task-heartbeat-period", "30"),
                     "--node-name", node["name"],
                     "--worker-id", str(worker["index"])],
                    env=env, start_new_session=True,
                    stdout=agent_log, stderr=agent_log,
                )
            finally:
                agent_log.close()
            worker["pid"] = process.pid

    def get_node(self, name: str) -> NodeInfo:
        payload = self._load(self._node_path(name))
        return NodeInfo(
            name=payload["name"],
            state=payload["state"],
            accelerator_type=payload["accelerator_type"],
            endpoints=[worker["endpoint"] for worker in payload["workers"]],
            worker_count=len(payload["workers"]),
            health="HEALTHY" if payload["state"] == NODE_READY else "",
        )

    def delete_node(self, name: str) -> None:
        path = self._node_path(name)
        if not os.path.exists(path):
            raise ResourceNotFoundError(name)
        payload = self._load(path)
        self._kill_workers(payload)
        os.remove(path)
        shutil.rmtree(os.path.join(self.root, "node-exec", name), ignore_errors=True)

    def list_nodes(self) -> List[str]:
        directory = os.path.join(self.root, "nodes")
        if not os.path.isdir(directory):
            return []
        return sorted(name[:-5] for name in os.listdir(directory) if name.endswith(".json"))

    def _kill_workers(self, node: dict) -> None:
        import signal

        for worker in node.get("workers", []):
            pid = worker.get("pid") or 0
            if pid:
                try:
                    os.killpg(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        pass

    # -- fault injection ------------------------------------------------------
    def preempt_node(self, name: str, graceful: bool = False) -> None:
        """Spot reclaim: stop the node's workers, mark PREEMPTED.

        ``graceful`` delivers SIGTERM to each agent (the reclaim-warning
        shape real clouds give) so it can final-sync and report before
        exiting. The pids are then FORGOTTEN: the reconciler's very next
        read requeues the SUSPENDED resource, and delete_node reaping the
        recorded pids would SIGKILL the agents mid-final-sync — revoking
        exactly the grace this mode grants (the agent's own TERM→grace→KILL
        ladder bounds a stuck child). Default is a hard kill — capacity
        yanked mid-write."""
        import signal as signal_module

        payload = self._load(self._node_path(name))
        if graceful:
            for worker in payload.get("workers", []):
                pid = worker.get("pid") or 0
                if not pid:
                    continue
                try:
                    os.kill(pid, signal_module.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        else:
            self._kill_workers(payload)
        for worker in payload["workers"]:
            worker["pid"] = 0
        payload["state"] = NODE_PREEMPTED
        # Honored by requeue(): a graceful reclaim's agents are still
        # final-syncing on their "disk" — reclaiming the capacity must not
        # also reclaim the directory they are draining into.
        payload["graceful_reclaim"] = bool(graceful)
        self._store(self._node_path(name), payload)

    def requeue(self, qr_name: str) -> None:
        """Re-queue a SUSPENDED queued resource (delete node, back to WAITING).

        This is the operation the orchestrator's recovery reconciler performs —
        the TPU equivalent of the ASG respawning a spot instance.

        Node teardown rides :meth:`_reap_node`, so a gracefully-reclaimed
        node's still-draining agents keep their exec directory."""
        payload = self._load(self._qr_path(qr_name))
        node_name = payload.get("node_name", "")
        if node_name and os.path.exists(self._node_path(node_name)):
            self._reap_node(node_name)
        payload["state"] = QR_WAITING
        payload["ticks"] = 0
        payload["events"].append(self._event("REQUEUE", "re-queued after preemption"))
        self._store(self._qr_path(payload["name"]), payload)


# -- REST client --------------------------------------------------------------

class RestTpuClient:
    """Real Cloud TPU v2 API client (gated: requires network + credentials).

    API shapes per https://cloud.google.com/tpu/docs/reference/rest/v2.
    """

    def __init__(self, project: str, zone: str, credentials_json: str = ""):
        from tpu_task.storage.http_util import OAuthToken

        self.project = project
        self.zone = zone
        self.credentials_json = credentials_json
        self._token = OAuthToken(self._fetch_token)
        self._urlopen = None  # test hook: injectable transport
        self._sleep = None    # test hook: injectable backoff sleep
        self._event_stamps: dict = {}  # (qr, code, description) → first-seen

    def _first_seen(self, name: str, code: str, description: str) -> str:
        """Stable timestamp for a synthesized state event: stamped when the
        condition is first observed by this client, reused on later polls."""
        import time as _time

        key = (name, code, description)
        if key not in self._event_stamps:
            self._event_stamps[key] = _time.strftime(
                "%Y-%m-%dT%H:%M:%S+00:00", _time.gmtime())
        return self._event_stamps[key]

    # -- plumbing -------------------------------------------------------------
    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _fetch_token(self):
        from tpu_task.storage.backends import (
            _gcs_token_from_metadata,
            _gcs_token_from_service_account,
        )

        if self.credentials_json:
            return _gcs_token_from_service_account(self.credentials_json)
        return _gcs_token_from_metadata()

    def _request(self, method: str, path: str, payload: Optional[dict] = None) -> dict:
        import urllib.error

        from tpu_task.storage.http_util import authorized_send

        url = f"https://tpu.googleapis.com/v2/{path}"
        data = json.dumps(payload).encode() if payload is not None else None
        try:
            body = authorized_send(
                self._token, method, url, data=data,
                headers={"Content-Type": "application/json"},
                urlopen=self._urlopen,
                sleep=self._sleep or time.sleep)
            return json.loads(body or b"{}")
        except urllib.error.HTTPError as error:
            if error.code == 404:
                raise ResourceNotFoundError(path) from error
            if error.code == 409:
                raise ResourceAlreadyExistsError(path) from error
            raise

    def _wait_operation(self, operation: dict, timeout: float = 900.0) -> dict:
        """Exponential-backoff LRO poller, 2 s → 32 s (the reference's GCP op
        waiter — task/gcp/resources/common.go:15-35)."""
        delay = 2.0
        deadline = time.time() + timeout
        while not operation.get("done"):
            if time.time() > deadline:
                raise TimeoutError(f"operation timed out: {operation.get('name')}")
            time.sleep(delay)
            delay = min(delay * 2, 32.0)
            operation = self._request("GET", operation["name"])
        if "error" in operation:
            raise RuntimeError(f"operation failed: {operation['error']}")
        return operation

    # -- queued resources -----------------------------------------------------
    def create_queued_resource(self, name: str, spec: QueuedResourceSpec) -> None:
        body = {
            "tpu": {
                "nodeSpec": [{
                    "parent": self._parent(),
                    "nodeId": spec.node_id,
                    "node": {
                        "acceleratorType": spec.accelerator_type,
                        "runtimeVersion": spec.runtime_version,
                        "networkConfig": {
                            "network": spec.network,
                            "enableExternalIps": spec.enable_external_ips,
                        },
                        **({"tags": spec.tags} if spec.tags else {}),
                        "metadata": {
                            "startup-script": spec.startup_script,
                            **spec.metadata,
                        },
                        "labels": spec.labels,
                        **({"serviceAccount": {"email": spec.service_account}}
                           if spec.service_account else {}),
                        **({"schedulingConfig": {"preemptible": True, "spot": True}}
                           if spec.spot else {}),
                    },
                }],
            },
        }
        try:
            operation = self._request(
                "POST", f"{self._parent()}/queuedResources?queuedResourceId={name}", body)
            self._wait_operation(operation)
        except ResourceAlreadyExistsError:
            pass  # idempotent create: AlreadyExists → no-op (HTTP 409)
        except RuntimeError as error:
            if "ALREADY_EXISTS" not in str(error):
                raise
        else:
            # A fresh incarnation was accepted: drop first-seen event stamps
            # a prior same-named QR may have left behind (e.g. its delete
            # wait failed transiently), so the new incarnation's events
            # aren't suppressed by follow-loop dedup.
            self._clear_event_stamps(name)

    def get_queued_resource(self, name: str) -> QueuedResourceInfo:
        payload = self._request("GET", f"{self._parent()}/queuedResources/{name}")
        state_payload = payload.get("state", {})
        state = state_payload.get("state", QR_WAITING)
        # The v2 API exposes no transition timeline, but the state record
        # carries who initiated the current state and, on FAILED, the error
        # — fold what exists into events so `read --follow` surfaces it.
        # Stamped at FIRST observation and cached: a fresh stamp per poll
        # would make each poll look like a new event to follow-loop dedup.
        events = []
        failed = state_payload.get("failedData", {})
        if failed:
            message = failed.get("error", {}).get("message", "")
            events.append({
                "time": self._first_seen(name, "FAILED", message),
                "code": "FAILED",
                "description": message or "queued resource failed"})
        initiator = state_payload.get("stateInitiator", "")
        if initiator:
            description = f"state set by {initiator}"
            events.append({
                "time": self._first_seen(name, state, description),
                "code": state, "description": description})
        node_id = ""
        spec_payload = payload.get("tpu", {}).get("nodeSpec", [])
        spec = QueuedResourceSpec(node_id="", accelerator_type="", runtime_version="")
        if spec_payload:
            node_id = spec_payload[0].get("nodeId", "")
            node = spec_payload[0].get("node", {})
            # Parse the FULL node spec back — the API echoes startup-script,
            # metadata, labels, network and scheduling in this GET, and the
            # recovery reconciler re-queues from exactly this spec so a bare
            # `read` (fresh process, empty local TaskSpec) recovers a
            # preempted slice with its original bootstrap intact.
            metadata = dict(node.get("metadata", {}))
            startup_script = metadata.pop("startup-script", "")
            scheduling = node.get("schedulingConfig", {})
            spec = QueuedResourceSpec(
                node_id=node_id,
                accelerator_type=node.get("acceleratorType", ""),
                runtime_version=node.get("runtimeVersion", ""),
                startup_script=startup_script,
                metadata=metadata,
                labels=dict(node.get("labels", {})),
                spot=bool(scheduling.get("spot") or scheduling.get("preemptible")),
                service_account=node.get("serviceAccount", {}).get("email", ""),
                network=node.get("networkConfig", {}).get("network", "default"),
                enable_external_ips=bool(node.get("networkConfig", {})
                                         .get("enableExternalIps", True)),
                tags=list(node.get("tags", [])),
            )
        return QueuedResourceInfo(name=name, state=state, spec=spec,
                                  node_name=node_id, events=events)

    def delete_queued_resource(self, name: str, force: bool = True) -> None:
        operation = self._request(
            "DELETE", f"{self._parent()}/queuedResources/{name}?force={str(force).lower()}")
        self._wait_operation(operation)
        # The QR is confirmed gone: a re-created QR under this name is a new
        # incarnation whose state events must get fresh first-seen stamps,
        # not the old ones (which follow-loop dedup would suppress). Only on
        # confirmed deletion — a failed delete leaves the SAME incarnation
        # alive, and wiping its stamps would re-emit its whole history as
        # duplicates. The create path clears stamps too, which covers a
        # same-name re-create after an unconfirmed delete.
        self._clear_event_stamps(name)

    def _clear_event_stamps(self, name: str) -> None:
        for key in [k for k in self._event_stamps if k[0] == name]:
            del self._event_stamps[key]

    def list_queued_resources(self) -> List[str]:
        payload = self._request("GET", f"{self._parent()}/queuedResources")
        return sorted(item["name"].rsplit("/", 1)[-1]
                      for item in payload.get("queuedResources", []))

    # -- nodes ----------------------------------------------------------------
    def get_node(self, name: str) -> NodeInfo:
        payload = self._request("GET", f"{self._parent()}/nodes/{name}")
        endpoints = [endpoint.get("ipAddress", "")
                     for endpoint in payload.get("networkEndpoints", [])]
        return NodeInfo(
            name=name,
            state=payload.get("state", ""),
            accelerator_type=payload.get("acceleratorType", ""),
            endpoints=endpoints,
            worker_count=max(1, len(endpoints)),
            health=payload.get("health", ""),
        )

    def delete_node(self, name: str) -> None:
        operation = self._request("DELETE", f"{self._parent()}/nodes/{name}")
        self._wait_operation(operation)

    def list_nodes(self) -> List[str]:
        payload = self._request("GET", f"{self._parent()}/nodes")
        return sorted(item["name"].rsplit("/", 1)[-1]
                      for item in payload.get("nodes", []))
