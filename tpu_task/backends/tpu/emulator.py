"""Loopback Cloud TPU v2 REST emulator (control-plane subset over HTTP).

Drives :class:`~tpu_task.backends.tpu.api.RestTpuClient` through real
sockets: Bearer auth, the shared retry layer, JSON parsing, and the LRO
operation poller all run for real — the control-plane analog of
``storage/gcs_emulator.py``. Stateful: queued resources are stored from
the POSTed create body and echoed back in the real GET shape
(``tpu.nodeSpec[0].node`` with metadata/startup-script/schedulingConfig),
so the bare-read recovery path parses exactly what it created.

API shapes per https://cloud.google.com/tpu/docs/reference/rest/v2 — the
happy path plus 404/409 semantics and one-poll LRO operations for create
(delete operations return done immediately to keep the 2 s op-poller from
dominating test wall-clock).

Test hooks: ``preempt(name)`` flips a QR to SUSPENDED the way a spot
reclaim does; ``auth_headers`` records every Authorization header seen.
"""

from __future__ import annotations

import re
from typing import Dict, List

from tpu_task.backends.loopback import JsonBearerHandler, LoopbackControlPlane

_QR_PATH = re.compile(
    r"^/v2/projects/([^/]+)/locations/([^/]+)/queuedResources(?:/([^/?]+))?$")
_NODE_PATH = re.compile(
    r"^/v2/projects/([^/]+)/locations/([^/]+)/nodes(?:/([^/?]+))?$")
_OP_PATH = re.compile(
    r"^/v2/projects/([^/]+)/locations/([^/]+)/operations/([^/?]+)$")


class LoopbackTpu(LoopbackControlPlane):
    handler_class = JsonBearerHandler

    def __init__(self):
        super().__init__()
        self.qrs: Dict[str, dict] = {}        # name -> {"body", "state"}
        self.operations: Dict[str, int] = {}  # op name -> remaining polls
        self.auth_headers: List[str] = []
        self._op_counter = 0
        self._fail_queue: List[int] = []      # chaos: statuses to serve next

    # -- client wiring ---------------------------------------------------------
    def attach(self, client) -> None:
        from tpu_task.storage.object_store_emulators import loopback_transport

        client._token._fetch = lambda: ("loopback-token", 3600.0)
        client._urlopen = loopback_transport(
            "https://tpu.googleapis.com", self.port)

    # -- test hooks ------------------------------------------------------------
    def preempt(self, name: str) -> None:
        """Spot reclaim: node gone, queued resource SUSPENDED."""
        self.qrs[name]["state"] = "SUSPENDED"

    def fail_next(self, count: int = 1, status: int = 503) -> None:
        """Chaos hook: answer the next ``count`` requests with ``status``
        (control-plane brownout) — the real client's retry ladder and the
        reconciler's fault tolerance run over actual sockets, the
        socket-level counterpart of ``testing.chaos.ChaosTpuClient``."""
        with self._lock:
            self._fail_queue.extend([status] * count)

    # -- request handling ------------------------------------------------------
    def _operation(self, parent: str, pending_polls: int) -> dict:
        with self._lock:
            self._op_counter += 1
            name = f"projects/{parent}/operations/op-{self._op_counter}"
        self.operations[name] = pending_polls
        return {"name": name, "done": pending_polls == 0}

    def handle(self, method: str, path: str, query: dict, body: dict):
        with self._lock:
            if self._fail_queue:
                status = self._fail_queue.pop(0)
                return status, {"error": {
                    "code": status, "message": "chaos: injected brownout"}}
        op = _OP_PATH.match(path)
        if op:
            name = path[len("/v2/"):]
            if name not in self.operations:
                return 404, {"error": {"code": 404, "message": name}}
            remaining = self.operations[name]
            if remaining > 0:
                self.operations[name] = remaining - 1
                return 200, {"name": name, "done": False}
            return 200, {"name": name, "done": True}

        qr = _QR_PATH.match(path)
        if qr:
            project, zone, name = qr.groups()
            parent = f"{project}/locations/{zone}"
            if method == "POST":
                name = query.get("queuedResourceId", [""])[0]
                if name in self.qrs:
                    return 409, {"error": {"code": 409,
                                           "message": "ALREADY_EXISTS"}}
                self.qrs[name] = {"body": body, "state": "ACTIVE"}
                # One pending poll: the LRO waiter's 308-style loop runs.
                return 200, self._operation(parent, pending_polls=1)
            if method == "DELETE":
                if name not in self.qrs:
                    return 404, {"error": {"code": 404, "message": name}}
                del self.qrs[name]
                return 200, self._operation(parent, pending_polls=0)
            if name:  # GET one
                record = self.qrs.get(name)
                if record is None:
                    return 404, {"error": {"code": 404, "message": name}}
                return 200, {
                    "name": f"projects/{parent}/queuedResources/{name}",
                    "state": {"state": record["state"],
                              **record.get("state_extras", {})},
                    "tpu": record["body"].get("tpu", {}),
                }
            return 200, {"queuedResources": [
                {"name": f"projects/{parent}/queuedResources/{qr_name}"}
                for qr_name in sorted(self.qrs)]}

        node = _NODE_PATH.match(path)
        if node:
            project, zone, name = node.groups()
            if name and method == "DELETE":
                for record in self.qrs.values():
                    spec = record["body"].get("tpu", {}).get("nodeSpec", [{}])
                    if spec[0].get("nodeId") == name:
                        record["state"] = "SUSPENDED"
                return 200, self._operation(f"{project}/locations/{zone}",
                                            pending_polls=0)
            if name:
                record = next(
                    (qr for qr in self.qrs.values()
                     if qr["body"].get("tpu", {}).get("nodeSpec",
                                                      [{}])[0].get("nodeId")
                     == name and qr["state"] == "ACTIVE"), None)
                if record is None:
                    return 404, {"error": {"code": 404, "message": name}}
                spec_node = record["body"]["tpu"]["nodeSpec"][0].get("node", {})
                accelerator = spec_node.get("acceleratorType", "v2-8")
                workers = 1
                match = re.match(r"v\d+\w*-(\d+)", accelerator)
                if match:  # chips/8 hosts, ≥1 (v4-16 → 2 workers)
                    workers = max(1, int(match.group(1)) // 8)
                return 200, {
                    "name": name, "state": "READY",
                    "acceleratorType": accelerator,
                    "health": "HEALTHY",
                    "networkEndpoints": [
                        {"ipAddress": f"10.164.0.{index + 2}"}
                        for index in range(workers)],
                }
            return 200, {"nodes": []}

        return 404, {"error": {"code": 404, "message": path}}
