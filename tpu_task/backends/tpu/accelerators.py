"""Cloud TPU accelerator-type grammar.

Replaces the reference's GPU size maps (e.g.
/root/reference/task/gcp/resources/resource_instance_template.go:72-107) with
the TPU accelerator grammar: ``v{gen}-{size}`` (``v2-8``, ``v4-32``,
``v5p-128``, ``v5litepod-16``, ``v6e-8``...). The parse result carries the
slice topology facts the orchestrator needs: how many TPU-VM workers (hosts)
a slice has — multi-host fan-out (SSH, per-worker logs) and
``jax.distributed`` wiring depend on it.

Per-generation host shapes (public Cloud TPU docs):
  v2/v3:        size = TensorCores, 8 cores (4 chips) per host
  v4/v5p:       size = TensorCores, 8 cores (4 chips) per host
  v5litepod/v5e: size = chips, 8 chips per host
  v6e:          size = chips, 8 chips per host (single-host up to 8)
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict

# Generic sizes kept for reference parity (`s`/`m`/`l`/`xl` — the reference's
# cloud-agnostic grammar) → smallest sensible TPU slices.
GENERIC_SIZES: Dict[str, str] = {
    "s": "v2-8",
    "m": "v2-8",
    "l": "v3-8",
    "xl": "v4-8",
}

_TPU_RE = re.compile(r"^(v[0-9]+[a-z]*(?:pod)?)-([0-9]+)$")

# cores-or-chips per host, and whether the size counts cores or chips.
_GENERATIONS = {
    "v2": dict(per_host=8, unit="cores", cores_per_chip=2, runtime="tpu-ubuntu2204-base"),
    "v3": dict(per_host=8, unit="cores", cores_per_chip=2, runtime="tpu-ubuntu2204-base"),
    "v4": dict(per_host=8, unit="cores", cores_per_chip=2, runtime="tpu-ubuntu2204-base"),
    "v5p": dict(per_host=8, unit="cores", cores_per_chip=2, runtime="v2-alpha-tpuv5"),
    "v5litepod": dict(per_host=8, unit="chips", cores_per_chip=1, runtime="v2-alpha-tpuv5-lite"),
    "v5e": dict(per_host=8, unit="chips", cores_per_chip=1, runtime="v2-alpha-tpuv5-lite"),
    "v6e": dict(per_host=8, unit="chips", cores_per_chip=1, runtime="v2-alpha-tpuv6e"),
}


class InvalidAcceleratorError(ValueError):
    pass


@dataclass(frozen=True)
class Accelerator:
    """A parsed TPU accelerator type."""

    type: str          # canonical accelerator type, e.g. "v4-32"
    generation: str    # "v4"
    size: int          # trailing number (cores for v2-v5p, chips for v5e/v6e)
    chips: int         # total chips in the slice
    workers: int       # TPU-VM hosts in the slice (SSH/log fan-out width)
    runtime_version: str  # default TPU software version

    @property
    def cores(self) -> int:
        info = _GENERATIONS[self.generation]
        return self.chips * info["cores_per_chip"]


def parse_accelerator(machine: str) -> Accelerator:
    """Parse a machine string: TPU type, or generic s/m/l/xl alias."""
    machine = GENERIC_SIZES.get(machine, machine)
    match = _TPU_RE.match(machine)
    if not match:
        raise InvalidAcceleratorError(
            f"not a TPU accelerator type: {machine!r} "
            f"(want e.g. v4-8, v5p-128, v5litepod-16, or one of {sorted(GENERIC_SIZES)})"
        )
    generation, size_str = match.group(1), match.group(2)
    if generation not in _GENERATIONS:
        raise InvalidAcceleratorError(f"unknown TPU generation: {generation!r}")
    size = int(size_str)
    info = _GENERATIONS[generation]
    if info["unit"] == "cores":
        if size % info["cores_per_chip"]:
            raise InvalidAcceleratorError(f"core count must be even: {machine!r}")
        chips = size // info["cores_per_chip"]
        chips_per_host = info["per_host"] // info["cores_per_chip"]
    else:
        chips = size
        chips_per_host = info["per_host"]
    workers = max(1, (chips + chips_per_host - 1) // chips_per_host)
    return Accelerator(
        type=f"{generation}-{size}",
        generation=generation,
        size=size,
        chips=chips,
        workers=workers,
        runtime_version=info["runtime"],
    )
