"""TPU task backend: Cloud TPU slices as the machine substrate.

Composition parity with /root/reference/task/gcp/task.go — ordered step plan
over resources, Start/Stop as capacity changes, Read aggregating states into
Status/Addresses/Events — but TPU-first:

* the scaling-group pair (InstanceTemplate + MIG) becomes N **QueuedResources**
  (``parallelism`` = number of slices; each slice is 1..W TPU-VM workers from
  the accelerator topology);
* spot recovery is an **explicit reconciler**: a SUSPENDED queued resource
  (preempted node) is deleted and re-queued on every Read — the loop the
  reference delegates to ASG/MIG auto-healing (SURVEY.md §7 hard part #1);
  recovery events (with timestamps) make preemption-recovery MTTR measurable;
* the bootstrap is a startup-script rendered by ``machine.render_script``
  (real mode) or the metadata contract executed by the fake control plane
  (hermetic mode).
"""

from __future__ import annotations

import base64
import json
import logging
import os
import time
import uuid
from datetime import datetime, timezone
from typing import Dict, List, Optional

from tpu_task.backends.tpu.accelerators import Accelerator, parse_accelerator
from tpu_task.backends.tpu.api import (
    QR_ACTIVE,
    QR_PROVISIONING,
    QR_SUSPENDED,
    QR_WAITING,
    FakeTpuControlPlane,
    NodeInfo,
    QueuedResourceInfo,
    QueuedResourceSpec,
    RestTpuClient,
)
from tpu_task.backends.gcs_remote import GcsRemoteMixin
from tpu_task.common.cloud import Cloud
from tpu_task.common.errors import ResourceNotFoundError
from tpu_task.common.identifier import Identifier, WrongIdentifierError
from tpu_task.common.ssh import DeterministicSSHKeyPair
from tpu_task.common.steps import Step, run_steps
from tpu_task.common.values import Event, Status
from tpu_task.common.values import Task as TaskSpec
from tpu_task.machine import render_script
from tpu_task.storage import delete_storage
from tpu_task.task import Task

# Generic region → TPU zone map (the reference's region maps, client.go:47-52).
REGIONS: Dict[str, str] = {
    "us-east": "us-east1-d",
    "us-west": "us-west4-a",
    "us-central1": "us-central1-a",
    "us-central2": "us-central2-b",
    "eu-west": "europe-west4-a",
    "eu-north": "europe-north2-b",
    "ap-northeast": "asia-northeast1-b",
}


def resolve_zone(region: str) -> str:
    if region in REGIONS:
        return REGIONS[region]
    # Already zone-shaped ("us-central2-b").
    if region.count("-") >= 2:
        return region
    raise ValueError(f"cannot resolve TPU zone for region {region!r}")


def fake_mode() -> bool:
    return bool(os.environ.get("TPU_TASK_FAKE_TPU_ROOT"))


logger = logging.getLogger("tpu-task")


class TPUTask(GcsRemoteMixin, Task):
    def __init__(self, cloud: Cloud, identifier: Identifier, spec: TaskSpec):
        self.cloud = cloud
        self.identifier = identifier
        self.spec = spec
        self.accelerator: Accelerator = parse_accelerator(spec.size.machine or "v2-8")
        self.zone = resolve_zone(str(cloud.region))
        self._events: List[Event] = []
        # Recovery events survive across reads — they are the MTTR record.
        # Each is ALSO persisted to the bucket mailbox (reports/events-*)
        # so a fresh observer process sees past recoveries; the in-memory
        # list is the fallback when the bucket write failed.
        self._recovery_events: List[Event] = []
        self._remote_record: Optional[str] = None  # lazy QR-metadata lookup
        # Bucket probe caches: every read() would otherwise pay two storage
        # round-trips (shutdown marker + durable events).
        self._shutdown_seen = False
        self._shutdown_checked_at = float("-inf")
        self._bucket_events_cache: List[Event] = []
        self._bucket_event_records: Dict[str, Event] = {}
        self._bucket_events_at = float("-inf")
        self._warned: Dict[str, bool] = {}  # one warning per failure kind
        # Durable-event writes that failed (flaky bucket): retried on later
        # reads so the MTTR record survives transient storage faults.
        self._pending_event_writes: List[tuple] = []
        # Liveness + recovery-governor state (per queued-resource name).
        # Heartbeat BODIES ride the shared per-remote poll cache
        # (storage.sync.poll_cache): a blob whose listed (size, mtime) did
        # not move is never re-read — the same conditional-read mechanism
        # the status/log polls use.
        self._heartbeats_cache: Optional[Dict[str, dict]] = None
        self._heartbeats_at = float("-inf")
        self._first_active: Dict[str, float] = {}   # qr → first ACTIVE (wall)
        self._requeue_state: Dict[str, dict] = {}   # qr → governor record

        if fake_mode():
            self.client = FakeTpuControlPlane()
            self._bucket_dir = os.path.join(self.client.root, "buckets", identifier.long())
        else:
            credentials = ""
            if cloud.credentials.gcp:
                credentials = cloud.credentials.gcp.application_credentials
            project = ""
            if credentials:
                project = json.loads(credentials).get("project_id", "")
            self.client = RestTpuClient(project=project, zone=self.zone,
                                        credentials_json=credentials)
            self._bucket_dir = ""

    # -- resources ------------------------------------------------------------
    def _qr_name(self, index: int) -> str:
        return f"{self.identifier.long()}-{index}"

    def _remote(self) -> str:
        """Bucket connection string (StorageCredentials.ConnectionString parity).

        A bare `read`/`delete` (fresh process, empty TaskSpec) must target the
        storage the task was CREATED with: the queued resource's metadata
        records the remote, so recover it from the control plane before
        assuming the default per-task bucket — a task created with a
        pre-allocated container must not be observed/emptied at the wrong
        bucket."""
        if self.spec.remote_storage is not None:
            return self._remote_storage_connection()
        recorded = self._recorded_remote()
        if recorded:
            return recorded
        if fake_mode():
            return self._bucket_dir
        local_root = os.environ.get("TPU_TASK_LOCAL_BUCKET_ROOT")
        if local_root:
            # Local-directory bucket root: the per-task "bucket" is a
            # directory under it. The hermetic stand-in for the default
            # per-task GCS bucket — lets the REAL control-plane path (REST
            # client, loopback emulator, CLI) run end-to-end with a local
            # data plane, the role rclone's local backend plays in the
            # reference's tests (storage_test.go:54).
            return os.path.join(local_root, self.identifier.long())
        config = {}
        if self.cloud.credentials.gcp and self.cloud.credentials.gcp.application_credentials:
            config["service_account_credentials"] = \
                self.cloud.credentials.gcp.application_credentials
        from tpu_task.storage import Connection

        return str(Connection(backend="googlecloudstorage",
                              container=self.identifier.long(), config=config))

    def _recorded_remote(self) -> str:
        """The remote recorded in a surviving queued resource's metadata
        ('' when no queued resource holds one — e.g. during create)."""
        if self._remote_record is not None:
            return self._remote_record
        for name in self._existing_qrs():
            try:
                info = self.client.get_queued_resource(name)
            except ResourceNotFoundError:
                continue
            remote = info.spec.metadata.get("tpu-task-remote", "")
            if remote:
                self._remote_record = self._with_local_credentials(remote)
                return self._remote_record
        self._remote_record = ""
        return ""

    def _with_local_credentials(self, remote: str) -> str:
        if not remote.startswith(":googlecloudstorage"):
            return remote
        from tpu_task.storage import Connection

        conn = Connection.parse(remote)
        creds = ""
        if self.cloud.credentials.gcp:
            creds = self.cloud.credentials.gcp.application_credentials
        if creds:
            conn.config["service_account_credentials"] = creds
        return str(conn)

    def _credentials_env(self) -> Dict[str, str]:
        """Env map injected into workers (data_source_credentials.go:30-49)."""
        env = {
            "TPU_TASK_REMOTE": self._remote(),
            "TPU_TASK_CLOUD_PROVIDER": "tpu",
            "TPU_TASK_CLOUD_REGION": str(self.cloud.region),
            "TPU_TASK_IDENTIFIER": self.identifier.long(),
        }
        if self.cloud.credentials.gcp and self.cloud.credentials.gcp.application_credentials:
            env["GOOGLE_APPLICATION_CREDENTIALS_DATA"] = \
                self.cloud.credentials.gcp.application_credentials
        return env

    def _timeout_epoch(self) -> Optional[datetime]:
        timeout = self.spec.environment.timeout
        if timeout is None:
            return None
        return datetime.fromtimestamp(time.time() + timeout.total_seconds(),
                                      tz=timezone.utc)

    def _qr_spec(self) -> QueuedResourceSpec:
        variables = self.spec.environment.variables
        startup = render_script(
            self.spec.environment.script, self._credentials_env(), variables,
            self._timeout_epoch(),
            agent_wheel_url=getattr(self, "_agent_wheel_url", ""),
        )
        metadata = {
            # Contract consumed by the fake control plane's worker executor;
            # harmless extra metadata on real nodes. tpu-task-remote and
            # tpu-task-agent-wheel also serve as the control-plane record a
            # bare read/recovery resolves storage and the staged wheel from;
            # the remote is SANITIZED (no credentials) — readers re-inject
            # their own, and workers get theirs via the bootstrap env.
            "tpu-task-agent-wheel": getattr(self, "_agent_wheel_url", ""),
            "tpu-task-remote": self._sanitized_remote(),
            "tpu-task-script-b64": base64.b64encode(
                self.spec.environment.script.encode()).decode(),
            "tpu-task-timeout": str(int(self._timeout_epoch().timestamp())
                                    if self._timeout_epoch() else 0),
            "tpu-task-log-period": os.environ.get("TPU_TASK_LOCAL_LOG_PERIOD", "5"),
            "tpu-task-data-period": os.environ.get("TPU_TASK_LOCAL_DATA_PERIOD", "10"),
            "tpu-task-heartbeat-period": os.environ.get(
                "TPU_TASK_LOCAL_HEARTBEAT_PERIOD", "30"),
        }
        for name, value in {**self._credentials_env(),
                            **variables.enrich()}.items():
            metadata[f"tpu-task-env-{name}"] = value
        # networkConfig from the Firewall model: an ingress rule that allows
        # nothing (explicit empty ports or nets — values.py semantics) means
        # the slice needs no external IP; SSH then rides internal addressing.
        ingress = self.spec.firewall.ingress
        external = not (ingress.ports == [] or ingress.nets == [])
        return QueuedResourceSpec(
            node_id="",  # set per queued resource
            accelerator_type=self.accelerator.type,
            runtime_version=self.spec.environment.image
            if self.spec.environment.image not in ("", "ubuntu", "nvidia")
            else self.accelerator.runtime_version,
            startup_script=startup,
            metadata=metadata,
            labels=dict(self.cloud.tags),
            spot=self.spec.spot >= 0,
            service_account=self.spec.permission_set,
            enable_external_ips=external,
            # Slices carry the task identifier as a network tag so
            # tag-scoped firewall rules (user-managed or the GCE backend's
            # 6-rule scheme) can bind to exactly this task's workers.
            tags=[self.identifier.long()],
        )

    # -- lifecycle ------------------------------------------------------------
    def create(self) -> None:
        if self.spec.size.storage > 0:
            # TPU-VM boot disks are fixed-size and the QueuedResource API
            # attaches only pre-created data disks; rejecting loudly beats
            # silently provisioning nothing (honored on cloud=gcp GCE).
            # Validated here, not in __init__, so read/stop/delete on an
            # existing task never trip over it.
            raise ValueError(
                f"disk_size={self.spec.size.storage} is not supported for "
                "TPU slices; attach data via storage{} or use a GCE machine")
        run_steps([
            Step(f"Parsing accelerator {self.accelerator.type} "
                 f"({self.accelerator.chips} chips, {self.accelerator.workers} workers)...",
                 lambda: None),
            Step("Creating storage bucket...", self._create_bucket),
            Step("Staging agent wheel...", self._stage_agent),
            Step("Uploading directory...", self.push),
            Step("Submitting queued resources...", self.start),
        ])

    def _stage_agent(self) -> None:
        """Upload the tpu-task wheel the worker bootstrap installs
        (tpu-worker-script.sh.tpl fetches it with a metadata token)."""
        if fake_mode():
            return  # hermetic workers run the local agent directly
        from tpu_task.machine.wheel import stage_wheel

        self._agent_wheel_url = stage_wheel(self._remote())

    def _create_bucket(self) -> None:
        if fake_mode():
            os.makedirs(self._bucket_dir, exist_ok=True)
            return
        remote = self._remote()
        if not remote.startswith(":"):  # local-directory bucket root
            os.makedirs(remote, exist_ok=True)
            return
        if self.spec.remote_storage is not None:
            # Pre-allocated container: verify access, create nothing
            # (data_source_bucket.go role).
            from tpu_task.storage import check_storage

            check_storage(self._remote())
            return
        self._bucket_resource().create()

    def _bucket_resource(self):
        from tpu_task.backends.gcp.resources import Bucket

        return Bucket(self.identifier.long(), self.zone,
                      self.client.project,  # type: ignore[union-attr]
                      self._storage_config().get(
                          "service_account_credentials", ""))

    def _storage_config(self) -> Dict[str, str]:
        if self.cloud.credentials.gcp and self.cloud.credentials.gcp.application_credentials:
            return {"service_account_credentials":
                    self.cloud.credentials.gcp.application_credentials}
        return {}

    def start(self) -> None:
        spec = self._qr_spec()
        for index in range(self.spec.parallelism):
            name = self._qr_name(index)
            qr_spec = QueuedResourceSpec(**{
                **spec.__dict__, "node_id": name,
                # Per-slice identity: workers stamp it into heartbeats
                # (liveness correlation) and read it as TPU_TASK_NODE.
                "metadata": {**spec.metadata, "tpu-task-node": name}})
            self.client.create_queued_resource(name, qr_spec)

    def stop(self) -> None:
        # Iterate actual surviving QR names, unioned with the spec's index
        # range — an index scan alone misses stragglers when the surviving
        # set is sparse (e.g. only `-3` left after partial deletes) and the
        # local spec says parallelism=1.
        names = set(self._existing_qrs())
        names.update(self._qr_name(index)
                     for index in range(self.spec.parallelism))
        for name in sorted(names):
            try:
                self.client.delete_queued_resource(name, force=True)
            except ResourceNotFoundError:
                pass

    def _existing_qrs(self) -> List[str]:
        prefix = self.identifier.long() + "-"
        return [name for name in self.client.list_queued_resources()
                if name.startswith(prefix)]

    def observed_parallelism(self) -> Optional[int]:
        """Worker-count from the control plane's own record (surviving queued
        resources), so a bare `read` doesn't trust a defaulted flag."""
        return len(self._existing_qrs()) or None

    def read(self) -> None:
        # Self-destruct: worker 0 leaves a shutdown marker in the bucket at
        # task exit (alongside calling `tpu-task stop` directly when it has
        # credentials); observing it releases the TPU capacity
        # (machine-script.sh.tpl:10-14 semantics).
        if self._existing_qrs() and self._shutdown_requested():
            self._record_recovery(Event(
                time=datetime.now(timezone.utc), code="self-destruct",
                description=["shutdown marker observed; releasing slices"]),
                key_hint="self-destruct")
            self.stop()

        self._drain_pending_event_writes()
        stale_after = float(os.environ.get("TPU_TASK_HEARTBEAT_STALE_AFTER",
                                           "120"))
        heartbeats = self._heartbeat_index() if stale_after > 0 else None

        addresses: List[str] = []
        running = 0
        self._events = []
        for name in self._existing_qrs():
            try:
                info = self.client.get_queued_resource(name)
            except ResourceNotFoundError:
                continue
            for event in info.events:
                self._events.append(Event(
                    time=datetime.fromisoformat(event["time"]),
                    code=event["code"], description=[event["description"]]))
            # Recovery is gated on the *queued resource's own* spot bit, not
            # the in-memory spec: a bare `tpu-task read --follow` constructs
            # the task with an empty TaskSpec (spot = disabled), and the
            # primary real-world monitor loop must still recover preempted
            # spot slices. self.spec.spot remains as a fallback for specs
            # created before the API echoed schedulingConfig.
            if info.state == QR_SUSPENDED and (info.spec.spot
                                               or self.spec.spot >= 0):
                self._maybe_recover(info, code="recover")
                continue
            if info.state == QR_ACTIVE and info.node_name:
                try:
                    node = self.client.get_node(info.node_name)
                except ResourceNotFoundError:
                    continue
                if node.state == "READY":
                    # Liveness: a slice the control plane calls ACTIVE whose
                    # heartbeats went stale is dead capacity — treat it as
                    # preemption-equivalent and requeue (same governor:
                    # backoff + bounded recovery budget).
                    if stale_after > 0 and self._liveness_stale(
                            info, heartbeats, stale_after,
                            worker_count=node.worker_count):
                        self._maybe_recover(
                            info, code="liveness-requeue",
                            occurrence=self._liveness_occurrence(
                                info, heartbeats))
                        continue
                    self._maybe_reset_budget(info, heartbeats)
                    running += 1
                    addresses.extend(node.endpoints)
        self.spec.addresses = addresses
        self.spec.status = self.status(running=running)
        self.spec.events = self.events()

    def _shutdown_requested(self) -> bool:
        """Has worker 0 left a shutdown marker in the bucket?

        The probe costs a storage round-trip, so a negative answer is
        cached for TPU_TASK_SHUTDOWN_PROBE_PERIOD seconds (default 20 —
        self-destruct latency, not correctness) and a positive one latches.
        Storage failures are logged (once per failure kind), not silently
        swallowed: a persistently broken bucket should not invisibly
        disable self-destruct observation."""
        from tpu_task.common.errors import ResourceNotFoundError as _NotFound
        from tpu_task.storage.backends import open_backend

        if self._shutdown_seen:
            return True
        period = float(os.environ.get("TPU_TASK_SHUTDOWN_PROBE_PERIOD", "20"))
        now = time.monotonic()
        if now - self._shutdown_checked_at < period:
            return False
        self._shutdown_checked_at = now
        try:
            backend, _ = open_backend(self._remote())
            backend.read("shutdown")
            self._shutdown_seen = True
            return True
        except (_NotFound, FileNotFoundError):
            return False  # no marker yet: the expected steady state
        except Exception as error:
            self._warn_once("shutdown-probe",
                            f"shutdown-marker probe failed: {error}")
            return False

    def _warn_once(self, kind: str, message: str) -> None:
        if not self._warned.get(kind):
            self._warned[kind] = True
            logger.warning("%s", message)

    # -- durable recovery/MTTR events -----------------------------------------
    def _record_recovery(self, event: Event, key_hint: str = "") -> None:
        """Remember a recovery event AND persist it to the bucket mailbox
        (reports/events-*), so a second observer — a fresh `read --follow`
        process — sees the recovery history the way the reference surfaces
        ASG scaling activities (resource_auto_scaling_group.go:158-183).

        ``key_hint`` makes the record idempotent under concurrent
        observers: every process that witnesses the same occurrence
        computes the same object key (self-destruct is one-shot; a
        recovery is keyed by slice + observation minute), so duplicate
        writes collapse into one record instead of inflating the MTTR
        history forever."""
        self._recovery_events.append(event)
        hint = key_hint or f"{event.code}-{uuid.uuid4().hex[:8]}"
        key = f"reports/events-{hint}.json"
        payload = json.dumps({
            "time": event.time.isoformat(),
            "code": event.code,
            "description": list(event.description),
        }).encode()
        if not self._persist_event(key, payload):
            # Flaky bucket: queue the record and retry on later reads — a
            # transient storage fault must not erase the MTTR history.
            if len(self._pending_event_writes) < 64:
                self._pending_event_writes.append((key, payload))

    def _persist_event(self, key: str, payload: bytes) -> bool:
        from tpu_task.storage.backends import open_backend

        try:
            backend, _ = open_backend(self._remote())
            # First writer wins: concurrent observers of one occurrence
            # compute the same key but stamp their own clocks — an
            # overwrite would mutate a record other processes may have
            # cached under the immutability contract (_bucket_events).
            # write_if_absent is atomic on local (O_EXCL) and GCS
            # (ifGenerationMatch=0) — the deployed mailbox backends.
            wrote = backend.write_if_absent(key, payload)
            if wrote:
                self._bucket_events_at = float("-inf")  # cache now stale
            return True
        except Exception as error:
            self._warn_once("event-persist",
                            f"could not persist recovery event: {error}")
            return False

    def _drain_pending_event_writes(self) -> None:
        pending, self._pending_event_writes = self._pending_event_writes, []
        for key, payload in pending:
            if not self._persist_event(key, payload):
                self._pending_event_writes.append((key, payload))

    def _bucket_events(self) -> List[Event]:
        """Durable events from the bucket mailbox, cached for
        TPU_TASK_EVENTS_PROBE_PERIOD seconds (default 20). Event files are
        immutable once written, so refreshes list keys but only fetch
        bodies not seen before — O(new events) reads per poll, not O(all)."""
        period = float(os.environ.get("TPU_TASK_EVENTS_PROBE_PERIOD", "20"))
        now = time.monotonic()
        if now - self._bucket_events_at < period:
            return self._bucket_events_cache
        from tpu_task.storage.backends import open_backend

        records: Dict[str, Event] = {}
        try:
            backend, _ = open_backend(self._remote())
            for key in sorted(backend.list("reports/")):
                name = key.rsplit("/", 1)[-1]
                if not (name.startswith("events-") and name.endswith(".json")):
                    continue
                cached = self._bucket_event_records.get(key)
                if cached is not None:
                    records[key] = cached
                    continue
                payload = json.loads(backend.read(key))
                records[key] = Event(
                    time=datetime.fromisoformat(payload["time"]),
                    code=payload.get("code", ""),
                    description=list(payload.get("description", [])))
        except Exception as error:
            self._warn_once("event-read",
                            f"could not read durable events: {error}")
            return self._bucket_events_cache  # last known good
        self._bucket_event_records = records
        # Chronological, not key order: the dedup keys (recover-<slice>-
        # <minute>, self-destruct) don't sort by time lexically.
        self._bucket_events_cache = [
            records[key] for key in
            sorted(records, key=lambda k: (records[k].time, k))]
        self._bucket_events_at = now
        return self._bucket_events_cache

    # -- liveness (heartbeat staleness) ---------------------------------------
    def _heartbeat_index(self) -> Optional[Dict[str, Dict[int, dict]]]:
        """Newest heartbeat per slice worker from ``reports/heartbeat-*``
        blobs: ``{node: {worker: {"mtime": epoch_s, "final": bool}}}``.
        ``None`` when this probe failed (or the backend lists no mtimes) —
        a flaky bucket must yield *no decision*, never a spurious requeue,
        and never a stale snapshot that ages into one. Bodies
        (machine→node/worker mapping) come through the shared per-remote
        poll cache keyed on the listing's (size, mtime): a poll re-reads
        only blobs that moved — the same conditional-read mechanism behind
        the status/log polls. Cached for TPU_TASK_HEARTBEAT_PROBE_PERIOD
        seconds (default 20)."""
        period = float(os.environ.get("TPU_TASK_HEARTBEAT_PROBE_PERIOD", "20"))
        now = time.monotonic()
        if now - self._heartbeats_at < period:
            return self._heartbeats_cache
        from tpu_task.storage.backends import open_backend
        from tpu_task.storage.sync import _poll_cache_enabled, poll_cache

        try:
            backend, _ = open_backend(self._remote())
            meta = backend.list_meta("reports/")
            if meta is None:
                # A backend that lists no mtimes can't age heartbeats —
                # liveness makes NO decision rather than misreading every
                # blob as never-written (all deployed backends do report
                # mtimes; this is the contract for future ones).
                self._warn_once("heartbeat-meta",
                                "storage backend lists no mtimes; "
                                "heartbeat liveness disabled")
                self._heartbeats_cache = None
                self._heartbeats_at = now
                return None
            # Same kill switch as the status/log polls: with the cache
            # disabled every heartbeat body is re-read unconditionally.
            cache = poll_cache(self._remote()) if _poll_cache_enabled() \
                else None
            index: Dict[str, Dict[int, dict]] = {}
            for key in sorted(meta):
                name = key.rsplit("/", 1)[-1]
                if not name.startswith("heartbeat-"):
                    continue
                mtime = meta[key][1]
                payload = json.loads(
                    cache.read(backend, key, meta[key]) if cache is not None
                    else backend.read(key))
                node = payload.get("node", "")
                worker = int(payload.get("worker", 0))
                final = bool(payload.get("final"))
                workers = index.setdefault(node, {})
                entry = workers.get(worker)
                if entry is None or mtime > entry["mtime"]:
                    workers[worker] = {"mtime": mtime, "final": final}
            # Drop cache entries for blobs that left the listing (pruned on
            # requeue / task teardown) so the cache stays bounded.
            if cache is not None:
                cache.prune(set(meta), "heartbeat-")
        except Exception as error:
            # Probe failed → NO decision (never a stale last-known-good: a
            # sustained observer-side outage would otherwise age the frozen
            # cache past the staleness bound and requeue a healthy slice).
            self._warn_once("heartbeat-probe",
                            f"heartbeat probe failed: {error}")
            return None
        self._heartbeats_cache = index
        self._heartbeats_at = now
        return index

    def _liveness_stale(self, info: QueuedResourceInfo,
                        heartbeats: Optional[Dict[str, Dict[int, dict]]],
                        stale_after: float,
                        worker_count: int = 1) -> bool:
        """Is this ACTIVE slice hung? True when ANY of its workers' newest
        heartbeats is older than ``stale_after`` seconds (one hung worker
        wedges the whole jax.distributed job) — or when a worker never
        heartbeat at all within TPU_TASK_LIVENESS_BOOT_GRACE of the slice
        first being seen ACTIVE (a VM that hung before the agent started).
        Heartbeats older than the slice's last requeue belong to the
        previous incarnation and count as "none yet"; a worker whose last
        heartbeat is ``final`` exited cleanly and is the status mailbox's
        business, not liveness's."""
        now = time.time()
        first = self._first_active.setdefault(info.name, now)
        if heartbeats is None:
            return False  # probe failed: no data, no decision
        last_requeue = self._requeue_state.get(info.name, {}).get("at_wall", 0.0)
        anchor = max(first, last_requeue)
        grace = float(os.environ.get("TPU_TASK_LIVENESS_BOOT_GRACE", "600"))
        entries = heartbeats.get(info.node_name) or {}
        for worker in range(worker_count):
            entry = entries.get(worker)
            if entry is None or entry["mtime"] <= last_requeue:
                if now - anchor > grace:
                    return True
                continue
            if entry["final"]:
                continue
            if now - entry["mtime"] > stale_after:
                return True
        return False

    def _liveness_occurrence(self, info: QueuedResourceInfo,
                             heartbeats) -> str:
        """Idempotency suffix for one liveness occurrence, derived from the
        HUNG worker's last heartbeat (the oldest non-final one): every
        observer of the same hang sees the same frozen mtime — a healthy
        sibling's advancing heartbeats must not vary the key — while a
        later hang of the requeued incarnation freezes at a fresher mtime,
        so concurrent observers dedupe but successive requeues each get
        their own durable MTTR record."""
        entries = (heartbeats or {}).get(info.node_name) or {}
        stale = [e["mtime"] for e in entries.values() if not e["final"]]
        if stale:
            return str(int(min(stale)))
        anchor = max(self._first_active.get(info.name, 0.0),
                     self._requeue_state.get(info.name, {}).get("at_wall", 0.0))
        return f"boot{int(anchor)}"

    # -- requeue governor: backoff + bounded recovery budget ------------------
    def _maybe_recover(self, info: QueuedResourceInfo, code: str,
                       occurrence: str = "") -> None:
        """Gate every requeue through per-slice exponential backoff and a
        bounded recovery budget, so a poisoned spec converges to FAILED
        instead of thrashing forever. Every decision lands in the durable
        event mailbox — MTTR stays measurable from any observer."""
        state = self._requeue_state.setdefault(info.name, {
            "attempts": 0, "next_at": float("-inf"), "at_wall": 0.0,
            "exhausted": False})
        if state["exhausted"]:
            return
        budget = int(os.environ.get("TPU_TASK_RECOVERY_BUDGET", "5"))
        if state["attempts"] >= budget:
            state["exhausted"] = True
            self._fail_unrecoverable(info)
            return
        now = time.monotonic()
        if now < state["next_at"]:
            return  # backing off; reconsidered on a later read
        base = float(os.environ.get("TPU_TASK_REQUEUE_BACKOFF_BASE", "2"))
        cap = float(os.environ.get("TPU_TASK_REQUEUE_BACKOFF_CAP", "60"))
        state["attempts"] += 1
        state["next_at"] = now + min(base * (2 ** (state["attempts"] - 1)), cap)
        state["at_wall"] = time.time()
        self._first_active.pop(info.name, None)
        stamp = datetime.now(timezone.utc)
        reason = ("stale heartbeat on ACTIVE slice" if code == "liveness-requeue"
                  else "preempted")
        self._record_recovery(
            Event(time=stamp, code=code,
                  description=[f"re-queueing {reason} {info.name} "
                               f"(attempt {state['attempts']}/{budget})"]),
            key_hint=f"{code}-{info.name}-"
                     f"{occurrence or self._occurrence_stamp(info, stamp)}")
        self._recover(info)

    def _occurrence_stamp(self, info: QueuedResourceInfo, stamp) -> str:
        """Idempotency suffix for one recovery occurrence: concurrent
        observers of the SAME suspension compute the same key (the control
        plane's SUSPEND event time identifies it), while successive
        suspensions of one slice get distinct durable records. Falls back
        to the observation minute when the API exposed no SUSPEND event."""
        for event in reversed(info.events):
            if event.get("code") == "SUSPEND":
                return "".join(ch for ch in event["time"] if ch.isalnum())
        return stamp.strftime("%Y%m%dT%H%M")

    def _maybe_reset_budget(self, info: QueuedResourceInfo,
                            heartbeats: Optional[Dict[str, dict]]) -> None:
        """A healthy re-queue resets the budget: the slice came back ACTIVE
        and either produced a fresh heartbeat since its last requeue or ran
        for TPU_TASK_RECOVERY_HEALTHY_AFTER seconds — so the budget bounds
        *consecutive* failing recoveries, not lifetime preemptions."""
        state = self._requeue_state.get(info.name)
        if not state or not state["attempts"] or state["exhausted"]:
            return
        healthy_after = float(os.environ.get(
            "TPU_TASK_RECOVERY_HEALTHY_AFTER", "120"))
        entries = (heartbeats or {}).get(info.node_name) or {}
        heartbeat_fresh = any(entry["mtime"] > state["at_wall"]
                              for entry in entries.values())
        uptime_ok = time.time() - state["at_wall"] > healthy_after
        if heartbeat_fresh or uptime_ok:
            state["attempts"] = 0
            state["next_at"] = float("-inf")

    def _fail_unrecoverable(self, info: QueuedResourceInfo) -> None:
        """Recovery budget exhausted: surface FAILED and release capacity.

        A terminal status report (non-zero code) lands in the mailbox so
        EVERY observer's status fold sees the slice as failed; the durable
        budget-exhausted event is the forensic record; the queued resource
        is deleted so a poisoned spec stops consuming quota."""
        stamp = datetime.now(timezone.utc)
        budget = int(os.environ.get("TPU_TASK_RECOVERY_BUDGET", "5"))
        self._record_recovery(
            Event(time=stamp, code="recovery-budget-exhausted",
                  description=[f"{info.name}: {budget} consecutive recoveries "
                               "failed; giving up (FAILED)"]),
            key_hint=f"budget-{info.name}")
        from tpu_task.storage.backends import open_backend

        try:
            backend, _ = open_backend(self._remote())
            backend.write(f"reports/status-{info.name}", json.dumps({
                "result": "recovery-budget-exhausted",
                "code": "recovery-budget-exhausted", "status": ""}).encode())
        except Exception as error:
            self._warn_once("budget-status",
                            f"could not persist budget-exhausted status: {error}")
        try:
            self.client.delete_queued_resource(info.name, force=True)
        except ResourceNotFoundError:
            pass
        # The slice is gone: drop its governor record (the heartbeat cache
        # prunes dead incarnations the same way). A later re-create of the
        # same queued-resource name must start with a fresh budget, not
        # inherit a latched "exhausted" from a previous life.
        self._requeue_state.pop(info.name, None)
        self._first_active.pop(info.name, None)

    def _recover(self, info: QueuedResourceInfo) -> None:
        """The preemption-recovery reconciler: SUSPENDED → delete → re-queue.

        Workers of the re-granted node restore their workdir from the bucket
        (render_script / local agent restore path), so user scripts resume
        from the last synced checkpoint — ASG-respawn semantics made explicit.
        (Mechanical requeue only; event recording and backoff/budget gating
        live in :meth:`_maybe_recover`.)
        """
        # Recover the staged agent-wheel URL from the QR's own metadata —
        # a bare-read process never staged one itself, and a re-rendered
        # bootstrap without it would fall back to the package index.
        recorded_wheel = info.spec.metadata.get("tpu-task-agent-wheel", "")
        if recorded_wheel and not getattr(self, "_agent_wheel_url", ""):
            self._agent_wheel_url = recorded_wheel
        spec = info.spec
        if not spec.accelerator_type or not spec.startup_script:
            # REST reads return a sparse spec (no bootstrap/metadata);
            # re-render locally so the recovered node actually runs the task.
            spec = QueuedResourceSpec(**{**self._qr_spec().__dict__,
                                         "node_id": info.name})
        try:
            self.client.delete_queued_resource(info.name, force=True)
        except ResourceNotFoundError:
            pass
        # Prune the dead incarnation's heartbeat blobs BEFORE the respawn:
        # they are exactly what a FRESH observer (empty in-memory requeue
        # state) would otherwise read as "stale heartbeat on an ACTIVE
        # slice" while the re-granted VM is still booting — a spurious
        # requeue storm — and they grow without bound across requeues.
        # After the prune the new incarnation reads as "no heartbeat yet"
        # to every observer, which is what boot grace is for. (A graceful
        # agent's final=True heartbeat written after this is harmless —
        # final entries never count as stale.)
        self._prune_heartbeats(info.name)
        self.client.create_queued_resource(info.name, spec)

    def _prune_heartbeats(self, node_name: str) -> None:
        from tpu_task.storage.backends import open_backend
        from tpu_task.storage.sync import _poll_cache_enabled, poll_cache

        try:
            backend, _ = open_backend(self._remote())
            cache = poll_cache(self._remote()) if _poll_cache_enabled() \
                else None
            for key in backend.list("reports/"):
                name = key.rsplit("/", 1)[-1]
                if not name.startswith("heartbeat-"):
                    continue
                # Cache-served when the blob is unchanged since the last
                # liveness probe; a conditional read otherwise.
                body = cache.read(backend, key) if cache is not None \
                    else backend.read(key)
                node = json.loads(body).get("node", "")
                if node == node_name:
                    backend.delete(key)
                    if cache is not None:
                        cache.forget(key)
        except Exception as error:
            # Best effort: a failed prune leaves the (bounded) stale-blob
            # hazard, never breaks the requeue itself.
            self._warn_once("heartbeat-prune",
                            f"could not prune heartbeats: {error}")

    def delete(self) -> None:
        # Resolve (and cache) the remote BEFORE stop() deletes the queued
        # resources whose metadata records it.
        remote = self._remote()
        if self.spec.environment.directory:
            try:
                self.pull()
            except ResourceNotFoundError:
                pass
        self.stop()
        # Terminal teardown: prune the in-process governor + liveness state
        # for every slice (the heartbeat cache already resets via its
        # probe-period stamp). A deleted-then-recreated task must start
        # with a fresh recovery budget — without this, a reused task object
        # inherits attempts/backoff/exhaustion from the previous life.
        self._requeue_state.clear()
        self._first_active.clear()
        if not fake_mode() and self._is_per_task_bucket(remote):
            # Per-task bucket: empty it AND delete the bucket itself.
            self._bucket_resource().delete()
            return
        try:
            # Pre-allocated container: empty only this task's subdirectory.
            delete_storage(remote)
        except ResourceNotFoundError:
            pass
        if fake_mode() and os.path.isdir(self._bucket_dir):
            import shutil

            shutil.rmtree(self._bucket_dir, ignore_errors=True)


    # -- observation (data plane inherited from GcsRemoteMixin) ---------------
    def status(self, running: Optional[int] = None) -> Status:
        if running is None:
            # read() just folded the QR fan-out + status mailbox into
            # spec.status; a poll loop calling read()+status() must not redo
            # the listing+fold (same contract as the gcp/aws backends).
            if self.spec.status:
                return self.spec.status
            running = 0
            for name in self._existing_qrs():
                try:
                    info = self.client.get_queued_resource(name)
                    if info.state == QR_ACTIVE and info.node_name:
                        node = self.client.get_node(info.node_name)
                        if node.state == "READY":
                            running += 1
                except ResourceNotFoundError:
                    continue
        return self._folded_status(running)

    def events(self) -> List[Event]:
        """QR events + recovery history. Durable bucket events are the
        authoritative recovery record (visible to every observer); local
        recovery events are folded in only when missing there (persist
        failed), deduped by (time, code)."""
        durable = self._bucket_events()
        seen = {(event.time, event.code) for event in durable}
        local_only = [event for event in self._recovery_events
                      if (event.time, event.code) not in seen]
        return list(self._events) + durable + local_only

    # -- multi-host fan-out ---------------------------------------------------
    def worker_addresses(self) -> List[str]:
        """Every TPU-VM worker endpoint across the task's slices, rank order."""
        addresses: List[str] = []
        for name in self._existing_qrs():
            try:
                info = self.client.get_queued_resource(name)
                if info.state == QR_ACTIVE and info.node_name:
                    node = self.client.get_node(info.node_name)
                    if node.state == "READY":
                        addresses.extend(node.endpoints)
            except ResourceNotFoundError:
                continue
        return addresses

    def exec_on_workers(self, command: str, timeout: float = 60.0):
        """Run a command on all slice workers concurrently (SSH fan-out;
        hermetic LocalTransport against the fake control plane's per-worker
        workdirs in fake mode)."""
        from tpu_task.machine.fanout import LocalTransport, SSHTransport, fan_out

        if fake_mode():
            directories: List[str] = []
            for name in self._existing_qrs():
                try:
                    info = self.client.get_queued_resource(name)
                except ResourceNotFoundError:
                    continue
                if info.state != QR_ACTIVE or not info.node_name:
                    continue
                node_dir = os.path.join(self.client.root, "node-exec", info.node_name)
                if not os.path.isdir(node_dir):
                    continue
                worker_entries = [
                    entry for entry in os.listdir(node_dir)
                    if entry.startswith("worker") and entry[6:].isdigit() and
                    os.path.isdir(os.path.join(node_dir, entry))
                ]
                # Numeric sort: lexicographic would put worker10 before worker2.
                worker_entries.sort(key=lambda entry: int(entry[6:]))
                directories.extend(
                    os.path.join(node_dir, entry) for entry in worker_entries
                )
            return fan_out(directories, command, LocalTransport(), timeout=timeout)
        key_pair = self.get_key_pair()
        transport = SSHTransport(key_pair.private_string() if key_pair else "")
        try:
            # One key materialization serves the whole fan-out; close()
            # removes it as soon as the last worker returns.
            return fan_out(self.worker_addresses(), command, transport,
                           timeout=timeout)
        finally:
            transport.close()

    def get_key_pair(self) -> Optional[DeterministicSSHKeyPair]:
        """Deterministic keypair from the cloud secret (client.go:92 parity)."""
        secret = ""
        if self.cloud.credentials.gcp:
            secret = self.cloud.credentials.gcp.application_credentials
        if not secret:
            if not fake_mode():
                return None
            secret = "fake-tpu-control-plane"
        return DeterministicSSHKeyPair(secret, self.identifier.long())


def list_tpu_tasks(cloud: Cloud) -> List[Identifier]:
    if fake_mode():
        client = FakeTpuControlPlane()
    else:
        credentials = ""
        if cloud.credentials.gcp:
            credentials = cloud.credentials.gcp.application_credentials
        project = json.loads(credentials).get("project_id", "") if credentials else ""
        client = RestTpuClient(project=project, zone=resolve_zone(str(cloud.region)),
                               credentials_json=credentials)
    identifiers = []
    seen = set()
    for name in client.list_queued_resources():
        base = name.rsplit("-", 1)[0]
        if base in seen:
            continue
        seen.add(base)
        try:
            identifiers.append(Identifier.parse(base))
        except WrongIdentifierError:
            continue
    return identifiers
