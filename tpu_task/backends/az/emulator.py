"""Loopback ARM (Azure Resource Manager) emulator over HTTP.

Drives :class:`~tpu_task.backends.az.api.ArmClient` through real sockets:
Bearer auth, the shared retry layer, JSON parsing, and the
``provisioningState`` poller (``wait_provisioned``) all run for real — the
control-plane analog of ``storage/object_store_emulators.py``'s Azure Blob
loopback, completing the per-backend set started with the TPU and EC2/ASG
emulators. Stateful: resource groups contain their resources the way ARM's
containment works, so deleting the group IS the teardown the real
composition relies on (/root/reference/task/az/task.go).

Shapes follow the ARM REST conventions the client exercises: PUT upsert
echoing the resource with an ``id`` and ``properties.provisioningState``,
``listKeys`` POST on storage accounts, VMSS ``instanceView`` /
``publicipaddresses`` subresources, and 404 for anything missing. Newly
created storage accounts and scale sets answer one ``Creating`` poll before
``Succeeded`` so the backoff poller actually loops.

The PUT handler also enforces the ARM rule that bit this codebase once
(ADVICE r3): a security rule carrying BOTH the singular and plural form of
an address field (``sourceAddressPrefix`` + ``sourceAddressPrefixes``) is
rejected with 400, so a regression fails loudly in tests instead of only
against live ARM.

Test hooks: ``auth_headers`` records every Authorization header;
``evict(name)`` zeroes a scale set's running count the way a spot eviction
does (capacity stays — Azure bills intent, not instances).
"""

from __future__ import annotations

import re
from typing import Dict, List

from tpu_task.backends.loopback import JsonBearerHandler, LoopbackControlPlane

_RG_PATH = re.compile(r"^/subscriptions/([^/]+)/resourcegroups(?:/([^/?]+))?$",
                      re.IGNORECASE)
_RESOURCE_PATH = re.compile(
    r"^/subscriptions/([^/]+)/resourcegroups/([^/]+)/providers/"
    r"([^/]+)/([^/]+)/([^/?]+)(/[^?]+)?$", re.IGNORECASE)

_ADDRESS_SIDES = ("source", "destination")

FIXED_ACCOUNT_KEY = "bG9vcGJhY2stYWNjb3VudC1rZXk="  # valid base64 for SharedKey


def _validate_nsg(body: dict) -> str:
    """ARM rejects rules specifying both AddressPrefix and AddressPrefixes
    for one side — the exact live-ARM behavior ADVICE r3 flagged."""
    for rule in body.get("properties", {}).get("securityRules", []):
        properties = rule.get("properties", {})
        for side in _ADDRESS_SIDES:
            if (f"{side}AddressPrefix" in properties
                    and f"{side}AddressPrefixes" in properties):
                return (f"rule {rule.get('name', '?')}: {side}AddressPrefix "
                        f"and {side}AddressPrefixes are mutually exclusive")
    return ""


def _not_found(path: str):
    return 404, {"error": {"code": "ResourceNotFound", "message": path}}


class _ArmHandler(JsonBearerHandler):
    # ARM's 401 shape carries a string error code, not a numeric one.
    unauthorized_body = b'{"error": {"code": "AuthenticationFailed"}}'


class LoopbackArm(LoopbackControlPlane):
    handler_class = _ArmHandler

    def __init__(self):
        super().__init__()
        # rg name -> {resource key "provider/type/name" -> body}
        self.groups: Dict[str, Dict[str, dict]] = {}
        self.auth_headers: List[str] = []
        # resource key -> remaining "Creating" polls before Succeeded
        self._pending: Dict[str, int] = {}
        self._evicted: Dict[str, bool] = {}

    # -- client wiring ---------------------------------------------------------
    def attach(self, client) -> None:
        from tpu_task.backends.az.api import MANAGEMENT
        from tpu_task.storage.object_store_emulators import loopback_transport

        client._token._fetch = lambda: ("loopback-token", 3600.0)
        client._urlopen = loopback_transport(MANAGEMENT, self.port)

    # -- test hooks ------------------------------------------------------------
    def evict(self, name: str) -> None:
        """Spot eviction: instances gone, sku capacity (intent) unchanged."""
        self._evicted[name] = True

    # -- request handling ------------------------------------------------------
    def handle(self, method: str, path: str, query: dict, body: dict):
        rg = _RG_PATH.match(path)
        if rg:
            _sub, name = rg.groups()
            if name is None:  # list
                return 200, {"value": [{"name": group}
                                       for group in sorted(self.groups)]}
            if method == "PUT":
                self.groups.setdefault(name, {})
                return 200, {"name": name, "location": body.get("location")}
            if name not in self.groups:
                return _not_found(path)
            if method == "DELETE":
                del self.groups[name]  # containment: children go with it
                return 200, {}
            return 200, {"name": name}

        resource = _RESOURCE_PATH.match(path)
        if not resource:
            return _not_found(path)
        _sub, group, provider, rtype, name, action = resource.groups()
        if group not in self.groups:
            return _not_found(path)
        resources = self.groups[group]
        key = f"{provider}/{rtype}/{name}"

        if action:
            return self._subresource(resources, key, rtype, name,
                                     action.strip("/"), method)
        if method == "PUT":
            if rtype == "networkSecurityGroups":
                problem = _validate_nsg(body)
                if problem:
                    return 400, {"error": {"code": "SecurityRuleInvalid...",
                                           "message": problem}}
            resources[key] = body
            if rtype in ("storageAccounts", "virtualMachineScaleSets"):
                self._pending[key] = 1  # one Creating poll, then Succeeded
            return 200, self._echo(resources, key, rtype, name, path)
        if key not in resources:
            return _not_found(path)
        if method == "DELETE":
            del resources[key]
            return 200, {}
        if method == "PATCH":
            stored = resources[key]
            if "sku" in body:  # VMSS scale: merge capacity into intent
                stored.setdefault("sku", {}).update(body["sku"])
            return 200, self._echo(resources, key, rtype, name, path)
        return 200, self._echo(resources, key, rtype, name, path)

    def _echo(self, resources: dict, key: str, rtype: str, name: str,
              path: str) -> dict:
        stored = resources[key]
        state = "Succeeded"
        if self._pending.get(key, 0) > 0:
            self._pending[key] -= 1
            state = "Creating"
        payload = {
            "id": path.split("?")[0],
            "name": name,
            **{field: stored[field]
               for field in ("location", "sku", "tags") if field in stored},
            "properties": {**stored.get("properties", {}),
                           "provisioningState": state},
        }
        if rtype == "virtualNetworks":
            payload["properties"]["subnets"] = [
                {"name": subnet.get("name", ""),
                 "id": f"{payload['id']}/subnets/{subnet.get('name', '')}",
                 **subnet}
                for subnet in stored.get("properties", {}).get("subnets", [])]
        return payload

    def _subresource(self, resources: dict, key: str, rtype: str, name: str,
                     action: str, method: str):
        if key not in resources:
            return _not_found(f"{key}/{action}")
        if rtype == "storageAccounts" and action == "listKeys":
            return 200, {"keys": [{"keyName": "key1",
                                   "value": FIXED_ACCOUNT_KEY}]}
        if rtype == "virtualMachineScaleSets":
            capacity = int(resources[key].get("sku", {}).get("capacity", 0))
            running = 0 if self._evicted.get(name) else capacity
            if action == "instanceView":
                return 200, {
                    "virtualMachine": {"statusesSummary": [
                        {"code": "ProvisioningState/succeeded",
                         "count": running}]},
                    "statuses": [{
                        "code": "ProvisioningState/succeeded",
                        "level": "Info",
                        "displayStatus": "Provisioning succeeded",
                        "message": f"{running} of {capacity} instances up",
                        "time": "2026-07-30T00:00:00Z",
                    }],
                }
            if action == "publicipaddresses":
                return 200, {"value": [
                    {"properties": {"ipAddress": f"20.0.0.{index + 4}"}}
                    for index in range(running)]}
        return _not_found(f"{key}/{action}")
