from tpu_task.backends.az.task import (
    AZ_REGIONS,
    AZ_SIZES,
    AZRealTask,
    AZTask,
    list_az_tasks,
    new_az_task,
    resolve_az_machine,
    resolve_az_region,
    validate_arm_id,
)

__all__ = [
    "AZ_REGIONS",
    "AZ_SIZES",
    "AZRealTask",
    "AZTask",
    "list_az_tasks",
    "new_az_task",
    "resolve_az_machine",
    "resolve_az_region",
    "validate_arm_id",
]
