"""Azure resource primitives over the ARM client.

Mirrors the reference's L2 objects (/root/reference/task/az/resources/):
ResourceGroup (root container — resource_group.go), VirtualNetwork with an
NSG-bound subnet (resource_virtual_network.go, resource_subnet.go,
resource_security_group.go), StorageAccount + BlobContainer
(resource_storage_account.go, resource_blob_container.go), and the
VirtualMachineScaleSet (resource_virtual_machine_scale_set.go: capacity 0,
CustomData bootstrap, {user}@{publisher}:{offer}:{sku}:{version} image
grammar, spot eviction-policy Delete + BillingProfile, Read folding
instance-view summaries into Status and per-VM public IPs into Addresses).

Deleting the resource group tears everything down — ARM's containment is
the teardown mechanism the reference leans on (task/az/task.go).
"""

from __future__ import annotations

import re
from datetime import datetime, timezone
from typing import Dict, List, Optional

from tpu_task.backends.az.api import API_VERSIONS, ArmClient
from tpu_task.common.errors import ResourceNotFoundError
from tpu_task.common.values import Event, Firewall

IMAGE_ALIASES = {
    "ubuntu": "ubuntu@Canonical:0001-com-ubuntu-server-focal:20_04-lts:latest",
    "nvidia": "ubuntu@microsoft-dsvm:ubuntu-2004:2004-gen2:latest",
}
_IMAGE_RE = re.compile(r"^([^@]+)@([^:]+):([^:]+):([^:]+):([^:]+)(#plan)?$")


def parse_image(identifier: str):
    """``{user}@{publisher}:{offer}:{sku}:{version}[#plan]`` →
    (ssh_user, image_reference_dict, plan?) — scale_set.go:265-285."""
    image = IMAGE_ALIASES.get(identifier or "ubuntu", identifier or "ubuntu")
    image = IMAGE_ALIASES.get("ubuntu") if image == "" else image
    match = _IMAGE_RE.match(image)
    if not match:
        raise ValueError(f"invalid machine image format: {identifier!r} "
                         "(use {user}@{publisher}:{offer}:{sku}:{version})")
    user, publisher, offer, sku, version, plan = match.groups()
    reference = {"publisher": publisher, "offer": offer, "sku": sku,
                 "version": version}
    return user, reference, bool(plan)


class ResourceGroup:
    def __init__(self, client: ArmClient, name: str, location: str):
        self.client = client
        self.name = name
        self.location = location
        self.path = client._rg_path(name)

    def create(self) -> None:
        self.client.request("PUT", self.path, API_VERSIONS["resourcegroups"],
                            {"location": self.location})

    def read(self) -> None:
        self.client.request("GET", self.path, API_VERSIONS["resourcegroups"])

    def delete(self) -> None:
        try:
            self.client.request("DELETE", self.path,
                                API_VERSIONS["resourcegroups"])
        except ResourceNotFoundError:
            pass


class SecurityGroup:
    """NSG with allow rules from the task Firewall (priority 100+i inbound,
    intra-VNet traffic rides Azure's default rules)."""

    def __init__(self, client: ArmClient, resource_group: str, name: str,
                 location: str, firewall: Firewall):
        self.client = client
        self.name = name
        self.location = location
        self.firewall = firewall
        self.path = client.provider_path(
            resource_group, "Microsoft.Network",
            f"networkSecurityGroups/{name}")
        self.resource_id = ""

    def _rule(self, name: str, priority: int, direction: str, access: str,
              port: str, nets: List[str]) -> dict:
        # The firewall's nets constrain the REMOTE side: sources for
        # inbound rules, destinations for outbound. ARM rejects rules that
        # carry both the singular and plural form of an address field, so
        # emit exactly one per side.
        def side(prefix: str) -> dict:
            if len(nets) > 1:
                return {f"{prefix}AddressPrefixes": nets}
            return {f"{prefix}AddressPrefix": nets[0] if nets else "*"}

        remote = "source" if direction == "Inbound" else "destination"
        local = "destination" if direction == "Inbound" else "source"
        return {
            "name": name,
            "properties": {
                "priority": priority,
                "direction": direction,
                "access": access,
                "protocol": "*",
                "sourcePortRange": "*",
                "destinationPortRange": port,
                **side(remote),
                f"{local}AddressPrefix": "*",
            },
        }

    def body(self) -> dict:
        """FirewallRule semantics (values.py): ports/nets None = allow any;
        specified-but-empty = allow none. Azure defaults: inbound internet
        denied, outbound allowed — so 'allow any' ingress needs an explicit
        rule and restricted egress needs an explicit deny."""
        rules = []
        ingress = self.firewall.ingress
        ingress_nets = (None if ingress.nets is None
                        else [str(net) for net in ingress.nets])
        if ingress_nets == []:
            pass  # allow none: Azure's default inbound deny covers it
        elif ingress.ports is None:
            rules.append(self._rule(f"{self.name}-in-any", 100, "Inbound",
                                    "Allow", "*", ingress_nets or []))
        else:
            for index, port in enumerate(ingress.ports):
                rules.append(self._rule(f"{self.name}-in-{port}", 100 + index,
                                        "Inbound", "Allow", str(port),
                                        ingress_nets or []))
        egress = self.firewall.egress
        egress_nets = (None if egress.nets is None
                       else [str(net) for net in egress.nets])
        if egress.ports is None and egress_nets is None:
            pass  # allow any: Azure's default outbound allow covers it
        elif egress_nets == []:
            rules.append(self._rule(f"{self.name}-out-deny", 4000,
                                    "Outbound", "Deny", "*", []))
        else:
            if egress.ports is None:
                # ports None = every port (values.py:74-77): any-port Allow
                # for the named nets, then the catch-all deny.
                rules.append(self._rule(f"{self.name}-out-any", 100,
                                        "Outbound", "Allow", "*",
                                        egress_nets or []))
            else:
                for index, port in enumerate(egress.ports):
                    rules.append(self._rule(f"{self.name}-out-{port}",
                                            100 + index, "Outbound", "Allow",
                                            str(port), egress_nets or []))
            rules.append(self._rule(f"{self.name}-out-deny", 4000,
                                    "Outbound", "Deny", "*", []))
        return {"location": self.location,
                "properties": {"securityRules": rules}}

    def create(self) -> None:
        resource = self.client.request(
            "PUT", self.path, API_VERSIONS["Microsoft.Network"], self.body())
        self.resource_id = resource.get("id", self.path)

    def delete(self) -> None:
        try:
            self.client.request("DELETE", self.path,
                                API_VERSIONS["Microsoft.Network"])
        except ResourceNotFoundError:
            pass


class VirtualNetwork:
    """10.0.0.0/16 VNet with one NSG-bound subnet
    (resource_virtual_network.go, resource_subnet.go)."""

    def __init__(self, client: ArmClient, resource_group: str, name: str,
                 location: str, security_group: SecurityGroup):
        self.client = client
        self.name = name
        self.location = location
        self.security_group = security_group
        self.path = client.provider_path(
            resource_group, "Microsoft.Network", f"virtualNetworks/{name}")
        self.subnet_id = ""

    def create(self) -> None:
        resource = self.client.request(
            "PUT", self.path, API_VERSIONS["Microsoft.Network"], {
                "location": self.location,
                "properties": {
                    "addressSpace": {"addressPrefixes": ["10.0.0.0/16"]},
                    "subnets": [{
                        "name": self.name,
                        "properties": {
                            "addressPrefix": "10.0.0.0/16",
                            "networkSecurityGroup": {
                                "id": self.security_group.resource_id},
                        },
                    }],
                },
            })
        subnets = resource.get("properties", {}).get("subnets", [])
        self.subnet_id = (subnets[0].get("id", "") if subnets
                          else f"{self.path}/subnets/{self.name}")

    def delete(self) -> None:
        try:
            self.client.request("DELETE", self.path,
                                API_VERSIONS["Microsoft.Network"])
        except ResourceNotFoundError:
            pass


class StorageAccount:
    """Per-task storage account named identifier.short() (24-char limit —
    resource_storage_account.go:16-23), Standard_LRS."""

    def __init__(self, client: ArmClient, resource_group: str, name: str,
                 location: str):
        self.client = client
        self.name = name
        self.location = location
        self.path = client.provider_path(
            resource_group, "Microsoft.Storage", f"storageAccounts/{name}")

    def create(self) -> None:
        self.client.request("PUT", self.path, API_VERSIONS["Microsoft.Storage"], {
            "location": self.location,
            "kind": "StorageV2",
            "sku": {"name": "Standard_LRS"},
        })
        self.client.wait_provisioned(self.path,
                                     API_VERSIONS["Microsoft.Storage"])

    def key(self) -> str:
        payload = self.client.request(
            "POST", f"{self.path}/listKeys", API_VERSIONS["Microsoft.Storage"])
        keys = payload.get("keys", [])
        if not keys:
            raise ResourceNotFoundError(f"no keys for {self.name}")
        return keys[0].get("value", "")

    def delete(self) -> None:
        try:
            self.client.request("DELETE", self.path,
                                API_VERSIONS["Microsoft.Storage"])
        except ResourceNotFoundError:
            pass


class BlobContainer:
    """Blob container via the data plane (SharedKey PUT restype=container —
    resource_blob_container.go)."""

    def __init__(self, account: str, key: str, name: str):
        from tpu_task.storage.cloud_backends import AzureBlobBackend

        self.account = account
        self.account_key = key
        self.name = name
        self.backend = AzureBlobBackend(name, config={"account": account,
                                                      "key": key})

    def create(self) -> None:
        import urllib.error

        try:
            self.backend._request("PUT", f"/{self.name}",
                                  {"restype": "container"})
        except urllib.error.HTTPError as error:
            if error.code != 409:  # ContainerAlreadyExists → idempotent
                raise

    def connection_string(self) -> str:
        from tpu_task.storage import Connection

        return str(Connection(backend="azureblob", container=self.name,
                              config={"account": self.account,
                                      "key": self.account_key}))


class VirtualMachineScaleSet:
    """VMSS at capacity 0 (resource_virtual_machine_scale_set.go:64-235):
    CustomData bootstrap, spot eviction Delete + BillingProfile max price
    (>0 cap, 0 → -1 no cap), per-instance public IPs."""

    def __init__(self, client: ArmClient, resource_group: str, name: str,
                 location: str, *, vm_size: str = "", subnet_id: str = "",
                 image_reference: Optional[dict] = None, ssh_user: str = "",
                 ssh_public_key: str = "", custom_data_b64: str = "",
                 spot: float = -1.0, disk_size_gb: int = -1,
                 identity_ids: Optional[List[str]] = None,
                 tags: Optional[Dict[str, str]] = None):
        self.client = client
        self.resource_group = resource_group
        self.name = name
        self.location = location
        self.vm_size = vm_size
        self.subnet_id = subnet_id
        self.image_reference = image_reference or {}
        self.ssh_user = ssh_user
        self.ssh_public_key = ssh_public_key
        self.custom_data_b64 = custom_data_b64
        self.spot = spot
        self.disk_size_gb = disk_size_gb
        self.identity_ids = identity_ids or []
        self.tags = tags or {}
        self.path = client.provider_path(
            resource_group, "Microsoft.Compute",
            f"virtualMachineScaleSets/{name}")
        self.addresses: List[str] = []
        self.events: List[Event] = []
        self.running = 0
        self.capacity = 0
        self.read_tags: Dict[str, str] = {}

    def body(self) -> dict:
        os_profile = {
            "computerNamePrefix": "tpi",
            "adminUsername": self.ssh_user,
            "customData": self.custom_data_b64,
            "linuxConfiguration": {
                "disablePasswordAuthentication": True,
                "ssh": {"publicKeys": [{
                    "path": f"/home/{self.ssh_user}/.ssh/authorized_keys",
                    "keyData": self.ssh_public_key,
                }]},
            },
        }
        storage_profile: dict = {"imageReference": self.image_reference}
        if self.disk_size_gb > 0:  # Size.storage honored
            storage_profile["osDisk"] = {
                "createOption": "FromImage",
                "diskSizeGB": self.disk_size_gb,
            }
        profile: dict = {
            "osProfile": os_profile,
            "storageProfile": storage_profile,
            "networkProfile": {"networkInterfaceConfigurations": [{
                "name": self.name,
                "properties": {
                    "primary": True,
                    "ipConfigurations": [{
                        "name": self.name,
                        "properties": {
                            "subnet": {"id": self.subnet_id},
                            "publicIPAddressConfiguration": {
                                "name": self.name,
                                "properties": {
                                    "idleTimeoutInMinutes": 15}},
                        },
                    }],
                },
            }]},
        }
        if self.spot >= 0:
            # Spot with eviction Delete; 0 → maxPrice -1 = on-demand cap
            # (scale_set.go:219-229).
            profile["priority"] = "Spot"
            profile["evictionPolicy"] = "Delete"
            profile["billingProfile"] = {
                "maxPrice": self.spot if self.spot > 0 else -1}
        body: dict = {
            "location": self.location,
            "sku": {"name": self.vm_size, "tier": "Standard", "capacity": 0},
            "tags": self.tags,
            "properties": {
                "overprovision": False,
                "upgradePolicy": {"mode": "Manual"},
                "virtualMachineProfile": profile,
            },
        }
        if self.identity_ids:
            body["identity"] = {
                "type": "UserAssigned",
                "userAssignedIdentities": {
                    arm_id: {} for arm_id in self.identity_ids},
            }
        return body

    def create(self) -> None:
        self.client.request("PUT", self.path, API_VERSIONS["Microsoft.Compute"],
                            self.body())
        self.client.wait_provisioned(self.path,
                                     API_VERSIONS["Microsoft.Compute"])

    def read(self) -> None:
        resource = self.client.request("GET", self.path,
                                       API_VERSIONS["Microsoft.Compute"])
        self.capacity = int(resource.get("sku", {}).get("capacity", 0))
        self.read_tags = dict(resource.get("tags", {}))

        view = self.client.request("GET", f"{self.path}/instanceView",
                                   API_VERSIONS["Microsoft.Compute"])
        self.running = 0
        for summary in view.get("virtualMachine", {}).get(
                "statusesSummary", []):
            if summary.get("code") == "ProvisioningState/succeeded":
                self.running = int(summary.get("count", 0))
        self.events = []
        for status in view.get("statuses", []):
            stamp = datetime.fromtimestamp(0, tz=timezone.utc)
            try:
                stamp = datetime.fromisoformat(
                    status.get("time", "").replace("Z", "+00:00"))
            except ValueError:
                pass
            self.events.append(Event(
                time=stamp, code=status.get("code", ""),
                description=[status.get("level", ""),
                             status.get("displayStatus", ""),
                             status.get("message", "")]))

        self.addresses = []
        ips = self.client.request(
            "GET", f"{self.path}/publicipaddresses",
            API_VERSIONS["Microsoft.Network"])
        for item in ips.get("value", []):
            address = item.get("properties", {}).get("ipAddress", "")
            if address:
                self.addresses.append(address)

    def scale(self, capacity: int) -> None:
        self.client.request("PATCH", self.path,
                            API_VERSIONS["Microsoft.Compute"],
                            {"sku": {"capacity": capacity}})

    def delete(self) -> None:
        try:
            self.client.request("DELETE", self.path,
                                API_VERSIONS["Microsoft.Compute"])
        except ResourceNotFoundError:
            pass
