"""Azure backend: reference-parity semantics on the hermetic control plane.

Size and region maps mirror /root/reference/task/az/resources/
resource_virtual_machine_scale_set.go:111-124 and task/az/client/client.go:
65-70; the user-assigned-identity ARM-ID validator mirrors
data_source_permission_set.go:18-44 (comma-separated list). Spot semantics
(VMSS eviction-policy Delete + BillingProfile, resource_virtual_machine_
scale_set.go:219-229): >0 is the max price, 0 maps to -1 (no cap). The real
ARM control plane is not wired this round (north star is Cloud TPU);
lifecycle semantics run on the hermetic scaling-group plane.
"""

from __future__ import annotations

import re
from typing import Dict, List

from tpu_task.backends.group_task import GroupBackedTask
from tpu_task.common.cloud import Cloud
from tpu_task.common.identifier import Identifier, WrongIdentifierError

AZ_SIZES: Dict[str, str] = {
    "s": "Standard_B1s",
    "m": "Standard_F8s_v2",
    "l": "Standard_F32s_v2",
    "xl": "Standard_F64s_v2",
    "m+t4": "Standard_NC4as_T4_v3",
    "m+k80": "Standard_NC6",
    "l+k80": "Standard_NC12",
    "xl+k80": "Standard_NC24",
    "m+v100": "Standard_NC6s_v3",
    "l+v100": "Standard_NC12s_v3",
    "xl+v100": "Standard_NC24s_v3",
}

AZ_REGIONS: Dict[str, str] = {
    "us-east": "eastus",
    "us-west": "westus2",
    "eu-north": "northeurope",
    "eu-west": "westeurope",
}

_VM_SIZE_RE = re.compile(r"^[A-Za-z0-9_]+$")
_ARM_ID_RE = re.compile(
    r"^/subscriptions/[0-9a-fA-F-]{36}"
    r"/resourceGroups/[^/]+"
    r"/providers/Microsoft\.ManagedIdentity"
    r"/userAssignedIdentities/[^/]+$"
)


def resolve_az_machine(machine: str) -> str:
    machine = AZ_SIZES.get(machine, machine)
    if not _VM_SIZE_RE.match(machine):
        raise ValueError(f"invalid Azure VM size: {machine!r}")
    return machine


def resolve_az_region(region: str) -> str:
    region = str(region)
    if region in AZ_REGIONS:
        return AZ_REGIONS[region]
    if re.match(r"^[a-z]+[a-z0-9]*$", region):
        return region
    raise ValueError(f"cannot resolve Azure region {region!r}")


def validate_arm_id(permission_set: str) -> List[str]:
    """Comma-separated user-assigned-identity ARM IDs
    (data_source_permission_set.go:18-44)."""
    ids = [item.strip() for item in permission_set.split(",") if item.strip()]
    for arm_id in ids:
        if not _ARM_ID_RE.match(arm_id):
            raise ValueError(f"invalid user-assigned identity ARM id: {arm_id!r}")
    return ids


class AZTask(GroupBackedTask):
    provider_name = "az"

    def validate(self) -> None:
        self.vm_size = resolve_az_machine(self.spec.size.machine or "m")
        self.region = resolve_az_region(str(self.cloud.region))
        validate_arm_id(self.spec.permission_set)

    def extra_environment(self) -> Dict[str, str]:
        env: Dict[str, str] = {}
        creds = self.cloud.credentials.az
        if creds and creds.client_id:
            env["AZURE_CLIENT_ID"] = creds.client_id
            env["AZURE_CLIENT_SECRET"] = creds.client_secret
            env["AZURE_SUBSCRIPTION_ID"] = creds.subscription_id
            env["AZURE_TENANT_ID"] = creds.tenant_id
        return env


def list_az_tasks(cloud: Cloud) -> List[Identifier]:
    from tpu_task.backends.local.control_plane import list_groups

    identifiers = []
    for name in list_groups():
        try:
            identifiers.append(Identifier.parse(name))
        except WrongIdentifierError:
            continue
    return identifiers
