"""Azure backend: real ARM control plane (with credentials) or hermetic.

Size and region maps mirror /root/reference/task/az/resources/
resource_virtual_machine_scale_set.go:111-124 and task/az/client/client.go:
65-70; the user-assigned-identity ARM-ID validator mirrors
data_source_permission_set.go:18-44 (comma-separated list). Spot semantics
(VMSS eviction-policy Delete + BillingProfile, resource_virtual_machine_
scale_set.go:219-229): >0 is the max price, 0 maps to -1 (no cap). With
Azure credentials configured, AZRealTask provisions the reference's
resource-group-rooted DAG over ARM REST; without credentials the hermetic
scaling-group plane keeps the semantics testable.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

from tpu_task.backends.gcs_remote import GcsRemoteMixin
from tpu_task.backends.group_task import GroupBackedTask
from tpu_task.common.cloud import Cloud
from tpu_task.common.errors import ResourceNotFoundError
from tpu_task.common.identifier import Identifier, WrongIdentifierError
from tpu_task.common.values import Task as TaskSpec
from tpu_task.task import Task

AZ_SIZES: Dict[str, str] = {
    "s": "Standard_B1s",
    "m": "Standard_F8s_v2",
    "l": "Standard_F32s_v2",
    "xl": "Standard_F64s_v2",
    "m+t4": "Standard_NC4as_T4_v3",
    "m+k80": "Standard_NC6",
    "l+k80": "Standard_NC12",
    "xl+k80": "Standard_NC24",
    "m+v100": "Standard_NC6s_v3",
    "l+v100": "Standard_NC12s_v3",
    "xl+v100": "Standard_NC24s_v3",
}

AZ_REGIONS: Dict[str, str] = {
    "us-east": "eastus",
    "us-west": "westus2",
    "eu-north": "northeurope",
    "eu-west": "westeurope",
}

_VM_SIZE_RE = re.compile(r"^[A-Za-z0-9_]+$")
_ARM_ID_RE = re.compile(
    r"^/subscriptions/[0-9a-fA-F-]{36}"
    r"/resourceGroups/[^/]+"
    r"/providers/Microsoft\.ManagedIdentity"
    r"/userAssignedIdentities/[^/]+$"
)


def resolve_az_machine(machine: str) -> str:
    machine = AZ_SIZES.get(machine, machine)
    if not _VM_SIZE_RE.match(machine):
        raise ValueError(f"invalid Azure VM size: {machine!r}")
    return machine


def resolve_az_region(region: str) -> str:
    region = str(region)
    if region in AZ_REGIONS:
        return AZ_REGIONS[region]
    if re.match(r"^[a-z]+[a-z0-9]*$", region):
        return region
    raise ValueError(f"cannot resolve Azure region {region!r}")


def validate_arm_id(permission_set: str) -> List[str]:
    """Comma-separated user-assigned-identity ARM IDs
    (data_source_permission_set.go:18-44)."""
    ids = [item.strip() for item in permission_set.split(",") if item.strip()]
    for arm_id in ids:
        if not _ARM_ID_RE.match(arm_id):
            raise ValueError(f"invalid user-assigned identity ARM id: {arm_id!r}")
    return ids


def _az_real_mode(cloud: Cloud) -> bool:
    """Real ARM when the 4-tuple is configured and the hermetic plane isn't
    forced (mirrors the AWS/GCE gates)."""
    if os.environ.get("TPU_TASK_FAKE_TPU_ROOT"):
        return False
    creds = cloud.credentials.az
    return bool(creds and creds.client_id and creds.subscription_id)


def new_az_task(cloud: Cloud, identifier: Identifier, spec: TaskSpec):
    if _az_real_mode(cloud):
        return AZRealTask(cloud, identifier, spec)
    return AZTask(cloud, identifier, spec)


class AZTask(GroupBackedTask):
    provider_name = "az"

    def validate(self) -> None:
        self.vm_size = resolve_az_machine(self.spec.size.machine or "m")
        self.region = resolve_az_region(str(self.cloud.region))
        validate_arm_id(self.spec.permission_set)

    def extra_environment(self) -> Dict[str, str]:
        env: Dict[str, str] = {}
        creds = self.cloud.credentials.az
        if creds and creds.client_id:
            env["AZURE_CLIENT_ID"] = creds.client_id
            env["AZURE_CLIENT_SECRET"] = creds.client_secret
            env["AZURE_SUBSCRIPTION_ID"] = creds.subscription_id
            env["AZURE_TENANT_ID"] = creds.tenant_id
        return env


class AZRealTask(GcsRemoteMixin, Task):
    """Azure task over the real ARM control plane.

    Composition parity with /root/reference/task/az/task.go: a resource
    group roots the DAG — storage account + blob container, NSG + VNet +
    subnet, VMSS at capacity 0 — then Push and Start (sku.capacity =
    parallelism). Read folds instance-view summaries into Status, statuses
    into Events, and per-VM public IPs into Addresses
    (resource_virtual_machine_scale_set.go:240-301). Deleting the resource
    group is the teardown.
    """

    def __init__(self, cloud: Cloud, identifier: Identifier, spec: TaskSpec):
        from tpu_task.backends.az.api import ArmClient
        from tpu_task.backends.az.resources import (
            ResourceGroup, VirtualMachineScaleSet,
        )

        self.cloud = cloud
        self.identifier = identifier
        self.spec = spec
        self.vm_size = resolve_az_machine(spec.size.machine or "m")
        self.region = resolve_az_region(str(cloud.region))
        self.identity_ids = validate_arm_id(spec.permission_set)
        creds = cloud.credentials.az
        self.client = ArmClient(creds.subscription_id, creds.tenant_id,
                                creds.client_id, creds.client_secret)
        self.resource_group = ResourceGroup(self.client, identifier.long(),
                                            self.region)
        self.scale_set = VirtualMachineScaleSet(
            self.client, identifier.long(), identifier.long(), self.region)
        self._remote_record: Optional[str] = None  # lazy tag lookup
        self._account_key: Optional[str] = None

    # -- plumbing -------------------------------------------------------------
    def _storage_account(self):
        from tpu_task.backends.az.resources import StorageAccount

        return StorageAccount(self.client, self.identifier.long(),
                              self.identifier.short(), self.region)

    def _container(self):
        from tpu_task.backends.az.resources import BlobContainer

        if self._account_key is None:
            self._account_key = self._storage_account().key()
        return BlobContainer(self.identifier.short(), self._account_key,
                             self.identifier.long())

    def _remote(self) -> str:
        if self.spec.remote_storage is not None:
            return self._remote_storage_connection(backend="azureblob")
        recorded = self._recorded_remote()
        if recorded:
            return recorded
        return self._container().connection_string()

    def _recorded_remote(self) -> str:
        """The remote recorded (sanitized) as a VMSS tag; the account key is
        re-fetched via listKeys rather than stored anywhere. Reuses tags a
        prior scale_set.read() already fetched — no extra ARM round-trips
        per poll tick."""
        if self._remote_record is not None:
            return self._remote_record
        if self.scale_set.read_tags:
            recorded = self.scale_set.read_tags.get("tpu-task-remote", "")
        else:
            try:
                self.scale_set.read()
                recorded = self.scale_set.read_tags.get("tpu-task-remote", "")
            except ResourceNotFoundError:
                recorded = ""
        self._remote_record = self._with_local_credentials(recorded)
        return self._remote_record

    def _with_local_credentials(self, remote: str) -> str:
        if not remote.startswith(":azureblob"):
            return remote
        from tpu_task.storage import Connection

        conn = Connection.parse(remote)
        if conn.config.get("account") == self.identifier.short():
            conn.config["key"] = self._container().account_key
        elif "key" not in conn.config:
            import logging

            logging.getLogger("tpu_task").warning(
                "recorded remote uses external account %r; supply its key "
                "via --storage-container-opts key=... for data access",
                conn.config.get("account", ""))
        return str(conn)

    def _credentials_env(self) -> Dict[str, str]:
        """Env map injected into the VM (data_source_credentials.go)."""
        creds = self.cloud.credentials.az
        return {
            "AZURE_CLIENT_ID": creds.client_id,
            "AZURE_CLIENT_SECRET": creds.client_secret,
            "AZURE_SUBSCRIPTION_ID": creds.subscription_id,
            "AZURE_TENANT_ID": creds.tenant_id,
            "TPU_TASK_REMOTE": self._remote(),
            "TPU_TASK_CLOUD_PROVIDER": "az",
            "TPU_TASK_CLOUD_REGION": str(self.cloud.region),
            "TPU_TASK_IDENTIFIER": self.identifier.long(),
        }

    def get_key_pair(self):
        from tpu_task.common.ssh import DeterministicSSHKeyPair

        return DeterministicSSHKeyPair(
            self.cloud.credentials.az.client_secret, self.identifier.long())

    def _custom_data(self) -> str:
        import base64
        import time as _time
        from datetime import datetime, timezone

        from tpu_task.machine import render_script

        timeout = self.spec.environment.timeout
        epoch = (None if timeout is None else datetime.fromtimestamp(
            _time.time() + timeout.total_seconds(), tz=timezone.utc))
        script = render_script(self.spec.environment.script,
                               self._credentials_env(),
                               self.spec.environment.variables, epoch,
                               agent_wheel_url=getattr(
                                   self, "_agent_wheel_url", ""))
        return base64.b64encode(script.encode()).decode()

    # -- lifecycle ------------------------------------------------------------
    def create(self) -> None:
        from tpu_task.backends.az.resources import (
            SecurityGroup, VirtualNetwork, parse_image,
        )
        from tpu_task.common.steps import Step, run_steps
        from tpu_task.storage import check_storage

        security_group = SecurityGroup(
            self.client, self.identifier.long(), self.identifier.long(),
            self.region, self.spec.firewall)
        network = VirtualNetwork(self.client, self.identifier.long(),
                                 self.identifier.long(), self.region,
                                 security_group)
        steps = [Step("Creating ResourceGroup...", self.resource_group.create)]
        if self.spec.remote_storage is not None:
            steps.append(Step("Verifying container...",
                              lambda: check_storage(self._remote())))
        else:
            steps += [
                Step("Creating StorageAccount...",
                     lambda: self._storage_account().create()),
                Step("Creating BlobContainer...",
                     lambda: self._container().create()),
            ]
        steps += [
            Step("Creating SecurityGroup...", security_group.create),
            Step("Creating VirtualNetwork...", network.create),
        ]
        run_steps(steps)

        from tpu_task.machine.wheel import stage_wheel

        self._agent_wheel_url = stage_wheel(self._remote())
        ssh_user, image_reference, _plan = parse_image(
            self.spec.environment.image)
        self.scale_set.vm_size = self.vm_size
        self.scale_set.subnet_id = network.subnet_id
        self.scale_set.image_reference = image_reference
        self.scale_set.ssh_user = ssh_user
        self.scale_set.ssh_public_key = self.get_key_pair().public_string()
        self.scale_set.custom_data_b64 = self._custom_data()
        self.scale_set.spot = float(self.spec.spot)
        self.scale_set.disk_size_gb = self.spec.size.storage
        self.scale_set.identity_ids = self.identity_ids
        self.scale_set.tags = {"tpu-task-remote": self._sanitized_remote(),
                               **self.cloud.tags}
        run_steps([
            Step("Creating VirtualMachineScaleSet...", self.scale_set.create),
            Step("Uploading Directory...", self.push),
            Step("Starting task...", self.start),
        ])

    def start(self) -> None:
        self.scale_set.scale(self.spec.parallelism)

    def stop(self) -> None:
        self.scale_set.scale(0)

    def read(self) -> None:
        self.scale_set.read()
        self.spec.addresses = list(self.scale_set.addresses)
        self.spec.status = self.status(running=self.scale_set.running)
        self.spec.events = self.events()

    def delete(self) -> None:
        import logging

        # Resolve the remote BEFORE the teardown removes the tag record;
        # a second delete (account already gone → listKeys 404) must stay
        # idempotent, and storage hiccups must never block the resource-
        # group teardown that actually stops the billing.
        try:
            remote = self._remote()
        except ResourceNotFoundError:
            remote = ""
        if remote and self.spec.environment.directory:
            try:
                self.pull()
            except ResourceNotFoundError:
                pass
        if remote and not self._is_per_task_bucket(remote):
            # Pre-allocated container: empty only this task's subdirectory.
            from tpu_task.storage import delete_storage

            try:
                delete_storage(remote)
            except ResourceNotFoundError:
                pass
            except Exception as error:
                logging.getLogger("tpu_task").warning(
                    "could not empty %s (%s); continuing with teardown",
                    remote, error)
        # The resource group contains everything (incl. the per-task
        # storage account): one delete is the full teardown (task/az/task.go).
        self.resource_group.delete()

    # -- observation (data plane inherited from GcsRemoteMixin) ---------------
    def status(self, running: Optional[int] = None):
        if running is None:
            if self.spec.status:
                return self.spec.status
            self.scale_set.read()
            running = self.scale_set.running
        return self._folded_status(running)

    def events(self):
        return list(self.scale_set.events)

    def observed_parallelism(self) -> Optional[int]:
        """sku.capacity from the VMSS's own record."""
        if not self.scale_set.capacity:
            try:
                self.scale_set.read()
            except ResourceNotFoundError:
                return None
        return self.scale_set.capacity or None


def list_az_tasks(cloud: Cloud) -> List[Identifier]:
    identifiers = []
    seen = set()

    def add(identifier: Identifier) -> None:
        if identifier.long() not in seen:
            seen.add(identifier.long())
            identifiers.append(identifier)

    if _az_real_mode(cloud):
        # ListResourceGroups backs `leo list` (resource_group.go:14).
        from tpu_task.backends.az.api import API_VERSIONS, ArmClient

        creds = cloud.credentials.az
        client = ArmClient(creds.subscription_id, creds.tenant_id,
                           creds.client_id, creds.client_secret)
        payload = client.request(
            "GET", f"/subscriptions/{client.subscription_id}/resourcegroups",
            API_VERSIONS["resourcegroups"])
        for item in payload.get("value", []):
            try:
                add(Identifier.parse(item.get("name", "")))
            except WrongIdentifierError:
                continue
    from tpu_task.backends.local.control_plane import list_groups

    for name in list_groups():
        try:
            add(Identifier.parse(name))
        except WrongIdentifierError:
            continue
    return identifiers
