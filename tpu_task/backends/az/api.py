"""Azure control-plane client: ARM REST over AAD client-credentials OAuth.

The reference drives Azure through 12 typed SDK clients under one authorizer
(/root/reference/task/az/client/client.go:20-53); this client speaks ARM's
JSON REST directly — one bearer token from login.microsoftonline.com, every
management call through the shared retry/refresh layer, 404/409 mapped to
the common NotFound/AlreadyExists semantics, and long-running operations
polled via provisioningState (the SDK futures' WaitForCompletionRef role).
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

from tpu_task.common.errors import ResourceAlreadyExistsError, ResourceNotFoundError

MANAGEMENT = "https://management.azure.com"

# api-versions per resource provider (matching the SDK versions the
# reference pins in its imports).
API_VERSIONS = {
    "resourcegroups": "2021-04-01",
    "Microsoft.Network": "2021-05-01",
    "Microsoft.Storage": "2021-08-01",
    "Microsoft.Compute": "2021-11-01",
}


class ArmClient:
    def __init__(self, subscription_id: str, tenant_id: str, client_id: str,
                 client_secret: str):
        from tpu_task.storage.http_util import OAuthToken

        self.subscription_id = subscription_id
        self.tenant_id = tenant_id
        self.client_id = client_id
        self.client_secret = client_secret
        self._token = OAuthToken(self._fetch_token)
        self._urlopen = None  # test hook: injectable transport
        self._sleep = None    # test hook: injectable backoff sleep

    def _fetch_token(self):
        import urllib.parse

        from tpu_task.storage.http_util import send

        body = urllib.parse.urlencode({
            "grant_type": "client_credentials",
            "client_id": self.client_id,
            "client_secret": self.client_secret,
            "scope": "https://management.azure.com/.default",
        }).encode()
        url = (f"https://login.microsoftonline.com/{self.tenant_id}"
               "/oauth2/v2.0/token")
        payload = json.loads(send(
            "POST", url, data=body,
            headers={"Content-Type": "application/x-www-form-urlencoded"},
            timeout=30, urlopen=self._urlopen,
            sleep=self._sleep or time.sleep))
        return payload["access_token"], float(payload.get("expires_in", 3600))

    def request(self, method: str, path: str, api_version: str,
                payload: Optional[dict] = None) -> dict:
        import urllib.error

        from tpu_task.storage.http_util import authorized_send

        url = f"{MANAGEMENT}{path}?api-version={api_version}"
        data = json.dumps(payload).encode() if payload is not None else None
        try:
            body = authorized_send(
                self._token, method, url, data=data,
                headers={"Content-Type": "application/json"},
                urlopen=self._urlopen, sleep=self._sleep or time.sleep)
            return json.loads(body or b"{}")
        except urllib.error.HTTPError as error:
            if error.code == 404:
                raise ResourceNotFoundError(path) from error
            if error.code == 409:
                raise ResourceAlreadyExistsError(path) from error
            raise

    def _rg_path(self, resource_group: str) -> str:
        return (f"/subscriptions/{self.subscription_id}/resourcegroups/"
                f"{resource_group}")

    def provider_path(self, resource_group: str, provider: str,
                      resource: str) -> str:
        return (f"{self._rg_path(resource_group)}/providers/{provider}/"
                f"{resource}")

    def wait_provisioned(self, path: str, api_version: str,
                         timeout: float = 900.0) -> dict:
        """Poll a resource until provisioningState Succeeded (2 s → 32 s
        backoff, the ARM analog of the reference's operation waiters)."""
        delay = 2.0
        deadline = time.time() + timeout
        sleep = self._sleep or time.sleep
        while True:
            resource = self.request("GET", path, api_version)
            state = resource.get("properties", {}).get("provisioningState", "")
            if state == "Succeeded":
                return resource
            if state in ("Failed", "Canceled"):
                raise RuntimeError(f"provisioning {state}: {path}")
            if time.time() > deadline:
                raise TimeoutError(f"provisioning timed out: {path}")
            sleep(delay)
            delay = min(delay * 2, 32.0)
