"""K8s task backend: manifest-driven real mode (kubectl), hermetic fallback.

Composition parity with /root/reference/task/k8s/task.go: ConfigMap + PVC +
indexed Job; no SSH keypair (task.go:330); Start/Stop unsupported on real
clusters (task.go:316-324 NotImplementedError). Real mode shells out to
``kubectl`` with manifests from ``render_manifests`` and is gated on a
kubeconfig being present (KUBECONFIG / KUBECONFIG_DATA — client/client.go);
without one, the hermetic scaling-group plane runs the job locally with
JOB_COMPLETION_INDEX ranks so indexed-completion semantics stay testable.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import tempfile
from typing import Dict, List, Optional

from tpu_task.backends.group_task import GroupBackedTask
from tpu_task.backends.k8s.machines import parse_k8s_machine
from tpu_task.backends.k8s.manifests import render_manifests
from tpu_task.common.cloud import Cloud
from tpu_task.common.errors import ResourceNotImplementedError
from tpu_task.common.identifier import Identifier, WrongIdentifierError
from tpu_task.common.ssh import DeterministicSSHKeyPair
from tpu_task.common.values import Task as TaskSpec


def _kubeconfig_path() -> Optional[str]:
    """KUBECONFIG_DATA env (written to a temp file) or KUBECONFIG."""
    data = os.environ.get("KUBECONFIG_DATA", "")
    if data:
        fd, path = tempfile.mkstemp(prefix="tpu-task-kubeconfig-")
        with os.fdopen(fd, "w") as handle:
            handle.write(data)
        return path
    path = os.environ.get("KUBECONFIG", "")
    return path if path and os.path.exists(path) else None


def real_mode() -> bool:
    return bool(shutil.which("kubectl")) and _kubeconfig_path() is not None


class K8STask(GroupBackedTask):
    provider_name = "k8s"

    def validate(self) -> None:
        parse_k8s_machine(self.spec.size.machine or "m")

    def extra_environment(self) -> Dict[str, str]:
        # Indexed-completion rank for the hermetic plane: the local agent
        # exports TPU_TASK_WORKER_ID; mirror it under the k8s-native name so
        # user scripts porting from real clusters keep working.
        return {"JOB_COMPLETION_INDEX": ""}

    def get_key_pair(self) -> Optional[DeterministicSSHKeyPair]:
        return None  # no SSH on k8s (task/k8s/task.go:330)

    # -- real-cluster mode ----------------------------------------------------
    def _kubectl(self, *argv: str, manifest: Optional[list] = None) -> str:
        config = _kubeconfig_path()
        command = ["kubectl", f"--kubeconfig={config}", *argv]
        result = subprocess.run(
            command, capture_output=True, text=True, timeout=300,
            input=json.dumps({"apiVersion": "v1", "kind": "List",
                              "items": manifest}) if manifest else None,
        )
        if result.returncode != 0:
            raise RuntimeError(f"kubectl failed: {result.stderr.strip()}")
        return result.stdout

    def create(self) -> None:
        if not real_mode():
            super().create()
            return
        manifests = render_manifests(self.identifier.long(), self.spec,
                                     region=str(self.cloud.region))
        self._kubectl("apply", "-f", "-", manifest=manifests)

    def delete(self) -> None:
        if not real_mode():
            super().delete()
            return
        self._kubectl("delete", "job,configmap,pvc",
                      "-l", f"tpu-task={self.identifier.long()}",
                      "--ignore-not-found=true")

    def start(self) -> None:
        if not real_mode():
            super().start()
            return
        raise ResourceNotImplementedError(
            "k8s jobs cannot be restarted (task/k8s/task.go:316-324)")

    def stop(self) -> None:
        if not real_mode():
            super().stop()
            return
        raise ResourceNotImplementedError(
            "k8s jobs cannot be stopped (task/k8s/task.go:316-324)")

    def logs(self) -> List[str]:
        if not real_mode():
            return super().logs()
        out = self._kubectl("logs", f"job/{self.identifier.long()}",
                            "--all-containers=true", "--prefix=true")
        return [out] if out else []


def list_k8s_tasks(cloud: Cloud) -> List[Identifier]:
    if real_mode():
        import json as json_module

        task = K8STask.__new__(K8STask)
        out = task._kubectl("get", "configmap", "-l", "tpu-task",
                            "-o", "json")
        identifiers = []
        for item in json_module.loads(out).get("items", []):
            name = item["metadata"]["labels"].get("tpu-task", "")
            try:
                identifiers.append(Identifier.parse(name))
            except WrongIdentifierError:
                continue
        return identifiers
    from tpu_task.backends.local.control_plane import list_groups

    identifiers = []
    for name in list_groups():
        try:
            identifiers.append(Identifier.parse(name))
        except WrongIdentifierError:
            continue
    return identifiers
