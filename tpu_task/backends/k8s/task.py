"""K8s task backend: manifest-driven real mode (kubectl), hermetic fallback.

Composition parity with /root/reference/task/k8s/task.go: ConfigMap + PVC +
indexed Job; no SSH keypair (task.go:330); Start/Stop unsupported on real
clusters (task.go:316-324 NotImplementedError). Real mode shells out to
``kubectl`` with manifests from ``render_manifests`` and is gated on a
kubeconfig being present (KUBECONFIG / KUBECONFIG_DATA — client/client.go);
without one, the hermetic scaling-group plane runs the job locally with
JOB_COMPLETION_INDEX ranks so indexed-completion semantics stay testable.

Real-mode observation and data plane (round-3 additions):

- ``read``/``status``/``events`` come from the cluster — Job counters map
  ``job.status.{active,succeeded,failed}`` exactly as the reference folds
  them (resource_job.go:337-344), events are the Job's event stream
  (resource_job.go:320-335), addresses are pod IPs.
- ``push``/``pull`` use an ephemeral transfer-mode Job sharing the workdir
  PVC plus ``kubectl cp`` (task.go:146-166 create-side, 207-230 +
  262-296 delete-side pull through a temp dir with output filtering).
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import shutil
import subprocess
import tempfile
import time
from contextlib import contextmanager
from datetime import datetime, timezone
from typing import Any, Dict, Iterator, List, Optional

from tpu_task.backends.group_task import GroupBackedTask
from tpu_task.backends.k8s.machines import parse_k8s_machine
from tpu_task.backends.k8s.manifests import (
    parse_workdir,
    render_manifests,
    render_transfer_job,
)
from tpu_task.common.cloud import Cloud
from tpu_task.common.errors import (
    ResourceNotFoundError,
    ResourceNotImplementedError,
)
from tpu_task.common.identifier import Identifier, WrongIdentifierError
from tpu_task.common.ssh import DeterministicSSHKeyPair
from tpu_task.common.values import Event, Status, StatusCode
from tpu_task.storage import limit_transfer, transfer

# KUBECONFIG_DATA is materialized to one temp file per distinct credential
# value, reused across calls and removed at exit (round-2 advisor: the old
# code leaked a new temp file per kubectl invocation).
_kubeconfig_cache: Dict[str, str] = {}


def _cleanup_kubeconfigs() -> None:
    for path in _kubeconfig_cache.values():
        try:
            os.unlink(path)
        except OSError:
            pass
    _kubeconfig_cache.clear()


atexit.register(_cleanup_kubeconfigs)


def _kubeconfig_path() -> Optional[str]:
    """KUBECONFIG_DATA env (written to a cached temp file) or KUBECONFIG."""
    data = os.environ.get("KUBECONFIG_DATA", "")
    if data:
        key = hashlib.sha256(data.encode()).hexdigest()
        path = _kubeconfig_cache.get(key)
        if path is None or not os.path.exists(path):
            fd, path = tempfile.mkstemp(prefix="tpu-task-kubeconfig-")
            with os.fdopen(fd, "w") as handle:
                handle.write(data)
            _kubeconfig_cache[key] = path
        return path
    path = os.environ.get("KUBECONFIG", "")
    return path if path and os.path.exists(path) else None


def real_mode() -> bool:
    return bool(shutil.which("kubectl")) and _kubeconfig_path() is not None


def namespace() -> str:
    """Target namespace; pinned so apply (manifest metadata) and every get/
    delete/cp agree even when the kubeconfig context names another one."""
    return os.environ.get("TPU_TASK_K8S_NAMESPACE", "default")


def kubectl(*argv: str, manifest: Optional[list] = None,
            timeout: Optional[float] = 300.0) -> str:
    """Run kubectl against the configured cluster; raise on failure.

    Module-level (not a method) so ``list_k8s_tasks`` needs no half-built
    task instance, and so tests fake exactly one seam. ``timeout=None``
    disables the cap (data-plane cp of large workdirs).
    """
    config = _kubeconfig_path()
    command = ["kubectl", f"--kubeconfig={config}",
               f"--namespace={namespace()}", *argv]
    result = subprocess.run(
        command, capture_output=True, text=True, timeout=timeout,
        input=json.dumps({"apiVersion": "v1", "kind": "List",
                          "items": manifest}) if manifest else None,
    )
    if result.returncode != 0:
        stderr = result.stderr.strip()
        # Only the API server's NotFound counts — a bare "not found" substring
        # also appears in unrelated failures (e.g. "tar: executable file not
        # found") that must not be treated as a missing resource.
        if "(NotFound)" in stderr:
            raise ResourceNotFoundError(stderr)
        raise RuntimeError(f"kubectl failed: {stderr}")
    return result.stdout


def _kubectl_json(*argv: str) -> Dict[str, Any]:
    return json.loads(kubectl(*argv, "-o", "json") or "{}")


def _parse_k8s_time(value: str) -> datetime:
    try:
        return datetime.fromisoformat(value.replace("Z", "+00:00"))
    except (ValueError, AttributeError):
        return datetime.fromtimestamp(0, tz=timezone.utc)


class K8STask(GroupBackedTask):
    provider_name = "k8s"

    def validate(self) -> None:
        parse_k8s_machine(self.spec.size.machine or "m")

    def get_key_pair(self) -> Optional[DeterministicSSHKeyPair]:
        return None  # no SSH on k8s (task/k8s/task.go:330)

    def workdir(self) -> str:
        """The grammar ``class:[size:]path`` puts the PVC on a storage
        class; the local sync directory is the path part
        (task/k8s/task.go:76-92)."""
        return parse_workdir(self.spec.environment.directory).path

    def _service_account_automount(self) -> Optional[bool]:
        """Verify ``permission_set`` names an existing ServiceAccount and
        return its automount setting (data_source_permission_set.go:34-50)."""
        name = self.spec.permission_set
        if not name:
            return None
        try:
            account = _kubectl_json("get", "serviceaccount", name)
        except ResourceNotFoundError:
            raise ResourceNotFoundError(
                f"service account {name!r} does not exist in namespace "
                f"{namespace()!r}") from None
        return account.get("automountServiceAccountToken")

    def _verify_remote_storage(self) -> None:
        """A pre-allocated PVC must exist before the Job references it
        (data_source_persistent_volume.go:31-41)."""
        if not self.spec.remote_storage:
            return
        claim = self.spec.remote_storage.container
        try:
            _kubectl_json("get", "pvc", claim)
        except ResourceNotFoundError:
            raise ResourceNotFoundError(
                f"persistent volume claim {claim!r} does not exist in "
                f"namespace {namespace()!r}") from None

    # -- real-cluster lifecycle -----------------------------------------------
    def create(self) -> None:
        if not real_mode():
            super().create()
            return
        automount = self._service_account_automount()
        self._verify_remote_storage()
        manifests = render_manifests(
            self.identifier.long(), self.spec, namespace=namespace(),
            region=str(self.cloud.region),
            automount_service_account_token=automount)
        *storage_objects, job = manifests
        # ConfigMap (+ PVC unless pre-allocated) first, then data upload
        # through a transfer pod while the claim is unclaimed, then the
        # real Job (task.go:129-176; ordering matters for ReadWriteOnce).
        kubectl("apply", "-f", "-", manifest=storage_objects)
        if self.workdir():
            self.push()
        kubectl("apply", "-f", "-", manifest=[job])

    def delete(self) -> None:
        if not real_mode():
            super().delete()
            return
        if self.workdir() and self._alive():
            try:
                # Free the PVC from the main Job before mounting it in the
                # transfer pod (task.go:207-230 deletes the Job first; the
                # pull is gated on Read succeeding, task.go:210, so an
                # idempotent delete of a gone task skips straight to cleanup).
                kubectl("delete", "job", self.identifier.long(),
                        "--ignore-not-found=true", "--wait=true")
                self.pull()
            except (ResourceNotFoundError, TimeoutError):
                pass
        kubectl("delete", "job,configmap,pvc",
                "-l", f"tpu-task={self.identifier.long()}",
                "--ignore-not-found=true")

    def start(self) -> None:
        if not real_mode():
            super().start()
            return
        raise ResourceNotImplementedError(
            "k8s jobs cannot be restarted (task/k8s/task.go:316-324)")

    def stop(self) -> None:
        if not real_mode():
            super().stop()
            return
        raise ResourceNotImplementedError(
            "k8s jobs cannot be stopped (task/k8s/task.go:316-324)")

    def _alive(self) -> bool:
        """True when the task's cluster objects still exist (delete gate)."""
        try:
            _kubectl_json("get", "job", self.identifier.long())
            return True
        except ResourceNotFoundError:
            return False

    # -- real-cluster observation ----------------------------------------------
    def read(self) -> None:
        if not real_mode():
            super().read()
            return
        job = _kubectl_json("get", "job", self.identifier.long())
        counters = job.get("status", {}) or {}
        self.spec.status = {
            StatusCode.ACTIVE: int(counters.get("active") or 0),
            StatusCode.SUCCEEDED: int(counters.get("succeeded") or 0),
            StatusCode.FAILED: int(counters.get("failed") or 0),
        }
        self.spec.events = self._cluster_events()
        self.spec.addresses = self._pod_addresses()

    def status(self) -> Status:
        if not real_mode():
            return super().status()
        if not self.spec.status:
            self.read()
        return self.spec.status

    def observed_parallelism(self):
        """Parallelism from the Job's own spec in real mode (a bare `read`
        holds only a default TaskSpec)."""
        if not real_mode():
            return super().observed_parallelism()
        try:
            job = _kubectl_json("get", "job", self.identifier.long())
        except ResourceNotFoundError:
            return None
        return int(job.get("spec", {}).get("parallelism") or 0) or None

    def events(self) -> List[Event]:
        if not real_mode():
            return super().events()
        return self._cluster_events()

    def _cluster_events(self) -> List[Event]:
        """Job event stream → Event records (resource_job.go:320-335)."""
        listing = _kubectl_json(
            "get", "events", "--field-selector",
            f"involvedObject.name={self.identifier.long()}")
        events = []
        for item in listing.get("items", []):
            stamp = (item.get("firstTimestamp")
                     or item.get("eventTime") or "")
            events.append(Event(
                time=_parse_k8s_time(stamp),
                code=item.get("message", ""),
                description=[item.get("reason", ""),
                             item.get("action", "")],
            ))
        return events

    def _pod_addresses(self) -> List[str]:
        listing = _kubectl_json(
            "get", "pods", "-l", f"tpu-task={self.identifier.long()}")
        return [item["status"]["podIP"]
                for item in listing.get("items", [])
                if item.get("status", {}).get("podIP")]

    def logs(self) -> List[str]:
        if not real_mode():
            return super().logs()
        # One entry per pod — `kubectl logs job/x` picks a single pod, which
        # drops every other worker's output for indexed parallelism > 1
        # (the reference streams each pod, resource_job.go:345-370).
        listing = _kubectl_json(
            "get", "pods", "-l", f"tpu-task={self.identifier.long()}")
        logs = []
        for item in listing.get("items", []):
            name = item["metadata"]["name"]
            try:
                out = kubectl("logs", name, "--all-containers=true",
                              "--timestamps=true")
            except RuntimeError:
                # Containers not started yet (ContainerCreating/Pending);
                # skip that pod, keep the others (resource_job.go:352-356).
                continue
            if out:
                logs.append(out)
        return logs

    # -- real-cluster data plane ----------------------------------------------
    @contextmanager
    def _transfer_pod(self) -> Iterator[str]:
        """Ephemeral sleep Job mounting the workdir PVC (task.go:146-166)."""
        name = f"{self.identifier.long()}-transfer"
        job = render_transfer_job(self.identifier.long(), self.spec,
                                  namespace=namespace(),
                                  region=str(self.cloud.region))
        kubectl("delete", "job", name, "--ignore-not-found=true",
                "--wait=true")
        kubectl("apply", "-f", "-", manifest=[job])
        try:
            yield self._wait_for_pod(f"tpu-task-transfer={self.identifier.long()}")
        finally:
            kubectl("delete", "job", name, "--ignore-not-found=true",
                    "--wait=true")

    def _wait_for_pod(self, selector: str, timeout: float = 300.0) -> str:
        """Poll until a pod matching ``selector`` is Running; return its name
        (reference WaitForPods, resources/common.go:17)."""
        interval = float(os.environ.get("TPU_TASK_K8S_POLL_PERIOD", "1"))
        deadline = time.monotonic() + timeout
        while True:
            listing = _kubectl_json("get", "pods", "-l", selector)
            for item in listing.get("items", []):
                if item.get("status", {}).get("phase") == "Running":
                    return item["metadata"]["name"]
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no running pod matched {selector!r} in {timeout}s")
            time.sleep(interval)

    def push(self) -> None:
        if not real_mode():
            super().push()
            return
        directory = self.workdir()
        if not directory:
            return
        # Apply the exclude rules locally before cp — kubectl cp has no
        # filter support, and the hermetic plane's push filters too.
        staging = tempfile.mkdtemp(prefix="tpu-task-push-")
        try:
            transfer(directory, staging,
                     list(self.spec.environment.exclude_list))
            with self._transfer_pod() as pod:
                kubectl("cp", staging, f"{pod}:/workdir", timeout=None)
        finally:
            shutil.rmtree(staging, ignore_errors=True)

    def pull(self) -> None:
        if not real_mode():
            super().pull()
            return
        directory = self.workdir()
        if not directory:
            return
        with self._transfer_pod() as pod:
            staging = tempfile.mkdtemp(prefix="tpu-task-pull-")
            try:
                kubectl("cp", f"{pod}:/workdir", staging, timeout=None)
                rules = limit_transfer(
                    self.spec.environment.directory_out,
                    list(self.spec.environment.exclude_list))
                transfer(staging, directory, rules)
            finally:
                shutil.rmtree(staging, ignore_errors=True)


def list_k8s_tasks(cloud: Cloud) -> List[Identifier]:
    if real_mode():
        listing = _kubectl_json("get", "configmap", "-l", "tpu-task")
        identifiers = []
        for item in listing.get("items", []):
            name = item["metadata"]["labels"].get("tpu-task", "")
            try:
                identifiers.append(Identifier.parse(name))
            except WrongIdentifierError:
                continue
        return identifiers
    from tpu_task.backends.local.control_plane import list_groups

    identifiers = []
    for name in list_groups():
        try:
            identifiers.append(Identifier.parse(name))
        except WrongIdentifierError:
            continue
    return identifiers
