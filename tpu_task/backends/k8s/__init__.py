from tpu_task.backends.k8s.machines import K8S_SIZES, K8sResources, parse_k8s_machine
from tpu_task.backends.k8s.manifests import render_manifests
from tpu_task.backends.k8s.task import K8STask, list_k8s_tasks

__all__ = [
    "K8S_SIZES",
    "K8STask",
    "K8sResources",
    "list_k8s_tasks",
    "parse_k8s_machine",
    "render_manifests",
]
