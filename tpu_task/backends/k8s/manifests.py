"""Kubernetes manifest rendering: ConfigMap + PVC + indexed Job.

The object shapes mirror /root/reference/task/k8s/task.go and
resources/resource_job.go: the task script travels in a ConfigMap mounted at
/script, the workdir in a PVC (RWX when parallelism > 1 —
resource_persistent_volume_claim.go:41-44), and the Job runs with
parallelism == completions, **Indexed completion mode when parallelism > 1**
(resource_job.go:135-140 — the rank mechanism), BackoffLimit high for
restart-on-failure (resource_job.go:130), and ActiveDeadlineSeconds as the
timeout (resource_job.go:142). Rendered as plain dicts (JSON == YAML subset)
so they golden-test cleanly and feed kubectl directly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from tpu_task.backends.k8s.machines import (
    K8S_IMAGES,
    K8sResources,
    parse_k8s_machine,
    parse_node_selectors,
)
from tpu_task.common.values import Task as TaskSpec

MAX_BACKOFF = 2147483647  # reference uses math.MaxInt32


def render_manifests(identifier: str, spec: TaskSpec, namespace: str = "default",
                     region: str = "") -> List[Dict[str, Any]]:
    resources = parse_k8s_machine(spec.size.machine or "m")
    selectors = parse_node_selectors(region)
    selectors.update(resources.node_selector())

    image = spec.environment.image or "ubuntu"
    image = K8S_IMAGES.get(image, image)

    labels = {"tpu-task": identifier}
    env = [{"name": name, "value": value}
           for name, value in sorted(spec.environment.variables.enrich().items())]
    env.append({"name": "TPI_TASK", "value": "true"})

    config_map = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": f"{identifier}-script", "namespace": namespace,
                     "labels": labels},
        "data": {"script": spec.environment.script},
    }

    pvc = {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": f"{identifier}-workdir", "namespace": namespace,
                     "labels": labels},
        "spec": {
            # RWX once multiple pods share the workdir
            # (resource_persistent_volume_claim.go:41-44).
            "accessModes": ["ReadWriteMany" if spec.parallelism > 1
                            else "ReadWriteOnce"],
            "resources": {"requests": {
                "storage": f"{spec.size.storage if spec.size.storage > 0 else 10}Gi",
            }},
        },
    }

    timeout = spec.environment.timeout
    job_spec: Dict[str, Any] = {
        "parallelism": spec.parallelism,
        "completions": spec.parallelism,
        "backoffLimit": MAX_BACKOFF,
        "template": {
            "metadata": {"labels": labels},
            "spec": {
                "restartPolicy": "Never",
                "terminationGracePeriodSeconds": 30,
                **({"nodeSelector": selectors} if selectors else {}),
                "containers": [{
                    "name": "task",
                    "image": image,
                    "command": ["/bin/sh", "-c", "exec /script/script"],
                    "env": env,
                    "resources": {"limits": resources.limits(spec.size.storage)},
                    "workingDir": "/workdir",
                    "volumeMounts": [
                        {"name": "script", "mountPath": "/script"},
                        {"name": "workdir", "mountPath": "/workdir"},
                    ],
                }],
                "volumes": [
                    {"name": "script", "configMap": {
                        "name": f"{identifier}-script", "defaultMode": 0o755}},
                    {"name": "workdir", "persistentVolumeClaim": {
                        "claimName": f"{identifier}-workdir"}},
                ],
            },
        },
    }
    if timeout is not None:
        job_spec["activeDeadlineSeconds"] = int(timeout.total_seconds())
    if spec.parallelism > 1:
        # Indexed completions give each pod a stable rank
        # (resource_job.go:135-140); JOB_COMPLETION_INDEX is injected by k8s.
        job_spec["completionMode"] = "Indexed"

    job = {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": identifier, "namespace": namespace, "labels": labels},
        "spec": job_spec,
    }
    return [config_map, pvc, job]


def render_transfer_job(identifier: str, spec: TaskSpec,
                        namespace: str = "default",
                        region: str = "") -> Dict[str, Any]:
    """Ephemeral sleep Job mounting the workdir PVC for ``kubectl cp``.

    The reference switches the same Job into "transfer mode" via
    TPI_TRANSFER_MODE, where the entrypoint sleeps instead of running the
    script (resource_job.go:203-213, task.go:146-166). Rendering a distinct
    single-pod Job is equivalent and avoids mutating process env. The main
    Job's *region* node selectors are carried over so a WaitForFirstConsumer
    RWO volume binds in a zone/pool the real Job can also schedule into; the
    accelerator selector is deliberately not — zone, not GPU model, decides
    where the volume binds, and the busybox pod requests no GPU so it would
    sit Pending behind accelerator taints.
    """
    selectors = parse_node_selectors(region)
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {
            "name": f"{identifier}-transfer",
            "namespace": namespace,
            "labels": {"tpu-task-transfer": identifier},
        },
        "spec": {
            "parallelism": 1,
            "completions": 1,
            "backoffLimit": 0,
            "template": {
                "metadata": {"labels": {"tpu-task-transfer": identifier}},
                "spec": {
                    "restartPolicy": "Never",
                    **({"nodeSelector": selectors} if selectors else {}),
                    "containers": [{
                        "name": "transfer",
                        "image": "busybox",
                        "command": ["/bin/sh", "-c", "sleep infinity"],
                        "workingDir": "/workdir",
                        "volumeMounts": [
                            {"name": "workdir", "mountPath": "/workdir"},
                        ],
                    }],
                    "volumes": [
                        {"name": "workdir", "persistentVolumeClaim": {
                            "claimName": f"{identifier}-workdir"}},
                    ],
                },
            },
        },
    }
