"""Kubernetes manifest rendering: ConfigMap + PVC + indexed Job.

The object shapes mirror /root/reference/task/k8s/task.go and
resources/resource_job.go: the task script travels in a ConfigMap mounted at
/script, the workdir in a PVC (RWX when parallelism > 1 —
resource_persistent_volume_claim.go:41-44), and the Job runs with
parallelism == completions, **Indexed completion mode when parallelism > 1**
(resource_job.go:135-140 — the rank mechanism), BackoffLimit high for
restart-on-failure (resource_job.go:130), and ActiveDeadlineSeconds as the
timeout (resource_job.go:142). Rendered as plain dicts (JSON == YAML subset)
so they golden-test cleanly and feed kubectl directly.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from tpu_task.backends.k8s.machines import (
    K8S_IMAGES,
    K8sResources,
    parse_k8s_machine,
    parse_node_selectors,
)
from tpu_task.common.values import Task as TaskSpec

MAX_BACKOFF = 2147483647  # reference uses math.MaxInt32

# The workdir storage-class grammar ``class:[size:]path``
# (task/k8s/task.go:76-92): a directory of "fast-ssd:20:/data/work" puts the
# workdir PVC on storage class "fast-ssd" with a 20 Gi claim and uploads
# from/downloads to /data/work.
_WORKDIR_RE = re.compile(r"^([^:]+):(?:(\d+):)?(.+)$")


@dataclass
class Workdir:
    """Parsed ``environment.directory`` for the K8s backend."""

    path: str = ""
    storage_class: str = ""
    size_gb: Optional[int] = None


def parse_workdir(directory: str) -> Workdir:
    """Split the K8s ``class:[size:]path`` workdir grammar; a plain path
    (no colon) passes through unchanged (task/k8s/task.go:76-92)."""
    match = _WORKDIR_RE.match(directory or "")
    if match:
        return Workdir(
            path=match.group(3),
            storage_class=match.group(1),
            size_gb=int(match.group(2)) if match.group(2) else None,
        )
    return Workdir(path=directory or "")


def _workdir_volume(identifier: str, spec: TaskSpec) -> Dict[str, Any]:
    """The Job/transfer-pod workdir volume: the task's own PVC, or the
    pre-allocated claim named by ``storage.container``
    (data_source_persistent_volume.go:46-51)."""
    claim = (spec.remote_storage.container if spec.remote_storage
             else f"{identifier}-workdir")
    return {"name": "workdir", "persistentVolumeClaim": {"claimName": claim}}


def _workdir_mount(spec: TaskSpec) -> Dict[str, Any]:
    """Mount for the workdir volume; a pre-allocated claim's ``path``
    becomes the mount subPath (resource_job.go:184-189)."""
    mount: Dict[str, Any] = {"name": "workdir", "mountPath": "/workdir"}
    if spec.remote_storage and spec.remote_storage.path:
        mount["subPath"] = spec.remote_storage.path.strip("/")
    return mount


def render_manifests(identifier: str, spec: TaskSpec, namespace: str = "default",
                     region: str = "",
                     automount_service_account_token: Optional[bool] = None,
                     ) -> List[Dict[str, Any]]:
    """ConfigMap [+ PVC] + Job. The PVC is omitted when ``remote_storage``
    names a pre-allocated claim (task/k8s/task.go:66-70) — the existing PVC
    is referenced, never owned, so delete won't touch it."""
    resources = parse_k8s_machine(spec.size.machine or "m")
    selectors = parse_node_selectors(region)
    selectors.update(resources.node_selector())

    image = spec.environment.image or "ubuntu"
    image = K8S_IMAGES.get(image, image)

    labels = {"tpu-task": identifier}
    env = [{"name": name, "value": value}
           for name, value in sorted(spec.environment.variables.enrich().items())]
    env.append({"name": "TPI_TASK", "value": "true"})

    config_map = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": f"{identifier}-script", "namespace": namespace,
                     "labels": labels},
        "data": {"script": spec.environment.script},
    }

    workdir = parse_workdir(spec.environment.directory)
    size_gb = workdir.size_gb or (spec.size.storage
                                  if spec.size.storage > 0 else 10)
    pvc = {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": f"{identifier}-workdir", "namespace": namespace,
                     "labels": labels},
        "spec": {
            # RWX once multiple pods share the workdir
            # (resource_persistent_volume_claim.go:41-44).
            "accessModes": ["ReadWriteMany" if spec.parallelism > 1
                            else "ReadWriteOnce"],
            # storageClassName only when the workdir grammar names one —
            # otherwise the cluster default applies
            # (resource_persistent_volume_claim.go:66-70).
            **({"storageClassName": workdir.storage_class}
               if workdir.storage_class else {}),
            "resources": {"requests": {"storage": f"{size_gb}Gi"}},
        },
    }

    timeout = spec.environment.timeout
    job_spec: Dict[str, Any] = {
        "parallelism": spec.parallelism,
        "completions": spec.parallelism,
        "backoffLimit": MAX_BACKOFF,
        "template": {
            "metadata": {"labels": labels},
            "spec": {
                "restartPolicy": "Never",
                "terminationGracePeriodSeconds": 30,
                **({"nodeSelector": selectors} if selectors else {}),
                # permission_set names an existing ServiceAccount the pods
                # run as (resource_job.go:259-260).
                **({"serviceAccountName": spec.permission_set}
                   if spec.permission_set else {}),
                **({"automountServiceAccountToken":
                    automount_service_account_token}
                   if automount_service_account_token is not None else {}),
                "containers": [{
                    "name": "task",
                    "image": image,
                    "command": ["/bin/sh", "-c", "exec /script/script"],
                    "env": env,
                    # Requests pinned to 0 (resource_job.go:245-249): K8s
                    # defaults each resource's request to its limit, leaving
                    # pods Pending on nodes smaller than the cap. Every
                    # requestable resource the limits can contain needs a pin.
                    "resources": {
                        "limits": resources.limits(spec.size.storage),
                        "requests": {"cpu": "0", "memory": "0",
                                     "ephemeral-storage": "0"},
                    },
                    "workingDir": "/workdir",
                    "volumeMounts": [
                        {"name": "script", "mountPath": "/script"},
                        _workdir_mount(spec),
                    ],
                }],
                "volumes": [
                    {"name": "script", "configMap": {
                        "name": f"{identifier}-script", "defaultMode": 0o755}},
                    _workdir_volume(identifier, spec),
                ],
            },
        },
    }
    if timeout is not None:
        job_spec["activeDeadlineSeconds"] = int(timeout.total_seconds())
    if spec.parallelism > 1:
        # Indexed completions give each pod a stable rank
        # (resource_job.go:135-140); JOB_COMPLETION_INDEX is injected by k8s.
        job_spec["completionMode"] = "Indexed"

    job = {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": identifier, "namespace": namespace, "labels": labels},
        "spec": job_spec,
    }
    if spec.remote_storage:
        return [config_map, job]
    return [config_map, pvc, job]


def render_transfer_job(identifier: str, spec: TaskSpec,
                        namespace: str = "default",
                        region: str = "") -> Dict[str, Any]:
    """Ephemeral sleep Job mounting the workdir PVC for ``kubectl cp``.

    The reference switches the same Job into "transfer mode" via
    TPI_TRANSFER_MODE, where the entrypoint sleeps instead of running the
    script (resource_job.go:203-213, task.go:146-166). Rendering a distinct
    single-pod Job is equivalent and avoids mutating process env. The main
    Job's *region* node selectors are carried over so a WaitForFirstConsumer
    RWO volume binds in a zone/pool the real Job can also schedule into; the
    accelerator selector is deliberately not — zone, not GPU model, decides
    where the volume binds, and the busybox pod requests no GPU so it would
    sit Pending behind accelerator taints.
    """
    selectors = parse_node_selectors(region)
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {
            "name": f"{identifier}-transfer",
            "namespace": namespace,
            "labels": {"tpu-task-transfer": identifier},
        },
        "spec": {
            "parallelism": 1,
            "completions": 1,
            "backoffLimit": 0,
            "template": {
                "metadata": {"labels": {"tpu-task-transfer": identifier}},
                "spec": {
                    "restartPolicy": "Never",
                    **({"nodeSelector": selectors} if selectors else {}),
                    "containers": [{
                        "name": "transfer",
                        "image": "busybox",
                        "command": ["/bin/sh", "-c", "sleep infinity"],
                        "workingDir": "/workdir",
                        "volumeMounts": [
                            _workdir_mount(spec),
                        ],
                    }],
                    "volumes": [
                        _workdir_volume(identifier, spec),
                    ],
                },
            },
        },
    }
