"""Kubernetes size grammar: ``{cpu}-{memoryMB}[+{accelerator}*{count}]``.

Parity with /root/reference/task/k8s/resources/resource_job.go:71-124 —
generic aliases, the cpu-memory regex, GPU limits via ``nvidia.com/gpu``
with an ``accelerator`` node selector, and the region attribute as a
comma-separated node-selector label list (resource_job.go:42-48).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict

K8S_SIZES: Dict[str, str] = {
    "s": "1-1000",
    "m": "8-32000",
    "l": "32-128000",
    "xl": "64-256000",
    "m+t4": "4-16000+nvidia*1",
    "m+k80": "4-64000+nvidia*1",
    "l+k80": "32-512000+nvidia*8",
    "xl+k80": "64-768000+nvidia*16",
    "m+v100": "8-64000+nvidia*1",
    "l+v100": "32-256000+nvidia*4",
    "xl+v100": "64-512000+nvidia*8",
}

K8S_IMAGES: Dict[str, str] = {
    "ubuntu": "ubuntu",
    "nvidia": "nvidia/cuda:11.3.1-cudnn8-runtime-ubuntu20.04",
}

_SIZE_RE = re.compile(r"^(\d+)-(\d+)(?:\+([^*]+)\*([1-9]\d*))?$")


@dataclass(frozen=True)
class K8sResources:
    cpu: int
    memory_mb: int
    accelerator: str = ""
    gpu_count: int = 0

    def limits(self, disk_gb: int = -1) -> Dict[str, str]:
        limits = {"cpu": str(self.cpu), "memory": f"{self.memory_mb}M"}
        if disk_gb > 0:
            limits["ephemeral-storage"] = f"{disk_gb}G"
        if self.gpu_count > 0:
            limits["nvidia.com/gpu"] = str(self.gpu_count)
        return limits

    def node_selector(self) -> Dict[str, str]:
        if self.gpu_count > 0 and self.accelerator:
            return {"accelerator": self.accelerator}
        return {}


def parse_k8s_machine(machine: str) -> K8sResources:
    machine = K8S_SIZES.get(machine, machine)
    match = _SIZE_RE.match(machine)
    if not match:
        raise ValueError(f"invalid k8s machine size: {machine!r}")
    return K8sResources(
        cpu=int(match.group(1)),
        memory_mb=int(match.group(2)),
        accelerator=match.group(3) or "",
        gpu_count=int(match.group(4)) if match.group(4) else 0,
    )


def parse_node_selectors(region: str) -> Dict[str, str]:
    """Region = comma-separated ``key=value`` node-selector labels."""
    selectors: Dict[str, str] = {}
    for item in str(region or "").split(","):
        key, sep, value = item.partition("=")
        if sep and value:
            selectors[key.strip()] = value.strip()
    return selectors
