"""Shared base for scaling-group-backed task backends.

The reference's per-cloud packages all compose the same shape — a scaling
group at desired capacity N, a storage container, a rendered bootstrap — and
differ in size grammars, region maps, credential env and the cloud control
plane (task/{aws,gcp,az,k8s}/task.go). This base carries the common lifecycle
over the hermetic ``MachineGroup`` control plane (subprocess workers, file
bucket) so every backend's *semantics* — size parsing, spot policy, env
injection, rank assignment — are exercised end-to-end without cloud
credentials; real control planes are wired per backend where available
(TPU: QueuedResources; others land incrementally).
"""

from __future__ import annotations

import os
import time
from datetime import datetime
from typing import Dict, List

from tpu_task.backends.local.control_plane import MachineGroup
from tpu_task.common.cloud import Cloud
from tpu_task.common.errors import ResourceNotFoundError
from tpu_task.common.identifier import Identifier
from tpu_task.common.steps import Step, run_steps
from tpu_task.common.values import Event, Status, StatusCode
from tpu_task.common.values import Task as TaskSpec
from tpu_task.storage import limit_transfer, logs as storage_logs
from tpu_task.storage import status as storage_status, transfer
from tpu_task.task import Task


class GroupBackedTask(Task):
    """Hermetic scaling-group lifecycle; subclasses set provider semantics."""

    provider_name = "local"

    def __init__(self, cloud: Cloud, identifier: Identifier, spec: TaskSpec):
        self.cloud = cloud
        self.identifier = identifier
        self.spec = spec
        self.validate()
        self.group = MachineGroup(identifier.long())

    # -- hooks ----------------------------------------------------------------
    def validate(self) -> None:
        """Parse/validate machine size, region, spot policy. Raise on error."""

    def extra_environment(self) -> Dict[str, str]:
        """Provider-specific env (credentials etc.) injected into workers."""
        return {}

    # -- common plumbing -------------------------------------------------------
    def _timeout_epoch(self) -> float:
        timeout = self.spec.environment.timeout
        if timeout is None:
            return 0.0
        return time.time() + timeout.total_seconds()

    def _environment(self) -> dict:
        env = dict(self.spec.environment.variables.enrich())
        env.update(self.extra_environment())
        env["TPU_TASK_CLOUD_PROVIDER"] = self.provider_name
        env["TPU_TASK_CLOUD_REGION"] = str(self.cloud.region)
        env["TPU_TASK_IDENTIFIER"] = self.identifier.long()
        env["TPU_TASK_REMOTE"] = self.group.bucket
        env["TPI_TASK"] = "true"
        return env

    def _sync_periods(self) -> tuple:
        log_period = float(os.environ.get("TPU_TASK_LOCAL_LOG_PERIOD", "5"))
        data_period = float(os.environ.get("TPU_TASK_LOCAL_DATA_PERIOD", "10"))
        return log_period, data_period

    # -- lifecycle -------------------------------------------------------------
    def create(self) -> None:
        log_period, data_period = self._sync_periods()
        run_steps([
            Step("Creating machine group...", lambda: self.group.create(
                script=self.spec.environment.script,
                parallelism=self.spec.parallelism,
                timeout_epoch=self._timeout_epoch(),
                environment=self._environment(),
                log_period=log_period, data_period=data_period,
            )),
            Step("Uploading directory...", self.push),
            Step("Starting task...", self.start),
        ])

    def read(self) -> None:
        state = self.group.reconcile()
        self.spec.addresses = [f"127.0.0.1#{worker.machine_id}"
                               for worker in state.workers]
        self.spec.status = self.status()
        self.spec.events = self.events()

    def delete(self) -> None:
        if self.group.exists() and self.workdir():
            try:
                self.pull()
            except ResourceNotFoundError:
                pass
        self.group.delete()

    def start(self) -> None:
        self.group.scale(self.spec.parallelism)

    def stop(self) -> None:
        self.group.scale(0)

    def observed_parallelism(self):
        """Parallelism from the group's own persisted state (not the spec a
        bare `read` was constructed with)."""
        if not self.group.exists():
            return None
        return self.group.reconcile().parallelism or None

    # -- data plane ------------------------------------------------------------
    def workdir(self) -> str:
        """Local directory the data plane syncs; backends with a richer
        directory grammar (K8s ``class:[size:]path``) override this."""
        return self.spec.environment.directory

    def push(self) -> None:
        directory = self.workdir()
        if not directory:
            return
        transfer(directory, os.path.join(self.group.bucket, "data"),
                 self.spec.environment.exclude_list)

    def pull(self) -> None:
        directory = self.workdir()
        if not directory:
            return
        rules = limit_transfer(self.spec.environment.directory_out,
                               list(self.spec.environment.exclude_list))
        transfer(os.path.join(self.group.bucket, "data"), directory, rules)

    # -- observation -----------------------------------------------------------
    def status(self) -> Status:
        initial: Status = {StatusCode.ACTIVE: len(self.group.live_workers())}
        return storage_status(self.group.bucket, initial)

    def events(self) -> List[Event]:
        return [
            Event(time=datetime.fromisoformat(event["time"]),
                  code=event["code"], description=[event["description"]])
            for event in self.group.events()
        ]

    def logs(self) -> List[str]:
        return storage_logs(self.group.bucket)

    def get_identifier(self) -> Identifier:
        return self.identifier

    def get_addresses(self) -> List[str]:
        return list(self.spec.addresses)

    def preempt(self, index: int = 0) -> None:
        """Simulate spot preemption of one worker (hermetic recovery tests)."""
        self.group.preempt(index)
