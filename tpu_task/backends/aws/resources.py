"""AWS resource primitives over the Query clients.

Mirrors the reference's L2 objects (/root/reference/task/aws/resources/):

* DefaultVpc / Subnets   — data_source_default_vpc.go, *_subnets.go
* Image                  — data_source_image.go ({user}@{owner}:{arch}:{name},
                           newest-first by CreationDate)
* KeyPair                — resource_key_pair.go (deterministic public key)
* SecurityGroup          — resource_security_group.go (revoke default egress,
                           intra-group allow-all, per-port TCP+UDP)
* LaunchTemplate         — resource_launch_template.go (UserData bootstrap,
                           size map handled by the task layer, gp2 root disk)
* AutoScalingGroup       — resource_auto_scaling_group.go (MixedInstancesPolicy
                           lowest-price spot, Read → Status/Addresses/Events,
                           Update = DesiredCapacity)
* Bucket                 — resource_bucket.go (S3 create/empty/delete +
                           rclone-style connection string)

Create tolerates AlreadyExists → no-op/Read; Delete tolerates NotFound
(SURVEY.md §7 hard part #5).
"""

from __future__ import annotations

import re
from datetime import datetime, timezone
from typing import Dict, List, Optional

from tpu_task.backends.aws.api import QueryClient, member_list, text, texts
from tpu_task.common.errors import ResourceAlreadyExistsError, ResourceNotFoundError
from tpu_task.common.values import Event, Firewall

EC2_VERSION = "2016-11-15"
ASG_VERSION = "2011-01-01"

IMAGE_ALIASES = {
    "ubuntu": "ubuntu@099720109477:x86_64:*ubuntu/images/hvm-ssd/"
              "ubuntu-focal-20.04*",
    "nvidia": "ubuntu@898082745236:x86_64:Deep Learning AMI GPU CUDA 11.3.* "
              "(Ubuntu 20.04) *",
}
_IMAGE_RE = re.compile(r"^([^@]+)@([^:]+):([^:]+):([^:]+)$")


class DefaultVpc:
    def __init__(self, ec2: QueryClient):
        self.ec2 = ec2
        self.vpc_id = ""

    def read(self) -> None:
        root = self.ec2.call("DescribeVpcs", {
            "Filter.1.Name": "isDefault", "Filter.1.Value.1": "true"})
        self.vpc_id = text(root, ".//vpcSet/item/vpcId")
        if not self.vpc_id:
            raise ResourceNotFoundError("default VPC")


class Subnets:
    def __init__(self, ec2: QueryClient, vpc: DefaultVpc):
        self.ec2 = ec2
        self.vpc = vpc
        self.subnet_ids: List[str] = []

    def read(self) -> None:
        root = self.ec2.call("DescribeSubnets", {
            "Filter.1.Name": "vpc-id", "Filter.1.Value.1": self.vpc.vpc_id})
        self.subnet_ids = texts(root, ".//subnetSet/item/subnetId")
        if not self.subnet_ids:
            raise ResourceNotFoundError("default VPC subnets")


class Image:
    """``{user}@{owner}:{arch}:{name-glob}``, newest CreationDate wins."""

    def __init__(self, ec2: QueryClient, identifier: str):
        self.ec2 = ec2
        self.identifier = identifier or "ubuntu"
        self.ssh_user = ""
        self.image_id = ""

    def read(self) -> None:
        image = IMAGE_ALIASES.get(self.identifier, self.identifier)
        match = _IMAGE_RE.match(image)
        if not match:
            raise ValueError(f"wrong image name: {self.identifier!r} "
                             "(expected '{user}@{owner}:{arch}:{name}')")
        self.ssh_user, owner, arch, name = match.groups()
        params = {"Filter.1.Name": "name", "Filter.1.Value.1": name,
                  "Filter.2.Name": "state", "Filter.2.Value.1": "available"}
        index = 3
        if arch != "*":
            params[f"Filter.{index}.Name"] = "architecture"
            params[f"Filter.{index}.Value.1"] = arch
            index += 1
        if owner != "*":
            params[f"Filter.{index}.Name"] = "owner-id"
            params[f"Filter.{index}.Value.1"] = owner
        root = self.ec2.call("DescribeImages", params)
        candidates = []
        for item in root.iterfind(".//imagesSet/item"):
            candidates.append((text(item, "creationDate"),
                               text(item, "imageId")))
        if not candidates:
            raise ResourceNotFoundError(f"no AMI matches {image!r}")
        self.image_id = max(candidates)[1]  # ISO dates sort lexically


class KeyPair:
    def __init__(self, ec2: QueryClient, name: str, public_key: str):
        self.ec2 = ec2
        self.name = name
        self.public_key = public_key

    def create(self) -> None:
        import base64

        try:
            self.ec2.call("ImportKeyPair", {
                "KeyName": self.name,
                "PublicKeyMaterial": base64.b64encode(
                    self.public_key.encode()).decode()})
        except ResourceAlreadyExistsError:
            pass

    def delete(self) -> None:
        try:
            self.ec2.call("DeleteKeyPair", {"KeyName": self.name})
        except ResourceNotFoundError:
            pass


class SecurityGroup:
    """Firewall from the task spec: default egress revoked, intra-group
    allow-all both ways, per-port TCP+UDP ingress
    (resource_security_group.go:34-204)."""

    def __init__(self, ec2: QueryClient, name: str, vpc: DefaultVpc,
                 firewall: Firewall):
        self.ec2 = ec2
        self.name = name
        self.vpc = vpc
        self.firewall = firewall
        self.group_id = ""

    def create(self) -> None:
        try:
            root = self.ec2.call("CreateSecurityGroup", {
                "GroupName": self.name,
                "GroupDescription": self.name,
                "VpcId": self.vpc.vpc_id})
            self.group_id = text(root, ".//groupId")
        except ResourceAlreadyExistsError:
            self.read()
            return
        # Revoke the default allow-all egress, then grant exactly what the
        # spec allows (plus intra-group everything for multi-node traffic).
        try:
            self.ec2.call("RevokeSecurityGroupEgress", {
                "GroupId": self.group_id,
                "IpPermissions.1.IpProtocol": "-1",
                "IpPermissions.1.IpRanges.1.CidrIp": "0.0.0.0/0"})
        except (ResourceNotFoundError, ResourceAlreadyExistsError):
            pass
        for direction in ("Ingress", "Egress"):
            self.ec2.call(f"AuthorizeSecurityGroup{direction}", {
                "GroupId": self.group_id,
                "IpPermissions.1.IpProtocol": "-1",
                "IpPermissions.1.UserIdGroupPairs.1.GroupId": self.group_id})
        self._authorize_rules("Ingress", self.firewall.ingress)
        self._authorize_rules("Egress", self.firewall.egress)

    def _authorize_rules(self, direction: str, rule) -> None:
        if rule.nets is not None and not rule.nets:
            return  # specified-but-empty = allow NONE (values.py semantics)
        nets = [str(net) for net in (rule.nets or [])] or ["0.0.0.0/0"]
        if rule.ports is not None and not rule.ports:
            return  # allow none
        if rule.ports is None:
            params = {"IpPermissions.1.IpProtocol": "-1"}
            for index, net in enumerate(nets):
                params[f"IpPermissions.1.IpRanges.{index + 1}.CidrIp"] = net
            self._authorize(direction, params)
            return
        for position, port in enumerate(rule.ports):
            params = {}
            for proto_index, protocol in enumerate(("tcp", "udp")):
                base = f"IpPermissions.{proto_index + 1}"
                params[f"{base}.IpProtocol"] = protocol
                params[f"{base}.FromPort"] = str(port)
                params[f"{base}.ToPort"] = str(port)
                for index, net in enumerate(nets):
                    params[f"{base}.IpRanges.{index + 1}.CidrIp"] = net
            self._authorize(direction, params)

    def _authorize(self, direction: str, permissions: Dict[str, str]) -> None:
        try:
            self.ec2.call(f"AuthorizeSecurityGroup{direction}",
                          {"GroupId": self.group_id, **permissions})
        except ResourceAlreadyExistsError:
            pass

    def read(self) -> None:
        root = self.ec2.call("DescribeSecurityGroups", {
            "Filter.1.Name": "group-name", "Filter.1.Value.1": self.name})
        self.group_id = text(root, ".//securityGroupInfo/item/groupId")
        if not self.group_id:
            raise ResourceNotFoundError(self.name)

    def delete(self, timeout: float = 600.0) -> None:
        import time as _time

        from tpu_task.backends.aws.api import AwsQueryError

        try:
            if not self.group_id:
                self.read()
        except ResourceNotFoundError:
            return
        # Instances from the just-force-deleted ASG keep ENIs referencing
        # this group for minutes; retry DependencyViolation until they drain
        # (the reference gets this from the SDK waiter it runs first).
        sleep = self.ec2._sleep or _time.sleep
        delay = 2.0
        deadline = _time.time() + timeout
        while True:
            try:
                self.ec2.call("DeleteSecurityGroup",
                              {"GroupId": self.group_id})
                return
            except ResourceNotFoundError:
                return
            except AwsQueryError as error:
                if error.code != "DependencyViolation" or \
                        _time.time() > deadline:
                    raise
                sleep(delay)
                delay = min(delay * 2, 32.0)


class LaunchTemplate:
    def __init__(self, ec2: QueryClient, name: str, *, instance_type: str,
                 image_id: str, key_name: str, security_group_id: str,
                 user_data_b64: str, instance_profile_arn: str = "",
                 disk_size_gb: int = -1, tags: Optional[Dict[str, str]] = None):
        self.ec2 = ec2
        self.name = name
        self.instance_type = instance_type
        self.image_id = image_id
        self.key_name = key_name
        self.security_group_id = security_group_id
        self.user_data_b64 = user_data_b64
        self.instance_profile_arn = instance_profile_arn
        self.disk_size_gb = disk_size_gb
        self.tags = tags or {}

    def params(self) -> Dict[str, str]:
        data = {
            "LaunchTemplateName": self.name,
            "LaunchTemplateData.UserData": self.user_data_b64,
            "LaunchTemplateData.ImageId": self.image_id,
            "LaunchTemplateData.KeyName": self.key_name,
            "LaunchTemplateData.InstanceType": self.instance_type,
            "LaunchTemplateData.SecurityGroupId.1": self.security_group_id,
            # gp2 root volume, delete-on-termination
            # (resource_launch_template.go:119-131).
            "LaunchTemplateData.BlockDeviceMapping.1.DeviceName": "/dev/sda1",
            "LaunchTemplateData.BlockDeviceMapping.1.Ebs."
            "DeleteOnTermination": "true",
            "LaunchTemplateData.BlockDeviceMapping.1.Ebs.VolumeType": "gp2",
        }
        if self.disk_size_gb > 0:  # Size.storage honored (:177-179 pattern)
            data["LaunchTemplateData.BlockDeviceMapping.1.Ebs."
                 "VolumeSize"] = str(self.disk_size_gb)
        if self.instance_profile_arn:
            data["LaunchTemplateData.IamInstanceProfile.Arn"] = \
                self.instance_profile_arn
        for index, (key, value) in enumerate(sorted(self.tags.items())):
            base = f"LaunchTemplateData.TagSpecification.1"
            data[f"{base}.ResourceType"] = "instance"
            data[f"{base}.Tag.{index + 1}.Key"] = key
            data[f"{base}.Tag.{index + 1}.Value"] = value
        return data

    def create(self) -> None:
        try:
            self.ec2.call("CreateLaunchTemplate", self.params())
        except ResourceAlreadyExistsError:
            pass

    def read_tags(self) -> Dict[str, str]:
        version_root = self.ec2.call("DescribeLaunchTemplateVersions", {
            "LaunchTemplateName": self.name, "LaunchTemplateVersion.1":
            "$Latest"})
        tags = {}
        for item in version_root.iterfind(
                ".//launchTemplateData/tagSpecificationSet/item/tagSet/item"):
            tags[text(item, "key")] = text(item, "value")
        return tags

    def delete(self) -> None:
        try:
            self.ec2.call("DeleteLaunchTemplate",
                          {"LaunchTemplateName": self.name})
        except ResourceNotFoundError:
            pass


class AutoScalingGroup:
    """ASG at desired 0, MixedInstancesPolicy lowest-price spot
    (resource_auto_scaling_group.go:51-106): spot > 0 → bid cap, 0 → 100%
    spot at on-demand price, < 0 → on-demand."""

    def __init__(self, asg: QueryClient, ec2: QueryClient, name: str,
                 launch_template: str = "", subnet_ids: Optional[List[str]] = None,
                 parallelism: int = 1, spot: float = -1.0):
        self.asg = asg
        self.ec2 = ec2
        self.name = name
        self.launch_template = launch_template
        self.subnet_ids = subnet_ids or []
        self.parallelism = parallelism
        self.spot = spot
        self.addresses: List[str] = []
        self.events: List[Event] = []
        self.running = 0
        self.desired = 0
        self.exists = False

    def create(self) -> None:
        on_demand_percentage = 100
        spot_price = ""
        if self.spot > 0:
            spot_price = f"{self.spot:.5f}"
            on_demand_percentage = 0
        elif self.spot == 0:
            on_demand_percentage = 0
        params = {
            "AutoScalingGroupName": self.name,
            "DesiredCapacity": "0",
            "MinSize": "0",
            "MaxSize": str(self.parallelism),
            "MixedInstancesPolicy.InstancesDistribution."
            "OnDemandBaseCapacity": "0",
            "MixedInstancesPolicy.InstancesDistribution."
            "OnDemandPercentageAboveBaseCapacity": str(on_demand_percentage),
            "MixedInstancesPolicy.InstancesDistribution."
            "SpotAllocationStrategy": "lowest-price",
            "MixedInstancesPolicy.LaunchTemplate."
            "LaunchTemplateSpecification.LaunchTemplateName":
                self.launch_template,
            "MixedInstancesPolicy.LaunchTemplate."
            "LaunchTemplateSpecification.Version": "$Latest",
            "VPCZoneIdentifier": ",".join(self.subnet_ids),
        }
        if spot_price:
            params["MixedInstancesPolicy.InstancesDistribution."
                   "SpotMaxPrice"] = spot_price
        try:
            self.asg.call("CreateAutoScalingGroup", params)
        except ResourceAlreadyExistsError:
            pass

    def read(self) -> None:
        root = self.asg.call("DescribeAutoScalingGroups", member_list(
            "AutoScalingGroupNames", [self.name], member=True))
        group = root.find(".//AutoScalingGroups/member")
        if group is None:
            self.exists = False
            raise ResourceNotFoundError(self.name)
        self.exists = True
        self.desired = int(text(group, "DesiredCapacity", "0"))
        instance_ids = texts(group, ".//Instances/member/InstanceId")

        self.running = 0
        self.addresses = []
        if instance_ids:
            instances = self.ec2.call(
                "DescribeInstances", member_list("InstanceId", instance_ids))
            for item in instances.iterfind(
                    ".//reservationSet/item/instancesSet/item"):
                if text(item, ".//instanceState/name") == "running":
                    self.running += 1
                address = text(item, "ipAddress")
                if address:
                    self.addresses.append(address)

        self.events = []
        activities = self.asg.call("DescribeScalingActivities",
                                   {"AutoScalingGroupName": self.name})
        for item in activities.iterfind(".//Activities/member"):
            stamp = datetime.fromtimestamp(0, tz=timezone.utc)
            try:
                stamp = datetime.fromisoformat(
                    text(item, "StartTime").replace("Z", "+00:00"))
            except ValueError:
                pass
            self.events.append(Event(
                time=stamp, code=text(item, "StatusCode"),
                description=[text(item, "Cause"), text(item, "Description"),
                             text(item, "StatusMessage")]))

    def resize(self, capacity: int) -> None:
        self.asg.call("SetDesiredCapacity", {
            "AutoScalingGroupName": self.name,
            "DesiredCapacity": str(capacity),
            "HonorCooldown": "false"})

    def delete(self, timeout: float = 600.0) -> None:
        import time as _time

        try:
            self.asg.call("DeleteAutoScalingGroup", {
                "AutoScalingGroupName": self.name, "ForceDelete": "true"})
        except ResourceNotFoundError:
            return
        # ForceDelete is async; wait for the group to disappear so the
        # security group behind it can actually be deleted next
        # (the reference's GroupNotExistsWaiter role).
        sleep = self.asg._sleep or _time.sleep
        delay = 2.0
        deadline = _time.time() + timeout
        while _time.time() < deadline:
            try:
                self.read()
            except ResourceNotFoundError:
                return
            sleep(delay)
            delay = min(delay * 2, 32.0)


class S3Bucket:
    """Per-task S3 bucket + rclone-style connection string
    (resource_bucket.go: create/wait/empty-on-delete; connstring :160-173)."""

    def __init__(self, name: str, region: str, access_key: str,
                 secret_key: str, session_token: str = ""):
        from tpu_task.storage.cloud_backends import S3Backend

        self.name = name
        self.region = region
        self.config = {"access_key_id": access_key,
                       "secret_access_key": secret_key,
                       "region": region}
        if session_token:
            self.config["session_token"] = session_token
        self.backend = S3Backend(name, config=self.config)

    def create(self) -> None:
        body = b""
        if self.region != "us-east-1":  # CreateBucket quirk: default region
            body = (f'<CreateBucketConfiguration><LocationConstraint>'
                    f'{self.region}</LocationConstraint>'
                    f'</CreateBucketConfiguration>').encode()
        import urllib.error

        try:
            self.backend._request("PUT", "/", {}, body=body)
        except urllib.error.HTTPError as error:
            if error.code != 409:  # BucketAlreadyOwnedByYou → idempotent
                raise

    def delete(self) -> None:
        from tpu_task.storage import delete_storage

        try:
            delete_storage(self.connection_string())
        except ResourceNotFoundError:
            return
        try:
            # Only a missing bucket is tolerable; a 409 BucketNotEmpty or
            # 403 must surface — swallowing them leaks a billed bucket
            # while reporting success.
            self.backend._request("DELETE", "/", {})
        except ResourceNotFoundError:
            pass

    def connection_string(self) -> str:
        from tpu_task.storage import Connection

        return str(Connection(backend="s3", container=self.name,
                              config=dict(self.config)))
