"""AWS control-plane client: EC2 + Auto Scaling Query APIs over SigV4.

The reference drives AWS through aws-sdk-go-v2 (/root/reference/task/aws/
client/client.go); this client speaks the raw Query protocol — form-encoded
``Action`` POSTs signed with the same SigV4 layer the S3 data plane uses
(:mod:`tpu_task.storage.signing`), XML responses parsed with the stdlib.
Transient failures ride the shared retry/backoff layer; AWS error codes map
to the common NotFound/AlreadyExists semantics so every resource keeps the
reference's idempotency discipline.
"""

from __future__ import annotations

import hashlib
import re
import time
import urllib.error
import urllib.parse
from typing import Dict, List, Optional
from xml.etree import ElementTree

from tpu_task.common.errors import ResourceAlreadyExistsError, ResourceNotFoundError
from tpu_task.storage.signing import sigv4_sign

# Error codes that mean "already there" / "not there" across EC2 and
# autoscaling (smithy APIError codes the reference matches by string).
_ALREADY_EXISTS = ("AlreadyExists", "Duplicate", "InvalidKeyPair.Duplicate",
                   "InvalidGroup.Duplicate", "InvalidLaunchTemplateName."
                   "AlreadyExistsException")
_NOT_FOUND = ("NotFound", "NotFoundException", "InvalidGroup.NotFound",
              "InvalidLaunchTemplateName.NotFoundException",
              "InvalidKeyPair.NotFound")


def _strip_namespaces(xml_text: bytes) -> ElementTree.Element:
    """Parse XML with namespaces removed — AWS responses carry per-service
    default namespaces that would otherwise infect every find()."""
    text = re.sub(rb'xmlns="[^"]+"', b"", xml_text, count=1)
    return ElementTree.fromstring(text)


class AwsQueryError(RuntimeError):
    def __init__(self, code: str, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


class QueryClient:
    """One AWS Query-protocol service endpoint (ec2 / autoscaling)."""

    def __init__(self, service: str, version: str, region: str,
                 access_key: str, secret_key: str, session_token: str = "",
                 host: str = ""):
        self.service = service
        self.version = version
        self.region = region
        self.access_key = access_key
        self.secret_key = secret_key
        self.session_token = session_token
        self.host = host or f"{service}.{region}.amazonaws.com"
        self._urlopen = None  # test hook: injectable transport
        self._sleep = None    # test hook: injectable backoff sleep

    # AWS signals throttling as HTTP 400 + one of these codes — the shared
    # retry layer (408/429/5xx only) never sees them, so the Query client
    # backs off itself, like the reference SDK's retryer.
    THROTTLE_CODES = ("Throttling", "ThrottlingException",
                      "RequestLimitExceeded", "RequestThrottled")

    def call(self, action: str, params: Optional[Dict[str, str]] = None
             ) -> ElementTree.Element:
        from tpu_task.storage.http_util import send

        form = {"Action": action, "Version": self.version, **(params or {})}
        body = urllib.parse.urlencode(sorted(form.items())).encode()
        sleep = self._sleep or time.sleep
        delay = 1.0
        for attempt in range(6):
            headers = sigv4_sign(
                "POST", self.host, "/", {},
                {"content-type": "application/x-www-form-urlencoded"},
                hashlib.sha256(body).hexdigest(),
                self.access_key, self.secret_key, self.region, self.service,
                time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
                self.session_token)
            headers["Content-Type"] = "application/x-www-form-urlencoded"
            try:
                response = send("POST", f"https://{self.host}/", data=body,
                                headers=headers, urlopen=self._urlopen,
                                sleep=sleep)
                return _strip_namespaces(response)
            except urllib.error.HTTPError as error:
                mapped = self._map_error(error)
                if isinstance(mapped, AwsQueryError) and \
                        mapped.code in self.THROTTLE_CODES and attempt < 5:
                    sleep(delay)
                    delay = min(delay * 2, 16.0)
                    continue
                raise mapped from error
        raise RuntimeError("unreachable retry loop exit")

    def _map_error(self, error: urllib.error.HTTPError) -> Exception:
        body = b""
        try:
            body = error.read() or b""
        except Exception:
            pass
        code_match = re.search(rb"<Code>([^<]+)</Code>", body)
        message_match = re.search(rb"<Message>([^<]*)</Message>", body)
        code = code_match.group(1).decode() if code_match else str(error.code)
        message = message_match.group(1).decode() if message_match else ""
        if any(code.endswith(marker) or marker in code
               for marker in _ALREADY_EXISTS):
            return ResourceAlreadyExistsError(f"{code}: {message}")
        if any(code.endswith(marker) or marker in code
               for marker in _NOT_FOUND):
            return ResourceNotFoundError(f"{code}: {message}")
        # The Auto Scaling API answers ValidationError for nearly every bad
        # request; only the "name not found" variant is a NotFound —
        # anything else must surface, not be swallowed by idempotent deletes.
        if code == "ValidationError" and "not found" in message.lower():
            return ResourceNotFoundError(f"{code}: {message}")
        return AwsQueryError(code, message)


def member_list(prefix: str, values: List[str],
                member: bool = False) -> Dict[str, str]:
    """AWS Query list encoding: ``Prefix.N`` (EC2) or ``Prefix.member.N``
    (autoscaling)."""
    infix = ".member." if member else "."
    return {f"{prefix}{infix}{index + 1}": value
            for index, value in enumerate(values)}


def texts(root: ElementTree.Element, path: str) -> List[str]:
    return [element.text or "" for element in root.iterfind(path)]


def text(root: ElementTree.Element, path: str, default: str = "") -> str:
    element = root.find(path)
    return element.text if element is not None and element.text else default
