"""Loopback EC2 + Auto Scaling Query emulator (control-plane over HTTP).

Drives :class:`~tpu_task.backends.aws.api.QueryClient` through real
sockets: SigV4-signed form POSTs, the shared retry layer, namespace-
stripped XML parsing, and the AWS error-code → NotFound/AlreadyExists
mapping all run for real — the control-plane analog of the S3 loopback in
``storage/object_store_emulators.py``. Stateful: security groups, key
pairs, launch templates (with their tag specifications), auto-scaling
groups, instances and scaling activities live across calls so the REAL
``AWSRealTask`` composition can run a full create → read → delete
lifecycle against it (reference smoke shape, task_smoke_test.go:162-233).

Happy-path + idempotency semantics: duplicate creates answer the same
AWS error codes the live services use (InvalidGroup.Duplicate,
InvalidLaunchTemplateName.AlreadyExistsException, …) and missing
resources the NotFound variants, because that mapping IS the behavior
under test. Auth headers are checked for SigV4 shape, not verified
cryptographically (test_signing.py holds the vector tests).

Attach BOTH Query clients (ec2 + autoscaling) — actions dispatch by name.
"""

from __future__ import annotations

import urllib.parse
from typing import Dict, List
from xml.sax.saxutils import escape

from tpu_task.backends.loopback import LoopbackControlPlane, LoopbackHandler


def _error(code: str, message: str = "") -> bytes:
    return (f"<Response><Errors><Error><Code>{escape(code)}</Code>"
            f"<Message>{escape(message)}</Message></Error></Errors>"
            "</Response>").encode()


class _AwsHandler(LoopbackHandler):
    def do_POST(self) -> None:
        auth = self.headers.get("Authorization", "")
        self.emulator.auth_headers.append(auth)
        if not auth.startswith("AWS4-HMAC-SHA256 Credential="):
            self.reply(403, _error("AuthFailure"), "text/xml")
            return
        form = dict(urllib.parse.parse_qsl(self.read_body().decode()))
        code, body = self.emulator.handle(form)
        self.reply(code, body, "text/xml")


class LoopbackAws(LoopbackControlPlane):
    handler_class = _AwsHandler

    def __init__(self):
        super().__init__()
        self.security_groups: Dict[str, str] = {}  # name -> groupId
        self.sg_rules: List[dict] = []
        self.key_pairs: Dict[str, str] = {}        # name -> material
        self.launch_templates: Dict[str, dict] = {}  # name -> create form
        self.asgs: Dict[str, dict] = {}  # name -> {params, desired, instances}
        self.instances: Dict[str, dict] = {}  # id -> {state, ip}
        self.activities: Dict[str, list] = {}  # asg -> [activity]
        self.auth_headers: List[str] = []
        self.forms: List[dict] = []
        self._counter = 0

    def attach(self, client) -> None:
        from tpu_task.storage.object_store_emulators import loopback_transport

        client._urlopen = loopback_transport(
            f"https://{client.host}", self.port)

    def _next(self, prefix: str) -> str:
        with self._lock:
            self._counter += 1
            return f"{prefix}-{self._counter}"

    # -- dispatch --------------------------------------------------------------
    def handle(self, form: dict):
        self.forms.append(form)
        action = form.get("Action", "")
        handler = getattr(self, f"_do_{action}", None)
        if handler is None:
            return 400, _error("InvalidAction", action)
        return handler(form)

    # -- EC2: network/image data sources ---------------------------------------
    def _do_DescribeVpcs(self, form):
        return 200, (b"<r><vpcSet><item><vpcId>vpc-default</vpcId>"
                     b"<isDefault>true</isDefault></item></vpcSet></r>")

    def _do_DescribeSubnets(self, form):
        items = "".join(f"<item><subnetId>{sn}</subnetId></item>"
                        for sn in ("subnet-a", "subnet-b"))
        return 200, f"<r><subnetSet>{items}</subnetSet></r>".encode()

    def _do_DescribeImages(self, form):
        # Two candidates so the newest-CreationDate-wins rule is exercised.
        return 200, (
            b"<r><imagesSet>"
            b"<item><imageId>ami-old</imageId>"
            b"<creationDate>2020-01-01T00:00:00.000Z</creationDate></item>"
            b"<item><imageId>ami-newest</imageId>"
            b"<creationDate>2024-06-01T00:00:00.000Z</creationDate></item>"
            b"</imagesSet></r>")

    # -- EC2: security groups --------------------------------------------------
    def _do_CreateSecurityGroup(self, form):
        name = form["GroupName"]
        if name in self.security_groups:
            return 400, _error("InvalidGroup.Duplicate", name)
        group_id = self._next("sg")
        self.security_groups[name] = group_id
        return 200, f"<r><groupId>{group_id}</groupId></r>".encode()

    def _rule_change(self, form):
        if form["GroupId"] not in self.security_groups.values():
            return 400, _error("InvalidGroup.NotFound", form["GroupId"])
        self.sg_rules.append(form)
        return 200, b"<r><return>true</return></r>"

    _do_AuthorizeSecurityGroupIngress = _rule_change
    _do_AuthorizeSecurityGroupEgress = _rule_change
    _do_RevokeSecurityGroupEgress = _rule_change

    def _do_DescribeSecurityGroups(self, form):
        name = form.get("Filter.1.Value.1", "")
        group_id = self.security_groups.get(name)
        if not group_id:
            return 200, b"<r><securityGroupInfo/></r>"
        return 200, (f"<r><securityGroupInfo><item>"
                     f"<groupId>{group_id}</groupId>"
                     f"<groupName>{escape(name)}</groupName>"
                     f"</item></securityGroupInfo></r>").encode()

    def _do_DeleteSecurityGroup(self, form):
        group_id = form.get("GroupId", "")
        for name, known in list(self.security_groups.items()):
            if known == group_id:
                del self.security_groups[name]
                return 200, b"<r><return>true</return></r>"
        return 400, _error("InvalidGroup.NotFound", group_id)

    # -- EC2: key pairs --------------------------------------------------------
    def _do_ImportKeyPair(self, form):
        name = form["KeyName"]
        if name in self.key_pairs:
            return 400, _error("InvalidKeyPair.Duplicate", name)
        self.key_pairs[name] = form.get("PublicKeyMaterial", "")
        return 200, f"<r><keyName>{escape(name)}</keyName></r>".encode()

    def _do_DeleteKeyPair(self, form):
        if form["KeyName"] not in self.key_pairs:
            return 400, _error("InvalidKeyPair.NotFound", form["KeyName"])
        del self.key_pairs[form["KeyName"]]
        return 200, b"<r><return>true</return></r>"

    # -- EC2: launch templates -------------------------------------------------
    def _do_CreateLaunchTemplate(self, form):
        name = form["LaunchTemplateName"]
        if name in self.launch_templates:
            return 400, _error(
                "InvalidLaunchTemplateName.AlreadyExistsException", name)
        self.launch_templates[name] = form
        return 200, (f"<r><launchTemplate><launchTemplateName>{escape(name)}"
                     f"</launchTemplateName></launchTemplate></r>").encode()

    def _do_DescribeLaunchTemplateVersions(self, form):
        name = form.get("LaunchTemplateName", "")
        stored = self.launch_templates.get(name)
        if stored is None:
            return 400, _error(
                "InvalidLaunchTemplateName.NotFoundException", name)
        tags = []
        index = 1
        while f"LaunchTemplateData.TagSpecification.1.Tag.{index}.Key" in stored:
            key = stored[f"LaunchTemplateData.TagSpecification.1.Tag.{index}.Key"]
            value = stored[
                f"LaunchTemplateData.TagSpecification.1.Tag.{index}.Value"]
            tags.append(f"<item><key>{escape(key)}</key>"
                        f"<value>{escape(value)}</value></item>")
            index += 1
        return 200, (
            "<r><launchTemplateVersionSet><item><launchTemplateData>"
            "<tagSpecificationSet><item>"
            f"<tagSet>{''.join(tags)}</tagSet>"
            "</item></tagSpecificationSet>"
            "</launchTemplateData></item></launchTemplateVersionSet></r>"
        ).encode()

    def _do_DeleteLaunchTemplate(self, form):
        name = form.get("LaunchTemplateName", "")
        if name not in self.launch_templates:
            return 400, _error(
                "InvalidLaunchTemplateName.NotFoundException", name)
        del self.launch_templates[name]
        return 200, b"<r><return>true</return></r>"

    # -- EC2: instances --------------------------------------------------------
    def _do_DescribeInstances(self, form):
        wanted = [value for key, value in form.items()
                  if key.startswith("InstanceId.")]
        items = []
        for instance_id in wanted:
            record = self.instances.get(instance_id)
            if record is None:
                continue
            items.append(
                f"<item><instanceId>{instance_id}</instanceId>"
                f"<instanceState><name>{record['state']}</name>"
                f"</instanceState>"
                f"<ipAddress>{record['ip']}</ipAddress></item>")
        return 200, (f"<r><reservationSet><item>"
                     f"<instancesSet>{''.join(items)}</instancesSet>"
                     f"</item></reservationSet></r>").encode()

    # -- Auto Scaling ----------------------------------------------------------
    def _do_CreateAutoScalingGroup(self, form):
        name = form["AutoScalingGroupName"]
        if name in self.asgs:
            return 400, _error("AlreadyExists", name)
        self.asgs[name] = {"params": form, "desired": 0, "instances": []}
        self.activities.setdefault(name, [])
        return 200, b"<r/>"

    def _do_SetDesiredCapacity(self, form):
        name = form["AutoScalingGroupName"]
        group = self.asgs.get(name)
        if group is None:
            return 400, _error("ValidationError",
                               f"AutoScalingGroup name not found: {name}")
        desired = int(form["DesiredCapacity"])
        group["desired"] = desired
        while len(group["instances"]) < desired:  # scale out
            instance_id = self._next("i")
            self.instances[instance_id] = {
                "state": "running",
                "ip": f"54.0.0.{len(self.instances) + 10}"}
            group["instances"].append(instance_id)
            self.activities[name].append({
                "StatusCode": "Successful",
                "StartTime": "2026-07-30T00:00:00Z",
                "Cause": "scale out",
                "Description": f"Launching {instance_id}"})
        while len(group["instances"]) > desired:  # scale in
            instance_id = group["instances"].pop()
            self.instances[instance_id]["state"] = "terminated"
            self.activities[name].append({
                "StatusCode": "Successful",
                "StartTime": "2026-07-30T00:00:00Z",
                "Cause": "scale in",
                "Description": f"Terminating {instance_id}"})
        return 200, b"<r/>"

    def _do_DescribeAutoScalingGroups(self, form):
        name = form.get("AutoScalingGroupNames.member.1", "")
        group = self.asgs.get(name)
        if group is None:
            return 200, b"<r><AutoScalingGroups/></r>"
        members = "".join(
            f"<member><InstanceId>{instance_id}</InstanceId></member>"
            for instance_id in group["instances"])
        return 200, (
            f"<r><AutoScalingGroups><member>"
            f"<AutoScalingGroupName>{escape(name)}</AutoScalingGroupName>"
            f"<DesiredCapacity>{group['desired']}</DesiredCapacity>"
            f"<Instances>{members}</Instances>"
            f"</member></AutoScalingGroups></r>").encode()

    def _do_DescribeScalingActivities(self, form):
        name = form.get("AutoScalingGroupName", "")
        members = "".join(
            "<member>" + "".join(
                f"<{field}>{escape(value)}</{field}>"
                for field, value in activity.items()) + "</member>"
            for activity in self.activities.get(name, []))
        return 200, f"<r><Activities>{members}</Activities></r>".encode()

    def _do_DeleteAutoScalingGroup(self, form):
        name = form.get("AutoScalingGroupName", "")
        group = self.asgs.pop(name, None)
        if group is None:
            return 400, _error("ValidationError",
                               f"AutoScalingGroup name not found: {name}")
        for instance_id in group["instances"]:
            self.instances[instance_id]["state"] = "terminated"
        return 200, b"<r/>"
