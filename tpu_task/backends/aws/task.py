"""AWS backend: reference-parity semantics on the hermetic control plane.

Size map and region map mirror /root/reference/task/aws/resources/
resource_launch_template.go:61-73 and task/aws/client/client.go:22-27; the
instance-profile ARN validator mirrors data_source_permission_set.go:15-40.
Spot semantics (ASG MixedInstancesPolicy, resource_auto_scaling_group.go:
64-90): any spot >= 0 is accepted — >0 is the max bid, 0 means 100% spot at
on-demand cap. The real EC2/S3 control plane is not wired in this round
(the framework's north star is Cloud TPU — SURVEY.md §7 stage 7); lifecycle
semantics run end-to-end on the hermetic scaling-group plane so a future
REST client drops into a tested seam.
"""

from __future__ import annotations

import re
from typing import Dict, List

from tpu_task.backends.group_task import GroupBackedTask
from tpu_task.common.cloud import Cloud
from tpu_task.common.identifier import Identifier, WrongIdentifierError

AWS_SIZES: Dict[str, str] = {
    "s": "t2.micro",
    "m": "m5.2xlarge",
    "l": "m5.8xlarge",
    "xl": "m5.16xlarge",
    "m+t4": "g4dn.xlarge",
    "m+k80": "p2.xlarge",
    "l+k80": "p2.8xlarge",
    "xl+k80": "p2.16xlarge",
    "m+v100": "p3.xlarge",
    "l+v100": "p3.8xlarge",
    "xl+v100": "p3.16xlarge",
}

AWS_REGIONS: Dict[str, str] = {
    "us-east": "us-east-1",
    "us-west": "us-west-1",
    "eu-north": "eu-north-1",
    "eu-west": "eu-west-1",
}

_INSTANCE_TYPE_RE = re.compile(r"^[a-z0-9]+\.[a-z0-9]+$")
_ARN_RE = re.compile(r"^arn:aws[a-z-]*:iam::\d{12}:instance-profile/[\w+=,.@-]+$")


def resolve_aws_machine(machine: str) -> str:
    machine = AWS_SIZES.get(machine, machine)
    if not _INSTANCE_TYPE_RE.match(machine):
        raise ValueError(f"invalid EC2 instance type: {machine!r}")
    return machine


def resolve_aws_region(region: str) -> str:
    region = str(region)
    if region in AWS_REGIONS:
        return AWS_REGIONS[region]
    if re.match(r"^[a-z]{2}(-[a-z]+)+-\d$", region):
        return region
    raise ValueError(f"cannot resolve AWS region {region!r}")


def validate_instance_profile_arn(arn: str) -> str:
    """Instance-profile ARN check (data_source_permission_set.go:15-40)."""
    if arn and not _ARN_RE.match(arn):
        raise ValueError(f"invalid instance profile ARN: {arn!r}")
    return arn


class AWSTask(GroupBackedTask):
    provider_name = "aws"

    def validate(self) -> None:
        self.instance_type = resolve_aws_machine(self.spec.size.machine or "m")
        self.region = resolve_aws_region(str(self.cloud.region))
        validate_instance_profile_arn(self.spec.permission_set)

    def extra_environment(self) -> Dict[str, str]:
        env: Dict[str, str] = {}
        creds = self.cloud.credentials.aws
        if creds and creds.access_key_id:
            env["AWS_ACCESS_KEY_ID"] = creds.access_key_id
            env["AWS_SECRET_ACCESS_KEY"] = creds.secret_access_key
            if creds.session_token:
                env["AWS_SESSION_TOKEN"] = creds.session_token
        return env


def list_aws_tasks(cloud: Cloud) -> List[Identifier]:
    from tpu_task.backends.local.control_plane import list_groups

    identifiers = []
    for name in list_groups():
        try:
            identifiers.append(Identifier.parse(name))
        except WrongIdentifierError:
            continue
    return identifiers
